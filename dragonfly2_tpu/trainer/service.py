"""Trainer service: receives telemetry datasets, trains, registers models.

Completes the reference's unfinished ML loop (SURVEY.md §3.4): the reference
defined the Train client-stream contract (pkg/rpc/trainer/server/server.go:59,
TrainMLPRequest/TrainGNNRequest chunks) and a trainer/ skeleton with config +
metrics but no training loop, and the manager's CreateModel was a TODO stub
(manager/rpcserver/manager_server_v2.go:739-743). Here:

  train_open → train_chunk* → train_close   (the client-stream, unrolled over
  our unary RPC; chunks are npz-serialized columnar telemetry arrays)

then a background task builds the dataset (trainer.dataset), trains the MLP
bandwidth predictor (config 1) and — when probe records exist — the GraphSAGE
topology scorer (config 2/3, sharded over whatever mesh is live), writes
artifacts, and registers + activates versions in the manager's model registry.
"""

from __future__ import annotations

import asyncio
import io
import logging
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import numpy as np

from dragonfly2_tpu.trainer import artifacts, dataset as datasetlib, train_gnn, train_mlp

logger = logging.getLogger(__name__)


def pack_records(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def unpack_records(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


@dataclass
class TrainSession:
    token: str
    scheduler_hostname: str = ""
    scheduler_id: int = 0
    downloads: list[np.ndarray] = field(default_factory=list)
    probes: list[np.ndarray] = field(default_factory=list)
    opened_at: float = field(default_factory=time.time)


@dataclass
class TrainerConfig:
    model_dir: str = "/tmp/dragonfly2_tpu_models"
    mlp: train_mlp.MLPTrainConfig = field(default_factory=train_mlp.MLPTrainConfig)
    gnn: train_gnn.GNNTrainConfig = field(default_factory=train_gnn.GNNTrainConfig)
    gnn_steps: int = 300
    min_pairs: int = 16        # skip training below this much signal
    min_probe_rows: int = 8
    # Rolling dataset pool: sessions accumulate (newest kept up to the cap) so
    # schedulers on short upload cadences still reach training mass; 0 = train
    # strictly on each upload in isolation.
    pool_rows: int = 500_000


class TrainerService:
    def __init__(self, config: TrainerConfig | None = None, *, manager: Any = None):
        """manager: RemoteManagerClient (or None to skip registry)."""
        self.cfg = config or TrainerConfig()
        self.manager = manager
        self._pool_downloads: list[np.ndarray] = []
        self._pool_probes: list[np.ndarray] = []
        self._sessions: dict[str, TrainSession] = {}
        self._next = 0
        self._training: asyncio.Task | None = None
        self.last_result: dict | None = None
        self.trains_started = 0
        self.trains_succeeded = 0

    # ---- RPC surface (adapter passes payload dicts straight through) ----

    async def train_open(self, p: dict) -> dict:
        self._next += 1
        token = f"sess-{self._next}-{int(time.time())}"
        self._sessions[token] = TrainSession(
            token,
            scheduler_hostname=p.get("hostname", ""),
            scheduler_id=p.get("scheduler_id", 0),
        )
        return {"token": token}

    async def train_chunk(self, p: dict) -> dict:
        sess = self._sessions.get(p["token"])
        if sess is None:
            raise KeyError(f"unknown train session {p['token']!r}")
        arr = unpack_records(p["data"])
        if p["kind"] == "downloads":
            sess.downloads.append(arr)
        elif p["kind"] == "probes":
            sess.probes.append(arr)
        else:
            raise ValueError(f"unknown dataset kind {p['kind']!r}")
        return {"rows": int(sum(len(a) for a in sess.downloads + sess.probes))}

    async def train_close(self, p: dict) -> dict:
        sess = self._sessions.pop(p["token"], None)
        if sess is None:
            raise KeyError(f"unknown train session {p['token']!r}")
        if self._training is not None and not self._training.done():
            # one training run at a time; a second upload queues behind it
            await self._training
        self.trains_started += 1
        self._training = asyncio.ensure_future(self._train(sess))
        return {"queued": True}

    async def status(self, p: Any = None) -> dict:
        running = self._training is not None and not self._training.done()
        return {
            "training": running,
            "trains_started": self.trains_started,
            "trains_succeeded": self.trains_succeeded,
            "last_result": self.last_result,
        }

    async def wait_idle(self) -> None:
        if self._training is not None:
            await self._training

    # ---- training driver ----

    async def _train(self, sess: TrainSession) -> None:
        try:
            result = await asyncio.to_thread(self._train_sync, sess)
            self.last_result = result
            self.trains_succeeded += 1
            if self.manager is not None:
                await self._register_models(sess, result)
        except Exception:
            logger.exception("training run failed")
            self.last_result = {"error": "training failed"}

    def _pool_add(self, pool: list[np.ndarray], arrays: list[np.ndarray]) -> np.ndarray:
        pool.extend(a for a in arrays if len(a))
        total = sum(len(a) for a in pool)
        while len(pool) > 1 and total - len(pool[0]) >= self.cfg.pool_rows:
            total -= len(pool.pop(0))  # evict oldest sessions beyond the cap
        return np.concatenate(pool) if pool else np.zeros(0)

    def _train_sync(self, sess: TrainSession) -> dict:
        if self.cfg.pool_rows > 0:
            downloads = self._pool_add(self._pool_downloads, sess.downloads)
            probes = self._pool_add(self._pool_probes, sess.probes)
        else:
            downloads = np.concatenate(sess.downloads) if sess.downloads else np.zeros(0)
            probes = np.concatenate(sess.probes) if sess.probes else np.zeros(0)
        ds = datasetlib.build_dataset(downloads, probes)
        version = f"v{int(time.time())}"
        out: dict[str, Any] = {"version": version, "num_pairs": ds.num_pairs, "num_nodes": ds.num_nodes}

        if ds.num_pairs >= self.cfg.min_pairs:
            tr, ev = datasetlib.split_pairs(ds.pairs)
            t0 = time.perf_counter()
            params, evaluation = train_mlp.train(self.cfg.mlp, tr, eval_pairs=ev, log=logger.info)
            evaluation["train_seconds"] = round(time.perf_counter() - t0, 2)
            path = artifacts.save_artifact(
                Path(self.cfg.model_dir) / f"mlp-{version}",
                model_type="mlp", version=version, params=params,
                config={"hidden": list(self.cfg.mlp.hidden)},
            )
            out["mlp"] = {"artifact": str(path), "evaluation": evaluation}

        if ds.num_pairs >= self.cfg.min_pairs and len(probes) >= self.cfg.min_probe_rows:
            cfg = self.cfg.gnn
            t0 = time.perf_counter()
            state, losses = train_gnn.train(
                cfg, ds.graph, ds.pairs, steps=self.cfg.gnn_steps, log=logger.info
            )
            evaluation = {
                "final_loss": losses[-1] if losses else float("nan"),
                "steps": self.cfg.gnn_steps,
                "train_seconds": round(time.perf_counter() - t0, 2),
                "steps_per_sec": round(self.cfg.gnn_steps / max(1e-9, time.perf_counter() - t0), 2),
            }
            path = artifacts.save_artifact(
                Path(self.cfg.model_dir) / f"gnn-{version}",
                model_type="gnn", version=version, params=state.params,
                config={
                    "hidden": cfg.hidden, "embed_dim": cfg.embed_dim,
                    "num_layers": cfg.num_layers,
                },
            )
            artifacts.save_graph(path, ds.graph, ds.host_index)
            try:
                artifacts.save_native(path, train_gnn.make_model(cfg), state.params, ds.graph)
            except Exception:
                # native serving is an optimization; the flax artifact always works
                logger.exception("native scorer export failed; flax artifact only")
            out["gnn"] = {"artifact": str(path), "evaluation": evaluation}
        return out

    async def _register_models(self, sess: TrainSession, result: dict) -> None:
        """Finish the reference's CreateModel stub: version rows + activation."""
        for mtype in ("mlp", "gnn"):
            info = result.get(mtype)
            if not info:
                continue
            try:
                row = await self.manager.create_model(
                    mtype, result["version"],
                    scheduler_id=sess.scheduler_id,
                    evaluation=info["evaluation"],
                    artifact_path=info["artifact"],
                )
                await self.manager.activate_model(row["id"])
            except Exception:
                logger.exception("model registry update failed for %s", mtype)
