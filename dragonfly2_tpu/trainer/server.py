"""Trainer process entry (the service the reference left as config+metrics).

`python -m dragonfly2_tpu.trainer.server --port 9300 --manager 127.0.0.1:9200
--model-dir /var/lib/df/models`
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from dragonfly2_tpu.rpc.core import RpcServer
from dragonfly2_tpu.rpc.trainer import register_trainer
from dragonfly2_tpu.trainer.service import TrainerConfig, TrainerService
from dragonfly2_tpu.utils.proc import run_until_signalled

logger = logging.getLogger("trainer")


async def run_trainer(
    *,
    host: str = "127.0.0.1",
    port: int = 9300,
    model_dir: str = "/tmp/dragonfly2_tpu_models",
    manager_addr: str | None = None,
    gnn_steps: int = 300,
    ready_event: asyncio.Event | None = None,
) -> None:
    manager = None
    if manager_addr:
        from dragonfly2_tpu.rpc.manager import RemoteManagerClient

        manager = RemoteManagerClient(manager_addr)
    service = TrainerService(
        TrainerConfig(model_dir=model_dir, gnn_steps=gnn_steps), manager=manager
    )
    server = RpcServer(host=host, port=port)
    register_trainer(server, service)
    await server.start()
    logger.info("trainer listening on %s", server.address)
    print(f"TRAINER_READY {server.address}", flush=True)
    try:
        await run_until_signalled(ready_event)
    finally:
        await server.stop()
        if manager is not None:
            await manager.close()


def main() -> None:
    ap = argparse.ArgumentParser(description="dragonfly2_tpu trainer")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9300)
    ap.add_argument("--model-dir", default="/tmp/dragonfly2_tpu_models")
    ap.add_argument("--manager", default=None)
    ap.add_argument("--gnn-steps", type=int, default=300)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    asyncio.run(
        run_trainer(
            host=args.host, port=args.port, model_dir=args.model_dir,
            manager_addr=args.manager, gnn_steps=args.gnn_steps,
        )
    )


if __name__ == "__main__":
    main()
