"""Trainer process entry (the service the reference left as config+metrics).

`python -m dragonfly2_tpu.trainer.server --port 9300 --manager 127.0.0.1:9200
--model-dir /var/lib/df/models`
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from dragonfly2_tpu.rpc.core import RpcServer
from dragonfly2_tpu.rpc.trainer import register_trainer
from dragonfly2_tpu.trainer.service import TrainerConfig, TrainerService
from dragonfly2_tpu.utils.proc import run_until_signalled

logger = logging.getLogger("trainer")


async def run_trainer(
    *,
    host: str = "127.0.0.1",
    port: int = 9300,
    model_dir: str = "/tmp/dragonfly2_tpu_models",
    manager_addr: str | None = None,
    gnn_steps: int = 300,
    gnn_hidden: int | None = None,
    mlp_steps: int | None = None,
    min_pairs: int | None = None,
    min_probe_rows: int | None = None,
    stats_interval: float = 20.0,
    ready_event: asyncio.Event | None = None,
) -> None:
    import dataclasses

    manager = None
    if manager_addr:
        from dragonfly2_tpu.rpc.manager import RemoteManagerClient

        manager = RemoteManagerClient(manager_addr)
    cfg = TrainerConfig(model_dir=model_dir, gnn_steps=gnn_steps)
    # overrides replace ONLY the named hyperparameter — every other field
    # keeps its production default
    if gnn_hidden is not None:
        cfg.gnn = dataclasses.replace(
            cfg.gnn, hidden=gnn_hidden, embed_dim=max(16, gnn_hidden // 2),
            batch_size=min(cfg.gnn.batch_size, gnn_hidden * 4),
        )
    if mlp_steps is not None:
        cfg.mlp = dataclasses.replace(cfg.mlp, steps=mlp_steps)
    if min_pairs is not None:
        cfg.min_pairs = min_pairs
    if min_probe_rows is not None:
        cfg.min_probe_rows = min_probe_rows
    service = TrainerService(cfg, manager=manager)
    server = RpcServer(host=host, port=port)
    register_trainer(server, service)
    await server.start()
    logger.info("trainer listening on %s", server.address)
    # cluster metrics plane (ISSUE 12): the trainer is a member of the
    # cluster view too — its frame (loop lag + whatever trainer families
    # exist) rides a keepalive tick like every other service
    from dragonfly2_tpu.observability.timeseries import (
        build_stats_frame,
        default_recorder,
    )

    recorder = default_recorder()
    recorder.start()
    stats_task = None
    if manager is not None:
        import socket as _socket

        trainer_host = _socket.gethostname()

        async def stats_loop() -> None:
            while True:
                await asyncio.sleep(stats_interval)
                try:
                    frame = build_stats_frame(
                        recorder, service="trainer", hostname=trainer_host
                    )
                    await manager.keepalive("trainer", trainer_host, stats=frame)
                except Exception:
                    logger.debug("stats frame push failed", exc_info=True)

        stats_task = asyncio.ensure_future(stats_loop())
    print(f"TRAINER_READY {server.address}", flush=True)
    try:
        await run_until_signalled(ready_event)
    finally:
        recorder.stop()
        if stats_task is not None:
            stats_task.cancel()
        await server.stop()
        if manager is not None:
            await manager.close()


def main() -> None:
    ap = argparse.ArgumentParser(description="dragonfly2_tpu trainer")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9300)
    ap.add_argument("--model-dir", default="/tmp/dragonfly2_tpu_models")
    ap.add_argument("--manager", default=None)
    ap.add_argument("--gnn-steps", type=int, default=300)
    ap.add_argument("--gnn-hidden", type=int, default=None,
                    help="override GNN width (small clusters / tests)")
    ap.add_argument("--mlp-steps", type=int, default=None,
                    help="override MLP training steps")
    ap.add_argument("--min-pairs", type=int, default=None,
                    help="minimum (parent,child) rows before training")
    ap.add_argument("--min-probe-rows", type=int, default=None,
                    help="minimum probe rows before GNN training")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    asyncio.run(
        run_trainer(
            host=args.host, port=args.port, model_dir=args.model_dir,
            manager_addr=args.manager, gnn_steps=args.gnn_steps,
            gnn_hidden=args.gnn_hidden, mlp_steps=args.mlp_steps,
            min_pairs=args.min_pairs, min_probe_rows=args.min_probe_rows,
        )
    )


if __name__ == "__main__":
    main()
