"""Build training inputs from telemetry records — vectorized and incremental.

Reference context: the scheduler streams its Download and NetworkTopology CSVs
to the trainer (scheduler/announcer/announcer.go:193-259); the reference
trainer dropped them (never implemented). Here the records are columnar numpy
(telemetry.records) and convert straight into the GNN's dense padded
TopoGraph + the PairBatch pool both trainers consume — no CSV unflattening.

Host identity: record host-id strings index into a contiguous node table
(insertion-ordered). Node features are aggregated from the download records
(upload success rate per parent host); probe records supply the edge list and
RTT statistics.

Two construction paths share one vectorized core:

  build_dataset(downloads, probes)   one-shot over full record arrays
  DatasetAccumulator                 incremental — fold announcer chunks in as
                                     they arrive, finalize() in O(nodes+edges
                                     +pairs) at train_close

Both are columnar numpy end-to-end: host-id interning via np.unique over the
structured-array id columns (first-occurrence order, matching the row-walk's
insertion order), per-(src,dst) probe aggregation via bincount on packed
64-bit edge keys, neighbor tables via one lexsort on (src, rtt, arrival) with
a vectorized top-max_neighbors cut, and node features via bincount weights.
The superseded per-row walk survives as _build_dataset_rowloop — the
reference implementation the equivalence tests and the bench A/B pin the
vectorized path against (tests/test_dataset_ingest.py, bench.py
dataset_build).

Threading model: DatasetAccumulator folds run on the trainer's event loop
(sub-ms per announcer chunk); freeze() takes a cheap consistent snapshot so
finalize() can run on a worker thread while new chunks keep folding.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from dragonfly2_tpu.models.features import FEATURE_DIM, NODE_FEATURE_DIM
from dragonfly2_tpu.models.graphsage import TopoGraph
from dragonfly2_tpu.trainer.synthetic import EDGE_FEATURE_DIM, PairBatch

GIB = float(1 << 30)


@dataclass
class Dataset:
    graph: TopoGraph
    pairs: PairBatch
    host_index: dict[bytes, int]  # host_id -> node row
    # Training-reference feature sketch (ISSUE 15): the per-feature
    # histogram of the pair rows this dataset trains on, frozen HERE at
    # finalize so it describes exactly the distribution the model saw.
    # Ships digest-covered inside the artifact (trainer/artifacts.py) and
    # becomes the serving scheduler's drift baseline. None on the rowloop
    # reference path (kept byte-for-byte r05-shaped for equivalence tests).
    feature_sketch: object | None = None

    @property
    def num_nodes(self) -> int:
        return self.graph.node_feats.shape[0]

    @property
    def num_pairs(self) -> int:
        return len(self.pairs.child)


class _HostTable:
    def __init__(self) -> None:
        self.index: dict[bytes, int] = {}

    def get(self, host_id: bytes) -> int:
        idx = self.index.get(host_id)
        if idx is None:
            idx = self.index[host_id] = len(self.index)
        return idx


def _sorted_unique(ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(sorted unique values, per-element inverse) — np.sort + one searchsorted,
    ~2.5x cheaper than np.unique(return_index/return_inverse) on S-dtype ids
    (measured: the stable argsort unique uses dominates build time)."""
    s = np.sort(ids)
    uniq = s[np.r_[True, s[1:] != s[:-1]]]
    return uniq, np.searchsorted(uniq, ids)


def _first_occurrence_rank(inv: np.ndarray, n_uniq: int) -> np.ndarray:
    """rank[u] = arrival order of unique u within the element sequence —
    rank 0 for whichever unique appears first, matching a row-walk's
    insertion order without the stable-argsort unique."""
    first = np.full(n_uniq, len(inv), np.int64)
    np.minimum.at(first, inv, np.arange(len(inv), dtype=np.int64))
    rank = np.empty(n_uniq, np.int64)
    rank[np.argsort(first, kind="stable")] = np.arange(n_uniq)
    return rank


class _Interner:
    """Vectorized insertion-ordered id interning.

    codes() assigns contiguous indices by order of FIRST OCCURRENCE across
    all calls — identical to walking the rows one by one through _HostTable.
    A sorted (ids, codes) cache resolves already-known ids with one binary
    search, so steady-state incremental folds never re-sort the id universe;
    only ids new to a batch touch the dict.
    """

    __slots__ = ("index", "_sorted_ids", "_sorted_codes")

    def __init__(self) -> None:
        self.index: dict[bytes, int] = {}
        self._sorted_ids: np.ndarray | None = None
        self._sorted_codes: np.ndarray | None = None

    def __len__(self) -> int:
        return len(self.index)

    def _probe(self, ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(known mask, codes — valid where known) against the sorted cache."""
        table, codes = self._sorted_ids, self._sorted_codes
        if table is None or not len(table):
            return np.zeros(len(ids), bool), np.zeros(len(ids), np.int64)
        pos = np.minimum(np.searchsorted(table, ids), len(table) - 1)
        return table[pos] == ids, codes[pos]

    def _admit(self, new_ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Intern unseen ids (given in arrival order, duplicates allowed);
        returns (their sorted uniques, per-element codes)."""
        uniq, inv = _sorted_unique(new_ids)
        rank = _first_occurrence_rank(inv, len(uniq))
        base = len(self.index)
        lut = base + rank
        index = self.index
        order = np.empty(len(uniq), np.int64)
        order[rank] = np.arange(len(uniq))
        for key in uniq[order].tolist():
            index[key] = len(index)
        if self._sorted_ids is None or not len(self._sorted_ids):
            self._sorted_ids, self._sorted_codes = uniq, lut
        else:
            merged = np.concatenate([self._sorted_ids, uniq])
            mcodes = np.concatenate([self._sorted_codes, lut])
            o = np.argsort(merged, kind="stable")
            self._sorted_ids, self._sorted_codes = merged[o], mcodes[o]
        return uniq, lut[inv]

    # Unknown ids are admitted a segment at a time: sorting S-ids is the
    # dominant cost, and after one segment most later "unknowns" are really
    # repeats — a binary-search probe against the refreshed cache is ~3x
    # cheaper than sorting them (one-shot 100k-row builds hit the same
    # amortization the chunked fold path gets for free).
    _ADMIT_SEGMENT = 32768

    def codes(self, ids: np.ndarray) -> np.ndarray:
        """Get-or-add: int64 code per element, first-occurrence ordered."""
        if len(ids) == 0:
            return np.zeros(0, np.int64)
        known, out = self._probe(ids)
        pending = np.flatnonzero(~known)
        while len(pending):
            seg, pending = pending[: self._ADMIT_SEGMENT], pending[self._ADMIT_SEGMENT :]
            _, out[seg] = self._admit(ids[seg])
            if len(pending):
                k2, o2 = self._probe(ids[pending])
                out[pending[k2]] = o2[k2]
                pending = pending[~k2]
        return out


class _Grow:
    """Amortized-doubling growable array (rows on axis 0)."""

    __slots__ = ("a", "n")

    def __init__(self, dtype, cols: int | None = None):
        shape = (0,) if cols is None else (0, cols)
        self.a = np.zeros(shape, dtype)
        self.n = 0

    def ensure(self, rows: int) -> None:
        """Grow (zero-filled) so that `rows` total rows are addressable."""
        if rows > len(self.a):
            cap = max(rows, 2 * len(self.a), 256)
            grown = np.zeros((cap,) + self.a.shape[1:], self.a.dtype)
            grown[: self.n] = self.a[: self.n]
            self.a = grown
        self.n = max(self.n, rows)

    def view(self) -> np.ndarray:
        return self.a[: self.n]


def _interleave(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """[a0, b0, a1, b1, ...] — the id sequence a per-row (a, b) walk interns."""
    out = np.empty(2 * len(a), dtype=a.dtype)
    out[0::2] = a
    out[1::2] = b
    return out


class FrozenIngest:
    """Immutable snapshot of accumulator state; finalize() is pure and safe
    to run on a worker thread while the live accumulator keeps folding."""

    def __init__(
        self,
        host_index: dict[bytes, int],
        edge_src: np.ndarray,
        edge_dst: np.ndarray,
        edge_sum: np.ndarray,
        edge_cnt: np.ndarray,
        stat_ids: list[bytes],
        stat_tot: np.ndarray,
        stat_succ: np.ndarray,
        stat_bw: np.ndarray,
        pair_chunks: tuple[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray], ...],
    ):
        self.host_index = host_index
        self._edge_src = edge_src
        self._edge_dst = edge_dst
        self._edge_sum = edge_sum
        self._edge_cnt = edge_cnt
        self._stat_ids = stat_ids
        self._stat_tot = stat_tot
        self._stat_succ = stat_succ
        self._stat_bw = stat_bw
        self._pair_chunks = pair_chunks

    def finalize(self, *, max_neighbors: int = 16, min_nodes: int = 8) -> Dataset:
        n = max(len(self.host_index), min_nodes)
        k = max_neighbors
        neighbors = np.zeros((n, k), np.int32)
        mask = np.zeros((n, k), np.float32)
        edge_feats = np.zeros((n, k, EDGE_FEATURE_DIM), np.float32)

        m = len(self._edge_src)
        if m:
            agg = self._edge_sum / self._edge_cnt[:, None]
            src = self._edge_src
            # stable (src, rtt_mean, arrival) order == the row-walk's
            # per-source stable sort by RTT with insertion-order tie-break;
            # keep the lowest-RTT max_neighbors per source (they matter most)
            order = np.lexsort((np.arange(m), agg[:, 0], src))
            s_sorted = src[order]
            starts = np.flatnonzero(np.r_[True, s_sorted[1:] != s_sorted[:-1]])
            seg_len = np.diff(np.r_[starts, m])
            pos_in_src = np.arange(m) - np.repeat(starts, seg_len)
            keep = pos_in_src < k
            sel = order[keep]
            rows = s_sorted[keep]
            cols = pos_in_src[keep]
            a = agg[sel]
            neighbors[rows, cols] = self._edge_dst[sel].astype(np.int32)
            mask[rows, cols] = 1.0
            edge_feats[rows, cols, 0] = a[:, 0] / 100.0  # ms -> per-100ms
            edge_feats[rows, cols, 1] = a[:, 1] / 100.0
            edge_feats[rows, cols, 2] = a[:, 2] / 100.0
            edge_feats[rows, cols, 3] = np.minimum(1.0, a[:, 3] / 30.0)

        # --- node features aggregated from download history ---
        node_feats = np.zeros((n, NODE_FEATURE_DIM), np.float32)
        if self._stat_ids:
            index = self.host_index
            main = np.fromiter(
                (index.get(h, -1) for h in self._stat_ids),
                np.int64,
                count=len(self._stat_ids),
            )
            present = main >= 0  # parents only ever seen in failed rows w/o probes drop out
            rows = main[present]
            total_cnt = np.zeros(n)
            success_cnt = np.zeros(n)
            bw_sum = np.zeros(n)
            total_cnt[rows] = self._stat_tot[present]
            success_cnt[rows] = self._stat_succ[present]
            bw_sum[rows] = self._stat_bw[present]
            served = total_cnt > 0
            node_feats[served, 1] = success_cnt[served] / total_cnt[served]
            node_feats[served, 5] = bw_sum[served] / total_cnt[served]
        # pair features carry the rest of the observable signal; idc/location
        # hash slots stay zero until host announces flow into telemetry

        if self._pair_chunks:
            cols4 = list(zip(*self._pair_chunks))
            pairs = PairBatch(
                np.concatenate(cols4[0]),
                np.concatenate(cols4[1]),
                np.concatenate(cols4[2]),
                np.concatenate(cols4[3]),
            )
        else:
            pairs = PairBatch(
                np.asarray([0], np.int32),
                np.asarray([0], np.int32),
                np.zeros((1, FEATURE_DIM), np.float32),
                np.asarray([0.0], np.float32),
            )
        graph = TopoGraph(node_feats, neighbors, mask, edge_feats)
        # freeze the training-reference sketch from the pair rows the model
        # will actually fit (ISSUE 15); one vectorized pass, O(pairs x F)
        from dragonfly2_tpu.models.features import FEATURE_NAMES
        from dragonfly2_tpu.observability.sketches import FeatureSketch

        sketch = FeatureSketch(FEATURE_DIM, names=FEATURE_NAMES)
        sketch.update(pairs.feats)
        return Dataset(
            graph=graph, pairs=pairs, host_index=dict(self.host_index),
            feature_sketch=sketch,
        )


class DatasetAccumulator:
    """Incremental telemetry→dataset ingest.

    Fold each announcer chunk in as it arrives (add_downloads/add_probes);
    finalize() materializes the Dataset from the aggregated state in
    O(nodes + edges + retained pairs) — no re-walk of raw telemetry rows, no
    retained raw record arrays. State kept:

      - host table        id -> node row, first-occurrence ordered
      - pair pool         columnar (child, parent, feats, label) chunks; when
                          max_pair_rows > 0, oldest whole chunks are evicted
                          once the newer ones alone reach the cap (the rolling
                          pool the per-upload row arrays used to provide, at
                          ~76 B/pair instead of ~376 B/raw row)
      - edge stats        per-(src,dst) float64 stat sums + probe-row counts,
                          keyed by packed 64-bit (src<<32|dst)
      - node counters     per-parent-id totals/successes/bandwidth sums, in a
                          side table so a parent first seen in a failed row
                          still counts once (and only once) it enters the host
                          table via a later ok-row or probe — matching the
                          one-shot walk, which counts after full interning

    Fold order defines node numbering: per upload the announcer streams all
    download chunks then all probe chunks, which reproduces build_dataset's
    interning order exactly (the chunked≡one-shot equivalence tests pin this).
    """

    def __init__(self, *, max_pair_rows: int = 0):
        self.hosts = _Interner()
        self.max_pair_rows = max_pair_rows
        self._pair_chunks: list[tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]] = []
        self.pair_rows = 0
        self._edge_pos: dict[int, int] = {}
        self._edge_src = _Grow(np.int64)
        self._edge_dst = _Grow(np.int64)
        self._edge_sum = _Grow(np.float64, cols=EDGE_FEATURE_DIM)
        self._edge_cnt = _Grow(np.int64)
        self._stats = _Interner()
        self._stat_tot = _Grow(np.float64)
        self._stat_succ = _Grow(np.float64)
        self._stat_bw = _Grow(np.float64)
        self.download_rows = 0
        self.probe_rows = 0

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    @property
    def num_edges(self) -> int:
        return self._edge_src.n

    def add_downloads(self, arr: np.ndarray) -> int:
        """Fold one DOWNLOAD_DTYPE chunk; returns rows folded."""
        rows = len(arr)
        if rows == 0:
            return 0
        self.download_rows += rows

        # field-wise extraction: indexing a single column copies only that
        # column; fancy-indexing the structured array would copy every field
        success = arr["success"]
        parent = arr["parent_host_id"]
        has_parent = parent != b""
        ok = success & has_parent  # back-to-source trains nothing pairwise
        if ok.any():
            ids = _interleave(arr["child_host_id"][ok], parent[ok])
            codes = self.hosts.codes(ids)
            labels = np.minimum(
                1.0, arr["bandwidth_bps"][ok].astype(np.float64) / GIB
            ).astype(np.float32)
            self._pair_chunks.append(
                (
                    codes[0::2].astype(np.int32),
                    codes[1::2].astype(np.int32),
                    arr["pair_features"][ok].astype(np.float32),
                    labels,
                )
            )
            self.pair_rows += int(ok.sum())
            self._evict_pairs()

        # --- per-parent upload counters (all rows, success or not) ---
        if has_parent.any():
            codes = self._stats.codes(parent[has_parent])
            nstat = len(self._stats)
            for g in (self._stat_tot, self._stat_succ, self._stat_bw):
                g.ensure(nstat)
            self._stat_tot.view()[:] += np.bincount(codes, minlength=nstat)
            su = success[has_parent]
            if su.any():
                okc = codes[su]
                self._stat_succ.view()[:] += np.bincount(okc, minlength=nstat)
                bw = np.minimum(
                    1.0, arr["bandwidth_bps"][has_parent][su].astype(np.float64) / GIB
                )
                self._stat_bw.view()[:] += np.bincount(
                    okc, weights=bw, minlength=nstat
                )
        return rows

    def _edge_rows(self, keys: np.ndarray) -> np.ndarray:
        """Get-or-add edge-table rows for packed (src<<32|dst) keys given in
        arrival order (duplicates allowed); new edges are appended in
        first-occurrence order. Returns the edge row per key position."""
        uniq, inv = _sorted_unique(keys)
        rank = _first_occurrence_rank(inv, len(uniq))
        order = np.empty(len(uniq), np.int64)
        order[rank] = np.arange(len(uniq))
        edge_pos = self._edge_pos
        base = self._edge_src.n
        rows_for = np.empty(len(uniq), np.int64)
        new_keys: list[int] = []
        for pos, key in zip(order.tolist(), uniq[order].tolist()):
            r = edge_pos.get(key)
            if r is None:
                r = edge_pos[key] = base + len(new_keys)
                new_keys.append(key)
            rows_for[pos] = r
        if new_keys:
            nk = np.asarray(new_keys, np.int64)
            total = base + len(new_keys)
            for g in (self._edge_src, self._edge_dst, self._edge_sum, self._edge_cnt):
                g.ensure(total)
            self._edge_src.view()[base:] = nk >> 32
            self._edge_dst.view()[base:] = nk & 0xFFFFFFFF
        return rows_for[inv]

    def add_probes(self, arr: np.ndarray) -> int:
        """Fold one PROBE_DTYPE chunk; returns rows folded."""
        rows = len(arr)
        if rows == 0:
            return 0
        self.probe_rows += rows

        ids = _interleave(arr["src_host_id"], arr["dst_host_id"])
        codes = self.hosts.codes(ids)
        s, d = codes[0::2], codes[1::2]
        erows = self._edge_rows((s << 32) | d)
        uniq_rows, inv = _sorted_unique(erows)

        stats = np.empty((rows, EDGE_FEATURE_DIM), np.float64)
        stats[:, 0] = arr["rtt_mean_ms"]
        stats[:, 1] = arr["rtt_std_ms"]
        stats[:, 2] = arr["rtt_min_ms"]
        stats[:, 3] = arr["probe_count"]
        esum = self._edge_sum.view()
        for c in range(EDGE_FEATURE_DIM):
            esum[uniq_rows, c] += np.bincount(
                inv, weights=stats[:, c], minlength=len(uniq_rows)
            )
        self._edge_cnt.view()[uniq_rows] += np.bincount(inv, minlength=len(uniq_rows))
        return rows

    def merge_from(self, other: "DatasetAccumulator") -> None:
        """Fold another accumulator's aggregated state in — O(other's
        nodes + edges + pair chunks), never touching raw rows. The service
        commits a session's accumulator into the shared pool at train_close
        this way: a session that dies mid-upload (RPC failure, TTL eviction)
        contributes NOTHING, so an announcer retry of the same snapshot can
        never double-count. Host/edge arrival order follows other's internal
        first-occurrence order, exactly as if its rows had been folded here
        directly."""
        if other.download_rows == 0 and other.probe_rows == 0:
            return
        # hosts: other's code i sits at position i of its insertion-ordered
        # key list; get-or-add yields the remap other-code -> self-code
        remap = np.zeros(0, np.int64)
        if len(other.hosts):
            ids = np.array(list(other.hosts.index), dtype="S64")
            remap = self.hosts.codes(ids)

        for child, parent, feats, labels in other._pair_chunks:
            self._pair_chunks.append(
                (
                    remap[child].astype(np.int32),
                    remap[parent].astype(np.int32),
                    feats,
                    labels,
                )
            )
            self.pair_rows += len(child)
        self._evict_pairs()

        m = other._edge_src.n
        if m:
            s = remap[other._edge_src.view()]
            d = remap[other._edge_dst.view()]
            erows = self._edge_rows((s << 32) | d)  # other's edges are unique keys
            self._edge_sum.view()[erows] += other._edge_sum.view()
            self._edge_cnt.view()[erows] += other._edge_cnt.view()

        if len(other._stats):
            sids = np.array(list(other._stats.index), dtype="S64")
            scodes = self._stats.codes(sids)
            nstat = len(self._stats)
            for g in (self._stat_tot, self._stat_succ, self._stat_bw):
                g.ensure(nstat)
            self._stat_tot.view()[scodes] += other._stat_tot.view()
            self._stat_succ.view()[scodes] += other._stat_succ.view()
            self._stat_bw.view()[scodes] += other._stat_bw.view()

        self.download_rows += other.download_rows
        self.probe_rows += other.probe_rows

    def _evict_pairs(self) -> None:
        """Rolling-pool semantics of the old per-session row arrays: evict
        oldest whole chunks while the remainder alone still covers the cap."""
        cap = self.max_pair_rows
        if cap <= 0:
            return
        chunks = self._pair_chunks
        while len(chunks) > 1 and self.pair_rows - len(chunks[0][0]) >= cap:
            self.pair_rows -= len(chunks.pop(0)[0])

    def freeze(self) -> FrozenIngest:
        """Cheap consistent snapshot (copies only the aggregate arrays; pair
        chunks are append-only so a shallow tuple copy suffices)."""
        return FrozenIngest(
            host_index=dict(self.hosts.index),
            edge_src=self._edge_src.view().copy(),
            edge_dst=self._edge_dst.view().copy(),
            edge_sum=self._edge_sum.view().copy(),
            edge_cnt=self._edge_cnt.view().copy(),
            stat_ids=list(self._stats.index),
            stat_tot=self._stat_tot.view().copy(),
            stat_succ=self._stat_succ.view().copy(),
            stat_bw=self._stat_bw.view().copy(),
            pair_chunks=tuple(self._pair_chunks),
        )

    def finalize(self, *, max_neighbors: int = 16, min_nodes: int = 8) -> Dataset:
        """Materialize the Dataset from aggregated state (non-destructive —
        keep folding and finalize again later)."""
        return self.freeze().finalize(max_neighbors=max_neighbors, min_nodes=min_nodes)


def build_dataset(
    downloads: np.ndarray,
    probes: np.ndarray,
    *,
    max_neighbors: int = 16,
    min_nodes: int = 8,
) -> Dataset:
    """downloads: DOWNLOAD_DTYPE rows; probes: PROBE_DTYPE rows.

    One-shot wrapper over the vectorized accumulator; equivalent to the
    per-row reference walk (_build_dataset_rowloop) up to float32-vs-float64
    accumulation order in the edge statistics.
    """
    acc = DatasetAccumulator()
    if len(downloads):  # 0-row placeholders may be plain (non-structured) zeros
        acc.add_downloads(downloads)
    if len(probes):
        acc.add_probes(probes)
    return acc.finalize(max_neighbors=max_neighbors, min_nodes=min_nodes)


def _build_dataset_rowloop(
    downloads: np.ndarray,
    probes: np.ndarray,
    *,
    max_neighbors: int = 16,
    min_nodes: int = 8,
) -> Dataset:
    """Reference implementation: the superseded per-row Python walk.

    Kept verbatim for the equivalence suite (tests/test_dataset_ingest.py)
    and the bench A/B (bench.py dataset_build) — every behavior of
    build_dataset is defined as "what this does", so changes must land here
    AND in the vectorized path together.
    """
    hosts = _HostTable()

    # --- pairs from download records (child <- parent transfers) ---
    child_idx, parent_idx, feats, labels = [], [], [], []
    ok = downloads[downloads["success"]] if len(downloads) else downloads
    for row in ok:
        if not row["parent_host_id"]:
            continue  # back-to-source rows train nothing pairwise
        c = hosts.get(bytes(row["child_host_id"]))
        p = hosts.get(bytes(row["parent_host_id"]))
        child_idx.append(c)
        parent_idx.append(p)
        feats.append(np.asarray(row["pair_features"], np.float32))  # dflint: disable=DF033 rowloop reference for the vectorized path
        labels.append(min(1.0, float(row["bandwidth_bps"]) / GIB))

    # --- edges from probe records, aggregated per (src, dst) ---
    edge_stats: dict[tuple[int, int], list[np.ndarray]] = {}
    for row in probes:
        s = hosts.get(bytes(row["src_host_id"]))
        d = hosts.get(bytes(row["dst_host_id"]))
        edge_stats.setdefault((s, d), []).append(
            np.array(  # dflint: disable=DF033 rowloop reference for the vectorized path
                [row["rtt_mean_ms"], row["rtt_std_ms"], row["rtt_min_ms"], row["probe_count"]],
                np.float32,
            )
        )

    n = max(len(hosts.index), min_nodes)
    neighbors = np.zeros((n, max_neighbors), np.int32)
    mask = np.zeros((n, max_neighbors), np.float32)
    edge_feats = np.zeros((n, max_neighbors, EDGE_FEATURE_DIM), np.float32)
    per_src: dict[int, list[tuple[int, np.ndarray]]] = {}
    for (s, d), stats in edge_stats.items():
        agg = np.mean(np.stack(stats), axis=0)  # dflint: disable=DF033 rowloop reference for the vectorized path
        per_src.setdefault(s, []).append((d, agg))
    for s, dests in per_src.items():
        # keep the lowest-RTT neighbors when over-degree (they matter most)
        dests.sort(key=lambda t: t[1][0])
        for k, (d, agg) in enumerate(dests[:max_neighbors]):
            neighbors[s, k] = d
            mask[s, k] = 1.0
            edge_feats[s, k, 0] = agg[0] / 100.0  # ms -> per-100ms
            edge_feats[s, k, 1] = agg[1] / 100.0
            edge_feats[s, k, 2] = agg[2] / 100.0
            edge_feats[s, k, 3] = min(1.0, agg[3] / 30.0)

    # --- node features aggregated from download history ---
    node_feats = np.zeros((n, NODE_FEATURE_DIM), np.float32)
    success_cnt = np.zeros(n)
    total_cnt = np.zeros(n)
    bw_sum = np.zeros(n)
    for row in downloads:
        if not row["parent_host_id"]:
            continue
        p = hosts.index.get(bytes(row["parent_host_id"]))
        if p is None:
            continue
        total_cnt[p] += 1
        if row["success"]:
            success_cnt[p] += 1
            bw_sum[p] += min(1.0, float(row["bandwidth_bps"]) / GIB)
    served = total_cnt > 0
    node_feats[served, 1] = success_cnt[served] / total_cnt[served]  # upload_success_rate
    node_feats[served, 5] = bw_sum[served] / total_cnt[served]  # network_tx_norm proxy
    # pair features carry the rest of the observable signal; idc/location hash
    # slots stay zero until host announces flow into telemetry (future work)

    pairs = PairBatch(
        np.asarray(child_idx or [0], np.int32),
        np.asarray(parent_idx or [0], np.int32),
        (np.stack(feats) if feats else np.zeros((1, FEATURE_DIM), np.float32)),
        np.asarray(labels or [0.0], np.float32),
    )
    graph = TopoGraph(node_feats, neighbors, mask, edge_feats)
    return Dataset(graph=graph, pairs=pairs, host_index=dict(hosts.index))


def split_pairs(pairs: PairBatch, holdout: float = 0.1, seed: int = 0) -> tuple[PairBatch, PairBatch]:
    """Random train/eval split of the pair pool."""
    n = len(pairs.child)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_eval = max(1, int(n * holdout)) if n > 1 else 0
    ev, tr = perm[:n_eval], perm[n_eval:]
    take = lambda idx: PairBatch(*(np.asarray(a)[idx] for a in pairs))
    return take(tr if len(tr) else perm), take(ev if len(ev) else perm)
