"""Build training inputs from telemetry records.

Reference context: the scheduler streams its Download and NetworkTopology CSVs
to the trainer (scheduler/announcer/announcer.go:193-259); the reference
trainer dropped them (never implemented). Here the records are columnar numpy
(telemetry.records) and convert straight into the GNN's dense padded
TopoGraph + the PairBatch pool both trainers consume — no CSV unflattening.

Host identity: record host-id strings index into a contiguous node table
(insertion-ordered). Node features are aggregated from the download records
(upload success rate per parent host); probe records supply the edge list and
RTT statistics.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from dragonfly2_tpu.models.features import FEATURE_DIM, NODE_FEATURE_DIM
from dragonfly2_tpu.models.graphsage import TopoGraph
from dragonfly2_tpu.trainer.synthetic import EDGE_FEATURE_DIM, PairBatch

GIB = float(1 << 30)


@dataclass
class Dataset:
    graph: TopoGraph
    pairs: PairBatch
    host_index: dict[bytes, int]  # host_id -> node row

    @property
    def num_nodes(self) -> int:
        return self.graph.node_feats.shape[0]

    @property
    def num_pairs(self) -> int:
        return len(self.pairs.child)


class _HostTable:
    def __init__(self) -> None:
        self.index: dict[bytes, int] = {}

    def get(self, host_id: bytes) -> int:
        idx = self.index.get(host_id)
        if idx is None:
            idx = self.index[host_id] = len(self.index)
        return idx


def build_dataset(
    downloads: np.ndarray,
    probes: np.ndarray,
    *,
    max_neighbors: int = 16,
    min_nodes: int = 8,
) -> Dataset:
    """downloads: DOWNLOAD_DTYPE rows; probes: PROBE_DTYPE rows."""
    hosts = _HostTable()

    # --- pairs from download records (child <- parent transfers) ---
    child_idx, parent_idx, feats, labels = [], [], [], []
    ok = downloads[downloads["success"]] if len(downloads) else downloads
    for row in ok:
        if not row["parent_host_id"]:
            continue  # back-to-source rows train nothing pairwise
        c = hosts.get(bytes(row["child_host_id"]))
        p = hosts.get(bytes(row["parent_host_id"]))
        child_idx.append(c)
        parent_idx.append(p)
        feats.append(np.asarray(row["pair_features"], np.float32))
        labels.append(min(1.0, float(row["bandwidth_bps"]) / GIB))

    # --- edges from probe records, aggregated per (src, dst) ---
    edge_stats: dict[tuple[int, int], list[np.ndarray]] = {}
    for row in probes:
        s = hosts.get(bytes(row["src_host_id"]))
        d = hosts.get(bytes(row["dst_host_id"]))
        edge_stats.setdefault((s, d), []).append(
            np.array(
                [row["rtt_mean_ms"], row["rtt_std_ms"], row["rtt_min_ms"], row["probe_count"]],
                np.float32,
            )
        )

    n = max(len(hosts.index), min_nodes)
    neighbors = np.zeros((n, max_neighbors), np.int32)
    mask = np.zeros((n, max_neighbors), np.float32)
    edge_feats = np.zeros((n, max_neighbors, EDGE_FEATURE_DIM), np.float32)
    per_src: dict[int, list[tuple[int, np.ndarray]]] = {}
    for (s, d), stats in edge_stats.items():
        agg = np.mean(np.stack(stats), axis=0)  # mean over probe snapshots
        per_src.setdefault(s, []).append((d, agg))
    for s, dests in per_src.items():
        # keep the lowest-RTT neighbors when over-degree (they matter most)
        dests.sort(key=lambda t: t[1][0])
        for k, (d, agg) in enumerate(dests[:max_neighbors]):
            neighbors[s, k] = d
            mask[s, k] = 1.0
            edge_feats[s, k, 0] = agg[0] / 100.0  # ms -> per-100ms
            edge_feats[s, k, 1] = agg[1] / 100.0
            edge_feats[s, k, 2] = agg[2] / 100.0
            edge_feats[s, k, 3] = min(1.0, agg[3] / 30.0)

    # --- node features aggregated from download history ---
    node_feats = np.zeros((n, NODE_FEATURE_DIM), np.float32)
    success_cnt = np.zeros(n)
    total_cnt = np.zeros(n)
    bw_sum = np.zeros(n)
    for row in downloads:
        if not row["parent_host_id"]:
            continue
        p = hosts.index.get(bytes(row["parent_host_id"]))
        if p is None:
            continue
        total_cnt[p] += 1
        if row["success"]:
            success_cnt[p] += 1
            bw_sum[p] += min(1.0, float(row["bandwidth_bps"]) / GIB)
    served = total_cnt > 0
    node_feats[served, 1] = success_cnt[served] / total_cnt[served]  # upload_success_rate
    node_feats[served, 5] = bw_sum[served] / total_cnt[served]  # network_tx_norm proxy
    # pair features carry the rest of the observable signal; idc/location hash
    # slots stay zero until host announces flow into telemetry (future work)

    pairs = PairBatch(
        np.asarray(child_idx or [0], np.int32),
        np.asarray(parent_idx or [0], np.int32),
        (np.stack(feats) if feats else np.zeros((1, FEATURE_DIM), np.float32)),
        np.asarray(labels or [0.0], np.float32),
    )
    graph = TopoGraph(node_feats, neighbors, mask, edge_feats)
    return Dataset(graph=graph, pairs=pairs, host_index=dict(hosts.index))


def split_pairs(pairs: PairBatch, holdout: float = 0.1, seed: int = 0) -> tuple[PairBatch, PairBatch]:
    """Random train/eval split of the pair pool."""
    n = len(pairs.child)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    n_eval = max(1, int(n * holdout)) if n > 1 else 0
    ev, tr = perm[:n_eval], perm[n_eval:]
    take = lambda idx: PairBatch(*(np.asarray(a)[idx] for a in pairs))
    return take(tr if len(tr) else perm), take(ev if len(ev) else perm)
