"""Model artifact (de)serialization for the registry.

One artifact = one directory: params.msgpack (flax serialized pytree) +
config.json (model hyperparameters + type + version) + sketch.json (the
training-reference feature sketch drift detection compares live scoring
features against, ISSUE 15) + the GNN's graph.npz/hosts.json/scorer.dfsc.
The manager's model registry rows point at these via artifact_path
(manager/models/model.go:28-45 kept evaluation metrics in the DB and the
artifact elsewhere; same split). The scheduler's ml evaluator loads an
artifact straight into a scorer; `artifact_digest` covers EVERY file, so
any of them tampering fails verify_artifact before attach.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import flax.serialization
import jax
import jax.numpy as jnp
import numpy as np

from dragonfly2_tpu.models.graphsage import TopoScorer
from dragonfly2_tpu.models.mlp import BandwidthMLP


# Bumped whenever the flax param-tree structure changes (renamed/reshaped
# modules make from_bytes fail); loaders refuse mismatched artifacts with a
# clear error instead of a pytree exception deep in deserialization.
# 2: SAGELayer pre-projection decomposition (msg_nbr/msg_self/msg_edge).
ARTIFACT_FORMAT = 2


class IncompatibleArtifact(Exception):
    pass


class ArtifactIntegrityError(IOError):
    """The on-disk artifact does not match the registry row's digest —
    truncated/corrupt/partially-written files must never attach to a live
    evaluator (ISSUE 11)."""


def artifact_digest(directory: str | Path) -> str:
    """Content digest of a whole artifact directory: sha256 over every
    regular file (sorted relative path + contents, length-framed so file
    boundaries can't alias). Computed by the trainer at publish time and
    stored on the registry row; the scheduler recomputes it before attaching
    a version. Injectable: each file's bytes pass the faultline
    `model.load` mutate point, so chaos tests corrupt artifacts the same
    seeded way they corrupt pieces."""
    import hashlib

    from dragonfly2_tpu.resilience import faultline

    d = Path(directory)
    h = hashlib.sha256()
    for f in sorted(p for p in d.rglob("*") if p.is_file()):
        data = f.read_bytes()
        if faultline.ACTIVE is not None:
            data = faultline.ACTIVE.mutate("model.load", data)
        rel = f.relative_to(d).as_posix().encode()
        h.update(len(rel).to_bytes(4, "big"))
        h.update(rel)
        h.update(len(data).to_bytes(8, "big"))
        h.update(data)
    return h.hexdigest()


def verify_artifact(directory: str | Path, expected_digest: str) -> None:
    """Raise ArtifactIntegrityError unless the directory's recomputed digest
    matches the registry row's. Empty expected digest = unverified row
    (pre-rollout registry) — allowed through, the caller decides policy."""
    if not expected_digest:
        return
    d = Path(directory)
    if not d.is_dir():
        raise FileNotFoundError(f"artifact directory {d} missing")
    got = artifact_digest(d)
    if got != expected_digest:
        raise ArtifactIntegrityError(
            f"artifact {d} digest mismatch: registry {expected_digest[:16]}…, "
            f"disk {got[:16]}… (truncated/corrupt artifact must not attach)"
        )


def save_artifact(
    directory: str | Path, *, model_type: str, version: str, params: Any, config: dict
) -> Path:
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    (d / "params.msgpack").write_bytes(flax.serialization.to_bytes(params))
    (d / "config.json").write_text(
        json.dumps({"type": model_type, "version": version, "format": ARTIFACT_FORMAT, **config})
    )
    return d


def _check_format(cfg: dict, directory: Any) -> None:
    fmt = cfg.get("format", 1)
    if fmt != ARTIFACT_FORMAT:
        raise IncompatibleArtifact(
            f"artifact {directory} has format {fmt}, this build expects "
            f"{ARTIFACT_FORMAT}; retrain to republish"
        )


def load_config(directory: str | Path) -> dict:
    return json.loads((Path(directory) / "config.json").read_text())


def load_gnn(directory: str | Path) -> tuple[TopoScorer, Any]:
    cfg = load_config(directory)
    assert cfg["type"] == "gnn", cfg
    _check_format(cfg, directory)
    model = TopoScorer(
        hidden=cfg["hidden"], embed_dim=cfg["embed_dim"], num_layers=cfg["num_layers"]
    )
    from dragonfly2_tpu.models.features import FEATURE_DIM, NODE_FEATURE_DIM
    from dragonfly2_tpu.models.graphsage import TopoGraph
    from dragonfly2_tpu.trainer.synthetic import EDGE_FEATURE_DIM

    # template pytree with the right structure for from_bytes
    g = TopoGraph(
        jnp.zeros((8, NODE_FEATURE_DIM)), jnp.zeros((8, 4), jnp.int32),
        jnp.zeros((8, 4)), jnp.zeros((8, 4, EDGE_FEATURE_DIM)),
    )
    template = model.init(
        jax.random.PRNGKey(0), g, jnp.zeros((2,), jnp.int32),
        jnp.zeros((2,), jnp.int32), jnp.zeros((2, FEATURE_DIM)),
    )
    params = flax.serialization.from_bytes(
        template, (Path(directory) / "params.msgpack").read_bytes()
    )
    return model, params


def save_graph(directory: str | Path, graph: Any, host_index: dict[bytes, int]) -> None:
    """Snapshot the topology graph + host→row mapping beside the GNN params —
    the scheduler's ml evaluator needs both to refresh scorer embeddings and
    translate live host ids into graph rows."""
    d = Path(directory)
    np.savez_compressed(
        d / "graph.npz",
        node_feats=np.asarray(graph.node_feats),
        neighbors=np.asarray(graph.neighbors),
        mask=np.asarray(graph.mask),
        edge_feats=np.asarray(graph.edge_feats),
    )
    (d / "hosts.json").write_text(
        json.dumps({k.decode("utf-8", "replace"): v for k, v in host_index.items()})
    )


def load_graph(directory: str | Path) -> tuple[Any, dict[str, int]]:
    from dragonfly2_tpu.models.graphsage import TopoGraph

    d = Path(directory)
    z = np.load(d / "graph.npz")
    graph = TopoGraph(z["node_feats"], z["neighbors"], z["mask"], z["edge_feats"])
    host_index = json.loads((d / "hosts.json").read_text())
    return graph, {k: int(v) for k, v in host_index.items()}


def save_native(directory: str | Path, model: TopoScorer, params: Any, graph: Any) -> Path:
    """Export the native serving artifact beside the flax one: compute the
    cached node embeddings once in JAX, then flatten head weights + embeddings
    into the C++ scorer's binary format (native/scorer.cc; replaces the
    reference's TF-Serving hop, tfserving/client_v1.go:82-102)."""
    from dragonfly2_tpu.native import export_scorer_artifact

    z = np.asarray(jax.jit(lambda p, g: model.apply(p, g, method=model.embed))(params, graph))
    return export_scorer_artifact(params, z, Path(directory) / "scorer.dfsc")


def load_native(directory: str | Path):
    """Load the native scorer if its artifact exists, else None."""
    from dragonfly2_tpu.native import NativeScorer

    path = Path(directory) / "scorer.dfsc"
    if not path.exists():
        return None
    return NativeScorer(path)


def save_sketch(directory: str | Path, sketch: Any) -> Path:
    """Write the training-reference feature sketch beside the params
    (ISSUE 15). Called BEFORE artifact_digest, so the digest covers it like
    every other file — a tampered/truncated sketch fails verify_artifact the
    same way tampered weights do."""
    p = Path(directory) / "sketch.json"
    p.write_text(json.dumps(sketch.to_dict()))
    return p


def load_sketch(directory: str | Path):
    """The artifact's training-reference FeatureSketch, or None for
    pre-sketch artifacts (every pre-ISSUE-15 artifact; drift detection just
    stays dormant for them)."""
    from dragonfly2_tpu.observability.sketches import FeatureSketch

    p = Path(directory) / "sketch.json"
    if not p.exists():
        return None
    return FeatureSketch.from_dict(json.loads(p.read_text()))


def load_mlp(directory: str | Path) -> tuple[BandwidthMLP, Any]:
    cfg = load_config(directory)
    assert cfg["type"] == "mlp", cfg
    _check_format(cfg, directory)
    model = BandwidthMLP(hidden=tuple(cfg["hidden"]))
    from dragonfly2_tpu.models.features import FEATURE_DIM

    template = model.init(jax.random.PRNGKey(0), jnp.zeros((2, FEATURE_DIM)))
    params = flax.serialization.from_bytes(
        template, (Path(directory) / "params.msgpack").read_bytes()
    )
    return model, params
