"""Sharded GNN training (north-star configs 2-3).

One jitted train step over a ("data", "model") mesh: graph node rows and the
pair batch are sharded over "data", Dense kernels over "model"; XLA inserts
the neighbor-gather all-gathers and the gradient psum from the sharding
annotations alone (no hand-written collectives — pjit style, per the
scaling-book recipe).

Replaces the reference's never-implemented trainer loop (trainer/ is
config+metrics only; the Train RPC at pkg/rpc/trainer/server/server.go:59
received CSV chunks and dropped them on the floor).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dragonfly2_tpu.models.graphsage import TopoGraph, TopoScorer
from dragonfly2_tpu.parallel import mesh as meshlib
from dragonfly2_tpu.trainer.synthetic import PairBatch, sample_batch


@dataclass
class GNNTrainConfig:
    hidden: int = 256
    embed_dim: int = 128
    num_layers: int = 3
    batch_size: int = 4096
    learning_rate: float = 3e-3
    weight_decay: float = 1e-4
    warmup_steps: int = 100
    remat: bool = False


def make_model(cfg: GNNTrainConfig) -> TopoScorer:
    return TopoScorer(hidden=cfg.hidden, embed_dim=cfg.embed_dim, num_layers=cfg.num_layers)


def init_state(
    cfg: GNNTrainConfig, graph: TopoGraph, rng_seed: int = 0
) -> train_state.TrainState:
    from dragonfly2_tpu.models.features import FEATURE_DIM

    model = make_model(cfg)
    dummy_idx = jnp.zeros((8,), jnp.int32)
    dummy_feats = jnp.zeros((8, FEATURE_DIM), jnp.float32)
    params = model.init(
        jax.random.PRNGKey(rng_seed), _as_jnp_graph(graph), dummy_idx, dummy_idx, dummy_feats
    )
    tx = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(
            optax.warmup_cosine_decay_schedule(
                0.0, cfg.learning_rate, cfg.warmup_steps, 20_000, cfg.learning_rate * 0.05
            ),
            weight_decay=cfg.weight_decay,
        ),
    )
    return train_state.TrainState.create(apply_fn=model.apply, params=params, tx=tx)


def _as_jnp_graph(g: TopoGraph) -> TopoGraph:
    return TopoGraph(*(jnp.asarray(a) for a in g))


def loss_fn(apply_fn: Callable, params: Any, g: TopoGraph, batch: PairBatch) -> jnp.ndarray:
    pred = apply_fn(params, g, batch.child, batch.parent, batch.feats)
    return jnp.mean((pred - batch.label) ** 2)


def make_train_step(remat: bool = False, *, with_metrics: bool = False) -> Callable:
    """One optimizer step; with `remat` the model apply is wrapped in
    jax.checkpoint, so the backward pass RECOMPUTES the GNN forward instead
    of holding its activations — the [N, K, H] message tensors dominate live
    memory at scaled node counts (16k nodes × 16 neighbors × hidden), and
    trading them for FLOPs is what lets the scaled shape fit a single chip's
    HBM. Verified structurally: the lowered HLO at the 16k-node shape gains
    recomputation dot_generals (tests/test_trainer.py pins this).

    with_metrics=False (default) keeps the historic (state, loss) return;
    True widens it to (state, (loss, grad_norm)) — the global pre-update
    gradient norm the training-run telemetry exports per step (ISSUE 15).
    Opt-in so existing jitted callers (bench, profile tools, the sharded
    equivalence tests) keep their compiled shapes."""

    def step(
        state: train_state.TrainState, g: TopoGraph, batch: PairBatch
    ):
        apply_fn = jax.checkpoint(state.apply_fn) if remat else state.apply_fn
        loss, grads = jax.value_and_grad(partial(loss_fn, apply_fn))(state.params, g, batch)
        if with_metrics:
            gnorm = optax.global_norm(grads)
            return state.apply_gradients(grads=grads), (loss, gnorm)
        return state.apply_gradients(grads=grads), loss

    return step


# the default (no-remat) step keeps its name: shard_for_training /
# make_scan_step build their own from make_train_step when remat is on
train_step = make_train_step(remat=False)


def _place_sharded(
    state: train_state.TrainState, g: TopoGraph, mesh: Mesh
) -> tuple[train_state.TrainState, Any, TopoGraph, TopoGraph]:
    """Shared placement: pad node rows to the dp size, kernels over "model",
    node rows over "data". Returns (state, state_sharding, g, g_sharding)."""
    dp = mesh.shape[meshlib.DATA_AXIS]
    g = pad_graph(g, meshlib.pad_to_multiple(g.node_feats.shape[0], dp))
    param_sh = meshlib.infer_param_sharding(state.params, mesh)
    state_sh = train_state.TrainState(
        step=NamedSharding(mesh, P()),
        apply_fn=state.apply_fn,
        params=param_sh,
        tx=state.tx,
        opt_state=jax.tree.map(
            lambda leaf: meshlib.param_leaf_sharding(leaf, mesh), state.opt_state
        ),
    )
    state = jax.device_put(state, state_sh)
    g_sh = TopoGraph(*meshlib.graph_shardings(mesh))
    g = jax.device_put(_as_jnp_graph(g), g_sh)
    return state, state_sh, g, g_sh


def shard_for_training(
    state: train_state.TrainState, g: TopoGraph, mesh: Mesh, *, remat: bool = False
) -> tuple[train_state.TrainState, TopoGraph, Callable]:
    """Place state/graph per the mesh rules and return the jitted step.

    Node rows over "data" (pad N to the dp size first), kernels over "model",
    batch rows over "data".
    """
    state, state_sh, g, g_sh = _place_sharded(state, g, mesh)
    batch_sh = PairBatch(*([meshlib.batch_sharding(mesh)] * 4))
    step = jax.jit(
        make_train_step(remat),
        in_shardings=(state_sh, g_sh, batch_sh),
        out_shardings=(state_sh, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
    return state, g, step


def pad_graph(g: TopoGraph, n_padded: int) -> TopoGraph:
    """Pad node dim to n_padded with masked isolated nodes (static shapes)."""
    n = g.node_feats.shape[0]
    if n_padded == n:
        return g
    pad = n_padded - n
    return TopoGraph(
        np.concatenate([g.node_feats, np.zeros((pad, g.node_feats.shape[1]), np.float32)]),
        np.concatenate([g.neighbors, np.zeros((pad, g.neighbors.shape[1]), np.int32)]),
        np.concatenate([g.mask, np.zeros((pad, g.mask.shape[1]), np.float32)]),
        np.concatenate(
            [g.edge_feats, np.zeros((pad,) + g.edge_feats.shape[1:], np.float32)]
        ),
    )


def shard_for_training_scan(
    state: train_state.TrainState,
    g: TopoGraph,
    pairs: PairBatch,
    mesh: Mesh,
    *,
    batch_size: int = 4096,
    steps_per_call: int = 10,
    remat: bool = False,
    with_metrics: bool = False,
) -> tuple[train_state.TrainState, TopoGraph, PairBatch, Callable]:
    """Device-resident training: the pair POOL lives on device and each
    jitted call runs `steps_per_call` optimizer steps via lax.scan, sampling
    minibatches with the JAX PRNG inside the scan body.

    This removes the per-step host round trip (numpy sampling + H2D transfer
    + dispatch) that dominates wall clock for a model this size — the
    scaling-book rule: don't bounce to the host between steps. Returns
    (state, g, pairs, multi_step) where
    ``multi_step(state, g, pairs, key) -> (state, losses[steps_per_call])``.
    """
    batch_size = meshlib.pad_to_multiple(batch_size, mesh.shape[meshlib.DATA_AXIS])
    state, state_sh, g, g_sh = _place_sharded(state, g, mesh)
    # the full pool is small (MBs) and replicated; sampled rows get
    # constrained onto the data axis inside the step
    pool_sh = PairBatch(*([NamedSharding(mesh, P())] * 4))
    pairs = jax.device_put(PairBatch(*(jnp.asarray(a) for a in pairs)), pool_sh)
    jitted = make_scan_step(
        mesh, state_sh, g_sh, pool_sh,
        batch_size=batch_size, steps_per_call=steps_per_call, remat=remat,
        with_metrics=with_metrics,
    )
    return state, g, pairs, jitted


def make_scan_step(
    mesh: Mesh,
    state_sh: Any,
    g_sh: TopoGraph,
    pool_sh: PairBatch,
    *,
    batch_size: int,
    steps_per_call: int,
    remat: bool = False,
    with_metrics: bool = False,
) -> Callable:
    """The jitted K-step scan alone, given already-known shardings — lets a
    caller with placed arrays build variants (e.g. a 1-step lowering for
    FLOPs accounting) without re-placing state on the device. Shardings can
    be recovered from placed arrays via ``jax.tree.map(lambda x: x.sharding,
    tree)``. with_metrics widens the scan's ys from losses[K] to
    (losses[K], grad_norms[K]) — the replicated out-sharding below is a
    pytree PREFIX, so it covers either shape."""
    batch_sh = NamedSharding(mesh, P(meshlib.DATA_AXIS))
    step = make_train_step(remat, with_metrics=with_metrics)

    def multi_step(st, gg, pool, key):
        n_pool = pool.child.shape[0]

        def one(carry, k):
            idx = jax.random.randint(k, (batch_size,), 0, n_pool)
            batch = PairBatch(
                *(jax.lax.with_sharding_constraint(a[idx], batch_sh) for a in pool)
            )
            return step(carry, gg, batch)

        keys = jax.random.split(key, steps_per_call)
        return jax.lax.scan(one, st, keys)

    return jax.jit(
        multi_step,
        in_shardings=(state_sh, g_sh, pool_sh, NamedSharding(mesh, P())),
        out_shardings=(state_sh, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


async def train_async(
    cfg: GNNTrainConfig,
    graph: TopoGraph,
    pairs: PairBatch,
    *,
    steps: int,
    mesh: Mesh | None = None,
    seed: int = 0,
    steps_per_call: int = 10,
    log_every: int = 100,
    log: Callable[[str], None] = lambda s: None,
    telemetry=None,
) -> tuple[train_state.TrainState, list[float]]:
    """Cooperative training driver for asyncio hosts (the trainer service).

    Uses the device-resident scan path: each jitted `steps_per_call`-step
    call runs in a worker thread (asyncio.to_thread) and the event loop
    regains control between calls, so the host keeps answering RPCs
    mid-train instead of stalling for the whole run. Setup (init + placement
    + the compile triggered by the first call) runs in the worker too — the
    loop never blocks on XLA. Returns (state, per-step losses); loss length
    is steps rounded up to a whole number of calls.

    telemetry: optional trainer.metrics.TrainRunTelemetry — per-step loss +
    grad-norm land in the dragonfly_train_* families after every call. The
    grad norms ride the scan's ys (with_metrics), so the telemetry costs no
    extra D2H sync: the per-call np.asarray pull already materializes them.
    """
    mesh = mesh or meshlib.make_mesh()
    steps_per_call = max(1, min(steps_per_call, steps))
    calls = -(-steps // steps_per_call)
    with_metrics = telemetry is not None

    def _setup():
        state = init_state(cfg, graph, seed)
        return shard_for_training_scan(
            state, graph, pairs, mesh,
            batch_size=cfg.batch_size, steps_per_call=steps_per_call,
            remat=cfg.remat, with_metrics=with_metrics,
        )

    state, g, pool, multi_step = await asyncio.to_thread(_setup)
    key = jax.random.PRNGKey(seed)

    def _one_call(st, k):
        k, sub = jax.random.split(k)
        st, ys = multi_step(st, g, pool, sub)
        # D2H pull materializes the whole call's chain before returning to
        # the loop — the same sync discipline the bench windows use
        if with_metrics:
            ls, gn = ys
            return st, k, np.asarray(ls), np.asarray(gn)
        return st, k, np.asarray(ys), None

    losses: list[float] = []
    t0 = time.perf_counter()
    for i in range(calls):
        state, key, ls, gn = await asyncio.to_thread(_one_call, state, key)
        if telemetry is not None and gn is not None:
            for lv, gv in zip(ls, gn):
                telemetry.on_step(
                    float(lv), float(gv), examples=cfg.batch_size
                )
        losses.extend(float(x) for x in ls)
        done = len(losses)
        if done % log_every < steps_per_call or i == calls - 1:
            log(
                f"step {done}/{calls * steps_per_call} loss={losses[-1]:.5f} "
                f"({done / (time.perf_counter() - t0):.2f} steps/s)"
            )
    return state, losses


def train(
    cfg: GNNTrainConfig,
    graph: TopoGraph,
    pairs: PairBatch,
    *,
    steps: int,
    mesh: Mesh | None = None,
    seed: int = 0,
    log_every: int = 100,
    log: Callable[[str], None] = lambda s: None,
) -> tuple[train_state.TrainState, list[float]]:
    """Full training driver; returns final state + loss history."""
    mesh = mesh or meshlib.make_mesh()
    state = init_state(cfg, graph, seed)
    state, g, step_fn = shard_for_training(state, graph, mesh, remat=cfg.remat)
    rng = np.random.default_rng(seed)
    # Batch rows shard over "data": round up so every shard is equal-sized.
    batch_size = meshlib.pad_to_multiple(cfg.batch_size, mesh.shape[meshlib.DATA_AXIS])
    losses: list[float] = []
    t0 = time.perf_counter()
    for i in range(steps):
        batch = sample_batch(pairs, batch_size, rng)
        state, loss = step_fn(state, g, PairBatch(*(jnp.asarray(a) for a in batch)))
        if (i + 1) % log_every == 0 or i == 0:
            lv = float(loss)
            losses.append(lv)
            log(f"step {i + 1}/{steps} loss={lv:.5f} ({(i + 1) / (time.perf_counter() - t0):.2f} steps/s)")
    jax.block_until_ready(state.params)
    return state, losses
