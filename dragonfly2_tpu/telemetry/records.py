"""Columnar telemetry stores with size-based rotation.

Record shapes mirror the reference's Download and NetworkTopology CSVs
(scheduler/storage/types.go:26-235) but normalized: instead of flattening 20
parents / 10 dest-hosts into one wide row, each (child, parent) transfer and
each (src, dst) probe is its *own row* — the natural layout for building
training pair batches and edge lists without unflattening.
"""

from __future__ import annotations

import time
from pathlib import Path

import numpy as np

_ID_LEN = 64  # sha256 hex task ids; peer/host ids truncated to fit

DOWNLOAD_DTYPE = np.dtype(
    [
        ("task_id", f"S{_ID_LEN}"),
        ("child_peer_id", f"S{_ID_LEN}"),
        ("parent_peer_id", f"S{_ID_LEN}"),
        ("child_host_id", f"S{_ID_LEN}"),
        ("parent_host_id", f"S{_ID_LEN}"),
        ("piece_count", "i4"),
        ("piece_size", "i8"),
        ("content_length", "i8"),
        ("bandwidth_bps", "f4"),  # observed child<-parent throughput
        ("piece_cost_ms_mean", "f4"),
        ("success", "?"),
        ("back_to_source", "?"),
        ("pair_features", "f4", (16,)),  # models.features.FEATURE_NAMES order
        ("created_at", "f8"),
    ]
)

PROBE_DTYPE = np.dtype(
    [
        ("src_host_id", f"S{_ID_LEN}"),
        ("dst_host_id", f"S{_ID_LEN}"),
        ("rtt_mean_ms", "f4"),
        ("rtt_std_ms", "f4"),
        ("rtt_min_ms", "f4"),
        ("probe_count", "i4"),
        ("created_at", "f8"),
    ]
)


class ColumnarStore:
    """Append-only structured-array store with rotation.

    Rows buffer in a preallocated numpy array; at `rotate_rows` the buffer
    flushes to `<dir>/<prefix>-<seq>.npz` and at most `max_backups` files are
    kept (ref storage.go rotation: maxSize/maxBackups).
    """

    def __init__(
        self,
        directory: str | Path,
        prefix: str,
        dtype: np.dtype,
        *,
        rotate_rows: int = 65536,
        max_backups: int = 10,
    ):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.prefix = prefix
        self.dtype = dtype
        self.rotate_rows = rotate_rows
        self.max_backups = max_backups
        self._buf = np.zeros(rotate_rows, dtype=dtype)
        self._n = 0
        self._seq = self._next_seq()

    def _next_seq(self) -> int:
        seqs = [int(p.stem.rsplit("-", 1)[1]) for p in self._files()]
        return (max(seqs) + 1) if seqs else 0

    def _files(self) -> list[Path]:
        out = []
        for p in self.dir.glob(f"{self.prefix}-*.npz"):
            try:
                int(p.stem.rsplit("-", 1)[1])
                out.append(p)
            except (ValueError, IndexError):
                continue
        return sorted(out, key=lambda p: int(p.stem.rsplit("-", 1)[1]))

    def append(self, **fields) -> None:
        row = self._buf[self._n]
        for k, v in fields.items():
            row[k] = v
        if "created_at" in self.dtype.names and "created_at" not in fields:
            row["created_at"] = time.time()
        self._n += 1
        if self._n >= self.rotate_rows:
            self.flush()

    def flush(self, *, prune: bool = True) -> Path | None:
        if self._n == 0:
            return None
        path = self.dir / f"{self.prefix}-{self._seq}.npz"
        np.savez_compressed(path, records=self._buf[: self._n].copy())
        self._seq += 1
        self._n = 0
        if prune:
            files = self._files()
            for old in files[: max(0, len(files) - self.max_backups)]:
                old.unlink(missing_ok=True)
        return path

    def load_all(self, *, include_buffer: bool = True) -> np.ndarray:
        """All persisted (+ buffered) records, oldest first."""
        parts = [np.load(p)["records"] for p in self._files()]
        if include_buffer and self._n:
            parts.append(self._buf[: self._n].copy())
        if not parts:
            return np.zeros(0, dtype=self.dtype)
        return np.concatenate(parts)

    def snapshot(self) -> tuple[np.ndarray, tuple[Path, ...]]:
        """Consistent upload cut: flush the buffer, then return (records,
        files) for exactly the rows present NOW. Rows appended afterwards
        land in the fresh buffer / later files and are untouched by a
        subsequent discard(files) — the clear-after-upload path that used to
        silently drop anything appended while the upload's RPCs were in
        flight. The cut flush skips max_backups pruning: at the cap, a
        pruning flush would delete the oldest unuploaded file an instant
        before the cut reads it; the upload's own discard() is what brings
        the file count back down."""
        self.flush(prune=False)
        files = tuple(self._files())
        return self.load_all(include_buffer=False), files

    def discard(self, files: tuple[Path, ...]) -> None:
        """Drop exactly the files a snapshot() returned (handed off upstream)."""
        for p in files:
            Path(p).unlink(missing_ok=True)

    def clear(self) -> None:
        for p in self._files():
            p.unlink(missing_ok=True)
        self._n = 0
        self._seq = 0

    def __len__(self) -> int:
        return sum(len(np.load(p)["records"]) for p in self._files()) + self._n


class TelemetryStorage:
    """Download + probe stores under one dir (ref scheduler/storage.Storage)."""

    def __init__(self, directory: str | Path, **kw):
        self.downloads = ColumnarStore(directory, "download", DOWNLOAD_DTYPE, **kw)
        self.probes = ColumnarStore(directory, "networktopology", PROBE_DTYPE, **kw)

    def flush(self) -> None:
        self.downloads.flush()
        self.probes.flush()

    def clear(self) -> None:
        self.downloads.clear()
        self.probes.clear()
