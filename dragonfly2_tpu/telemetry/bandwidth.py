"""Observed-bandwidth history: the serving-side store behind feature f[8].

The reference's download records carry per-transfer bandwidth into CSVs that
only the (never-implemented) trainer would read (scheduler/storage/types.go
Download.Bandwidth); nothing fed it back into scheduling. Here the loop is
closed: every successful peer result updates an EWMA keyed by
(parent_host, child_host) with a per-parent-host aggregate fallback, the
feature builder reads it at scoring time (models.features "bandwidth_norm"),
and on boot the history warm-starts from the telemetry store's persisted
download records — so the ML plane scores with the bandwidth eye open.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# 1 GiB/s — the reference's default total download/upload rate limit
# (client/config/constants.go:46-47); bandwidth_norm divides by this.
BANDWIDTH_NORM_BPS = float(1 << 30)


class BandwidthHistory:
    """EWMA bandwidth tracker keyed by host pair, with parent-host fallback.

    alpha: EWMA weight of a new observation. Pair-specific history answers
    "how fast was THIS parent for THIS child's host"; the per-parent aggregate
    answers for children that never downloaded from it before.
    """

    def __init__(self, alpha: float = 0.3):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self._pair: dict[tuple[str, str], float] = {}
        self._parent: dict[str, float] = {}
        # Coarse change counter (any mutation) kept for cheap staleness
        # checks; the evaluator's pair-row cache keys on parent_version()
        # below — one observation invalidates only that PARENT's rows.
        self.version = 0
        # Per-parent-host change counters: an observe(parent, child) updates
        # the (parent, child) pair EWMA and the parent-aggregate fallback, so
        # it can change normalized() for ANY child of that parent (children
        # with no pair entry read the fallback) — but never for another
        # parent. Monotonic, never deleted (see NetworkTopology._pair_vers
        # for the id-recycling rationale).
        self._parent_vers: dict[str, int] = {}
        # Native-mirror client (scheduler.mirror.MirrorClient): parent bumps
        # forward to the C-side mirror so its cached rows stale correctly
        self._mirror = None
        # Federation delta clock + merged remote view (same contract as
        # NetworkTopology — shared semantics in utils/deltaclock.py): local
        # observes stamp their pair key with the post-bump coarse version;
        # merged entries are never re-stamped (never re-gossiped), carry
        # their origin (so a restarted peer's leftovers can be purged), and
        # are consulted by query() only when no local history exists.
        from dragonfly2_tpu.utils.deltaclock import DeltaClock

        self._clock = DeltaClock()
        self._remote_pair: dict[tuple[str, str], float] = {}
        self._remote_origin: dict[tuple[str, str], str] = {}
        self._remote_parent: dict[str, float] = {}
        # host -> pair keys touching it, and a per-parent count of remote
        # pairs: forget_host ran an O(all pairs) membership scan per departed
        # host, and merge_remote's drop-the-aggregate-with-the-last-pair rule
        # re-scanned every remote pair per tombstone — together the top CPU
        # items under churn at 10^5 peers (swarm-simulator finding)
        self._pairs_by_host: dict[str, set] = {}
        self._remote_pairs_by_host: dict[str, set] = {}
        self._remote_parent_pairs: dict[str, int] = {}

    def parent_version(self, parent_host_id: str) -> int:
        """Change counter covering every pair this parent serves (pair EWMA
        or aggregate fallback) — the evaluator cache key's bandwidth leg."""
        return self._parent_vers.get(parent_host_id, 0)

    def _bump_parent(self, parent_host_id: str) -> None:
        ver = self._parent_vers[parent_host_id] = self._parent_vers.get(parent_host_id, 0) + 1
        m = self._mirror
        if m is not None:
            # native-mirror delta (ISSUE 19): post-bump version keys the
            # mirror's row staleness check for every pair this parent serves
            m.on_bw_parent(parent_host_id, ver)

    def observe(self, parent_host_id: str, child_host_id: str, bps: float) -> None:
        if not parent_host_id or not np.isfinite(bps) or bps <= 0:
            return
        a = self.alpha
        key = (parent_host_id, child_host_id)
        prev = self._pair.get(key)
        self._pair[key] = bps if prev is None else (1 - a) * prev + a * bps
        if prev is None:
            self._pairs_by_host.setdefault(parent_host_id, set()).add(key)
            self._pairs_by_host.setdefault(child_host_id, set()).add(key)
        prev = self._parent.get(parent_host_id)
        self._parent[parent_host_id] = bps if prev is None else (1 - a) * prev + a * bps
        # Versions bump AFTER the EWMA writes (reader-safe ordering for the
        # dispatcher's lock-free feature assembly): a concurrent reader that
        # observes the new parent_version must also observe the new EWMA —
        # the reverse order could cache the stale value under the new version
        # key, serving it until the NEXT observation. A reader that keyed on
        # the old version but read the new value merely re-assembles one row
        # on its next lookup (the cache converges, never sticks stale).
        self._bump_parent(parent_host_id)
        self.version += 1
        self._clock.stamp(key, self.version)

    def query(self, parent_host_id: str, child_host_id: str) -> Optional[float]:
        """Best available estimate in bytes/s, or None with no history.
        Lookup order: local pair EWMA → federation-merged pair EWMA → local
        per-parent aggregate → merged per-parent aggregate (local data wins
        at equal specificity: it is fresher than a gossip round)."""
        v = self._pair.get((parent_host_id, child_host_id))
        if v is not None:
            return v
        v = self._remote_pair.get((parent_host_id, child_host_id))
        if v is not None:
            return v
        v = self._parent.get(parent_host_id)
        if v is not None:
            return v
        return self._remote_parent.get(parent_host_id)

    def normalized(self, parent_host_id: str, child_host_id: str) -> float:
        """Feature-space value: observed bps / 1 GiB/s, clipped to [0, 1];
        0.0 means "no history" (matches the feature's training-time prior)."""
        v = self.query(parent_host_id, child_host_id)
        if v is None:
            return 0.0
        return float(min(v / BANDWIDTH_NORM_BPS, 1.0))

    def forget_host(self, host_id: str) -> None:
        """Drop all history touching a GC'd host — O(that host's pairs) via
        the per-host index, not O(all pairs)."""
        self._parent.pop(host_id, None)
        self._bump_parent(host_id)
        for key in [k for k in self._pairs_by_host.pop(host_id, ()) if k in self._pair]:
            del self._pair[key]
            other = key[0] if key[1] == host_id else key[1]
            if other != host_id:
                peers = self._pairs_by_host.get(other)
                if peers is not None:
                    peers.discard(key)
            # dropping a (parent, child) pair changes normalized() for that
            # PARENT (its children fall back to the aggregate) even when the
            # forgotten host was the child side
            if key[0] != host_id:
                self._bump_parent(key[0])
            self.version += 1
            self._clock.stamp_tombstone(key, self.version)  # gossiped delete
        self._remote_parent.pop(host_id, None)
        for key in list(self._remote_pairs_by_host.pop(host_id, ())):
            if key not in self._remote_pair:
                continue
            self._drop_remote_pair(key)
            if key[0] != host_id:
                self._bump_parent(key[0])
        self.version += 1
        self._clock.prune()

    def _drop_remote_pair(self, key: tuple[str, str]) -> None:
        """Remove one merged pair, maintaining both indexes and the
        per-parent refcount (aggregate eviction reads it)."""
        if self._remote_pair.pop(key, None) is None:
            return
        self._remote_origin.pop(key, None)
        for h in key:
            peers = self._remote_pairs_by_host.get(h)
            if peers is not None:
                peers.discard(key)
        n = self._remote_parent_pairs.get(key[0], 0) - 1
        if n > 0:
            self._remote_parent_pairs[key[0]] = n
        else:
            self._remote_parent_pairs.pop(key[0], None)

    def _add_remote_pair(self, key: tuple[str, str], origin: str) -> None:
        if key not in self._remote_pair:
            for h in key:
                self._remote_pairs_by_host.setdefault(h, set()).add(key)
            self._remote_parent_pairs[key[0]] = (
                self._remote_parent_pairs.get(key[0], 0) + 1
            )
        self._remote_origin[key] = origin

    # ---- federation delta sync (scheduler/federation.py) ----

    def local_entries_since(self, since: int) -> tuple[int, list[dict]]:
        """(watermark, deltas): locally-observed pair EWMAs stamped above
        `since`, each carrying the parent's aggregate fallback alongside;
        forgotten pairs ship tombstones. O(changed) payload."""
        out = []
        for key in self._clock.since(since):
            bps = self._pair.get(key)
            if bps is None:
                out.append({"parent": key[0], "child": key[1], "deleted": True})
            else:
                out.append({
                    "parent": key[0], "child": key[1], "bps": bps,
                    "parent_agg": self._parent.get(key[0], bps),
                })
        return self.version, out

    def merge_remote(self, entries: list[dict], *, origin: str = "") -> int:
        """Apply a peer's bandwidth deltas into the merged view (idempotent:
        re-delivering the same EWMA value is a no-op). Bumps the parent
        version so cached pair rows reading the fallback re-assemble."""
        applied = 0
        for e in entries:
            key = (e["parent"], e["child"])
            if e.get("deleted"):
                if key in self._remote_pair:
                    self._drop_remote_pair(key)
                    applied += 1
                    self.version += 1
                    self._bump_parent(key[0])
                # drop the merged parent aggregate once its LAST remote pair
                # is gone: a GC'd (possibly id-recycled) parent must not keep
                # serving a stale fallback estimate forever (refcount — the
                # original any()-over-every-pair scan per tombstone was the
                # top churn cost at 10^5 peers)
                if not self._remote_parent_pairs.get(key[0]):
                    if self._remote_parent.pop(key[0], None) is not None:
                        self._bump_parent(key[0])
                        self.version += 1
                continue
            changed = self._remote_pair.get(key) != e["bps"]
            agg = e.get("parent_agg")
            if agg is not None and self._remote_parent.get(key[0]) != agg:
                self._remote_parent[key[0]] = float(agg)
                changed = True
            if not changed:
                continue
            self._add_remote_pair(key, origin)
            self._remote_pair[key] = float(e["bps"])
            applied += 1
            self.version += 1
            self._bump_parent(key[0])
        return applied

    def purge_remote_origin(self, origin: str) -> int:
        """Drop merged entries received from a peer that RESTARTED (its
        successor's empty clock can never tombstone them) — mirror of
        NetworkTopology.purge_remote_origin."""
        dead = [k for k, o in self._remote_origin.items() if o == origin]
        for k in dead:
            self._drop_remote_pair(k)
            self._bump_parent(k[0])
            self.version += 1
            if not self._remote_parent_pairs.get(k[0]):
                if self._remote_parent.pop(k[0], None) is not None:
                    self._bump_parent(k[0])
                    self.version += 1
        return len(dead)

    def remote_entry_count(self) -> int:
        return len(self._remote_pair)

    def load_from(self, telemetry) -> int:
        """Warm-start from persisted download records (oldest first, so the
        EWMA ends weighted toward recent transfers). Returns rows ingested."""
        recs = telemetry.downloads.load_all()
        n = 0
        for r in recs:
            if not r["success"] or r["bandwidth_bps"] <= 0:
                continue
            parent = bytes(r["parent_host_id"]).rstrip(b"\x00").decode(errors="replace")
            child = bytes(r["child_host_id"]).rstrip(b"\x00").decode(errors="replace")
            if not parent:
                continue
            self.observe(parent, child, float(r["bandwidth_bps"]))
            n += 1
        return n

    def __len__(self) -> int:
        return len(self._pair)
