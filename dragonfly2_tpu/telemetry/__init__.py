"""Training-telemetry capture: download + network-topology records.

Reference equivalent: scheduler/storage/ (buffered CSV writers with rotation,
storage.go:60-208, record schemas types.go:26-235). Redesigned columnar —
numpy structured arrays persisted as .npz with rotation — so the trainer's
data loader is a zero-copy `np.load` into device arrays instead of CSV
parsing (SURVEY.md §7 hard part: "CSV→Arrow schema fidelity").
"""

from dragonfly2_tpu.telemetry.bandwidth import (  # noqa: F401
    BANDWIDTH_NORM_BPS,
    BandwidthHistory,
)
from dragonfly2_tpu.telemetry.records import (  # noqa: F401
    DOWNLOAD_DTYPE,
    PROBE_DTYPE,
    ColumnarStore,
    TelemetryStorage,
)
