"""Generic GC task registry.

Parity with reference pkg/gc/gc.go:28-70,144: named GC tasks with a per-task
interval and timeout; run-all / run-one; used by the scheduler's resource
managers (peer/task/host TTL sweeps) and the daemon's storage reclaimer.
Async-native here: the runner is an asyncio task per registration.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable

logger = logging.getLogger(__name__)


@dataclass
class GCTask:
    id: str
    interval: float
    runner: Callable[[], Awaitable[None] | None]
    timeout: float | None = None
    last_run: float | None = None
    runs: int = 0
    failures: int = 0
    _handle: asyncio.Task | None = field(default=None, repr=False)


class GC:
    def __init__(self) -> None:
        self._tasks: dict[str, GCTask] = {}
        self._started = False

    def add(
        self,
        task_id: str,
        interval: float,
        runner: Callable[[], Awaitable[None] | None],
        *,
        timeout: float | None = None,
    ) -> GCTask:
        if task_id in self._tasks:
            raise ValueError(f"gc task exists: {task_id}")
        if interval <= 0:
            raise ValueError("interval must be > 0")
        t = GCTask(task_id, interval, runner, timeout)
        self._tasks[task_id] = t
        if self._started:
            t._handle = asyncio.ensure_future(self._loop(t))
        return t

    def tasks(self) -> list[GCTask]:
        return list(self._tasks.values())

    async def run(self, task_id: str) -> None:
        await self._run_once(self._tasks[task_id])

    async def run_all(self) -> None:
        await asyncio.gather(*(self._run_once(t) for t in self._tasks.values()))

    async def _run_once(self, t: GCTask) -> None:
        t.last_run = time.monotonic()
        t.runs += 1
        try:
            result = t.runner()
            if inspect.isawaitable(result):
                if t.timeout:
                    await asyncio.wait_for(result, t.timeout)
                else:
                    await result
        except Exception:
            t.failures += 1
            logger.exception("gc task %s failed", t.id)

    async def _loop(self, t: GCTask) -> None:
        while True:
            await asyncio.sleep(t.interval)
            await self._run_once(t)

    def start(self) -> None:
        self._started = True
        for t in self._tasks.values():
            if t._handle is None:
                t._handle = asyncio.ensure_future(self._loop(t))

    def stop(self) -> None:
        self._started = False
        for t in self._tasks.values():
            if t._handle is not None:
                t._handle.cancel()
                t._handle = None
