"""Byte-size unit parsing/formatting (reference pkg/unit)."""

from __future__ import annotations

import re

_UNITS = {
    "": 1,
    "B": 1,
    "K": 1 << 10, "KB": 1 << 10, "KI": 1 << 10, "KIB": 1 << 10,
    "M": 1 << 20, "MB": 1 << 20, "MI": 1 << 20, "MIB": 1 << 20,
    "G": 1 << 30, "GB": 1 << 30, "GI": 1 << 30, "GIB": 1 << 30,
    "T": 1 << 40, "TB": 1 << 40, "TI": 1 << 40, "TIB": 1 << 40,
}

_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([A-Za-z]*)\s*$")

KB = 1 << 10
MB = 1 << 20
GB = 1 << 30


def parse_bytes(s: str | int | float) -> int:
    if isinstance(s, (int, float)):
        return int(s)
    m = _RE.match(s)
    if not m:
        raise ValueError(f"invalid size: {s!r}")
    value, suffix = float(m.group(1)), m.group(2).upper()
    if suffix not in _UNITS:
        raise ValueError(f"invalid size unit: {s!r}")
    return int(value * _UNITS[suffix])


def format_bytes(n: int | float) -> str:
    n = float(n)
    for suffix, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if abs(n) >= div:
            return f"{n / div:.1f}{suffix}"
    return f"{int(n)}B"
