"""Validated YAML config surface.

Parity with the reference's config layer: every service boots from a YAML
file parsed into a strongly-typed config struct with exhaustive defaults and
a ``Validate()`` pass that rejects bad values with a precise field path
(ref client/config/peerhost.go:176-476, scheduler/config/config.go:76-424,
417-424). Flags override file values (the reference's cobra/viper layering).

Declarative: a service config is a tree of dataclasses whose fields carry
constraints in ``field(metadata=...)`` via :func:`cfgfield`::

    @dataclass
    class SchedulerYaml:
        port: int = cfgfield(9000, minimum=1, maximum=65535)
        evaluator: str = cfgfield("base", choices=("base", "ml"))

    cfg = load_config(SchedulerYaml, "scheduler.yaml")

``load_config`` applies defaults for absent keys, rejects unknown keys,
coerces scalar types, recurses into nested dataclass sections, and raises
:class:`ConfigError` naming the dotted path of the offending field.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Type, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T")

_META_KEY = "dfconfig"


class ConfigError(ValueError):
    """A config violation with the dotted field path."""

    def __init__(self, path: str, message: str):
        self.path = path
        super().__init__(f"config field {path!r}: {message}" if path else message)


def cfgfield(
    default: Any = dataclasses.MISSING,
    *,
    default_factory: Any = dataclasses.MISSING,
    minimum: float | None = None,
    maximum: float | None = None,
    choices: tuple | None = None,
    required: bool = False,
    help: str = "",
):
    """A dataclass field carrying validation constraints."""
    meta = {
        _META_KEY: {
            "minimum": minimum,
            "maximum": maximum,
            "choices": choices,
            "required": required,
            "help": help,
        }
    }
    if default_factory is not dataclasses.MISSING:
        return dataclasses.field(default_factory=default_factory, metadata=meta)
    if default is dataclasses.MISSING:
        return dataclasses.field(metadata=meta)
    return dataclasses.field(default=default, metadata=meta)


def _coerce_scalar(value: Any, target: type, path: str) -> Any:
    if target is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise ConfigError(path, f"expected number, got {type(value).__name__}")
        return float(value)
    if target is int:
        if isinstance(value, bool) or not isinstance(value, int):
            raise ConfigError(path, f"expected integer, got {type(value).__name__}")
        return value
    if target is bool:
        if not isinstance(value, bool):
            raise ConfigError(path, f"expected boolean, got {type(value).__name__}")
        return value
    if target is str:
        if not isinstance(value, str):
            raise ConfigError(path, f"expected string, got {type(value).__name__}")
        return value
    return value


def _unwrap_optional(tp: Any) -> tuple[Any, bool]:
    """X | None → (X, True); plain types → (tp, False)."""
    if get_origin(tp) is not None and type(None) in get_args(tp):
        inner = [a for a in get_args(tp) if a is not type(None)]
        if len(inner) == 1:
            return inner[0], True
    return tp, False


def _build(cls: Type[T], data: Any, path: str) -> T:
    if data is None:
        data = {}
    if not isinstance(data, dict):
        raise ConfigError(path or "<root>", f"expected mapping, got {type(data).__name__}")
    hints = get_type_hints(cls)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    for key in data:
        if key not in fields:
            known = ", ".join(sorted(fields))
            raise ConfigError(
                f"{path}.{key}" if path else str(key), f"unknown key (known: {known})"
            )
    kwargs: dict[str, Any] = {}
    for name, f in fields.items():
        fpath = f"{path}.{name}" if path else name
        meta = f.metadata.get(_META_KEY, {})
        tp, optional = _unwrap_optional(hints.get(name, Any))
        if name not in data:
            if meta.get("required"):
                raise ConfigError(fpath, "required field missing")
            if dataclasses.is_dataclass(tp) and f.default is dataclasses.MISSING and (
                f.default_factory is dataclasses.MISSING
            ):
                kwargs[name] = _build(tp, {}, fpath)  # nested section, all defaults
            continue  # dataclass default applies
        value = data[name]
        if value is None and optional:
            kwargs[name] = None
            continue
        if dataclasses.is_dataclass(tp):
            kwargs[name] = _build(tp, value, fpath)
            continue
        origin = get_origin(tp)
        if origin in (list, tuple):
            if not isinstance(value, list):
                raise ConfigError(fpath, f"expected list, got {type(value).__name__}")
            item_t = (get_args(tp) or (Any,))[0]
            items = [
                _coerce_scalar(v, item_t, f"{fpath}[{i}]") if item_t in (int, float, bool, str) else v
                for i, v in enumerate(value)
            ]
            kwargs[name] = tuple(items) if origin is tuple else items
        elif tp in (int, float, bool, str):
            kwargs[name] = _coerce_scalar(value, tp, fpath)
        else:
            kwargs[name] = value
        _check_constraints(kwargs[name], meta, fpath)
    obj = cls(**kwargs)
    validate(obj, path)
    return obj


def _check_constraints(value: Any, meta: dict, path: str) -> None:
    if value is None:
        return
    mn, mx, choices = meta.get("minimum"), meta.get("maximum"), meta.get("choices")
    if mn is not None and isinstance(value, (int, float)) and value < mn:
        raise ConfigError(path, f"{value} below minimum {mn}")
    if mx is not None and isinstance(value, (int, float)) and value > mx:
        raise ConfigError(path, f"{value} above maximum {mx}")
    if choices is not None and value not in choices:
        raise ConfigError(path, f"{value!r} not one of {list(choices)}")


def validate(obj: Any, path: str = "") -> None:
    """Re-check every constraint on an already-built config tree (catches
    programmatic mutation after load; the reference's Validate())."""
    for f in dataclasses.fields(obj):
        fpath = f"{path}.{f.name}" if path else f.name
        value = getattr(obj, f.name)
        meta = f.metadata.get(_META_KEY, {})
        if dataclasses.is_dataclass(value) and not isinstance(value, type):
            validate(value, fpath)
        else:
            _check_constraints(value, meta, fpath)
    hook = getattr(obj, "validate_extra", None)
    if callable(hook):
        hook(path)


def load_config(cls: Type[T], path: str | Path | None = None, overrides: dict | None = None) -> T:
    """Build a validated config: YAML file (optional) + override mapping
    (flags), defaults elsewhere. Overrides use flat dotted keys
    (``{"scheduling.retry_limit": 5}``) or nested dicts."""
    import yaml

    data: dict = {}
    if path is not None:
        text = Path(path).read_text()
        loaded = yaml.safe_load(text)
        if loaded is None:
            loaded = {}
        if not isinstance(loaded, dict):
            raise ConfigError("<root>", f"config file must be a mapping, got {type(loaded).__name__}")
        data = loaded
    for key, value in (overrides or {}).items():
        if value is None:
            continue
        cursor = data
        *parents, leaf = key.split(".")
        for p in parents:
            nxt = cursor.setdefault(p, {})
            if not isinstance(nxt, dict):
                cursor[p] = nxt = {}
            cursor = nxt
        cursor[leaf] = value
    return _build(cls, data, "")
