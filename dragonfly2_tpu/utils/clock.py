"""Injectable time source for the control plane.

The scheduler's TTL/GC sweeps, peer/host `updated_at` freshness, the depth
memo's staleness bound, and probe-edge timestamps all read the clock. In
production that is the process clock; the discrete-event swarm simulator
(dragonfly2_tpu.sim) drives the SAME scheduler objects under a virtual clock
so that 24 h of TTL behavior, federation convergence, or a flash crowd can
play out in seconds of wall time — which only works if every time read on
those paths goes through one injectable seam.

Two readings, mirroring the stdlib split the call sites already used:

  monotonic()  elapsed-time comparisons (TTL sweeps, memo ages, touch())
  time()       wall-clock stamps that cross process boundaries (probe-edge
               updated_at rides the federation gossip's monotonic-merge rule,
               telemetry created_at)

`SYSTEM` is the module-level default; constructors take `clock=None` meaning
"the system clock" so production call sites never change. VirtualClock is
seedable (explicit start/epoch) and advanced only by its owner — it never
moves on its own, which is the whole point: event ORDER, not the wall,
defines simulated time.
"""

from __future__ import annotations

import time as _time


class Clock:
    """The system clock (production default)."""

    def monotonic(self) -> float:
        return _time.monotonic()

    def time(self) -> float:
        return _time.time()


class VirtualClock(Clock):
    """Manually-advanced clock for discrete-event simulation.

    monotonic() starts at `start`; time() reports `epoch + elapsed` so wall
    stamps are deterministic run-to-run (seedable). advance() moves forward
    only — simulated time, like real time, never goes backward.
    """

    __slots__ = ("_mono", "_epoch")

    def __init__(self, start: float = 0.0, epoch: float = 1_600_000_000.0):
        self._mono = float(start)
        self._epoch = float(epoch) - float(start)

    def monotonic(self) -> float:
        return self._mono

    def time(self) -> float:
        return self._epoch + self._mono

    def advance(self, dt: float) -> float:
        """Move time forward by dt seconds (dt < 0 is an error)."""
        if dt < 0:
            raise ValueError(f"virtual clock cannot go backward (dt={dt})")
        self._mono += dt
        return self._mono

    def advance_to(self, t: float) -> float:
        """Jump to monotonic time t; a t in the past is a no-op (an event
        processed tardily executes at the current now — see sim.engine)."""
        if t > self._mono:
            self._mono = t
        return self._mono


SYSTEM = Clock()
