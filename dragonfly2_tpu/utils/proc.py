"""Process lifecycle helpers shared by the service entry points."""

from __future__ import annotations

import asyncio
import signal


async def run_until_signalled(ready_event: asyncio.Event | None = None) -> None:
    """Signal readiness, then block until SIGINT/SIGTERM."""
    if ready_event is not None:
        ready_event.set()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover - non-unix loops
            pass
    await stop.wait()
