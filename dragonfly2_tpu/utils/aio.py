"""Small asyncio compatibility helpers.

This image runs Python 3.10, where asyncio.TaskGroup (3.11+) does not exist —
the two fan-out sites that wanted its semantics (ranged back-to-source piece
fetches, checkpoint multi-file fetch) raised AttributeError at runtime the
moment they were reached. `gather_all_cancel_on_error` provides the one
TaskGroup behavior those sites rely on: run everything, and on the first
failure cancel the stragglers before re-raising (so multi-GB sibling
downloads don't keep running detached after the caller has already failed).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Iterable

__all__ = ["gather_all_cancel_on_error"]


async def gather_all_cancel_on_error(coros: Iterable[Awaitable]) -> None:
    """Await all coroutines; first failure cancels the rest and re-raises.

    Unlike bare asyncio.gather (which returns control on the first error but
    leaves the remaining tasks running detached), every task is finished or
    cancelled by the time this returns — TaskGroup semantics on 3.10. The
    first exception (in completion order) propagates; later ones are eaten,
    as with TaskGroup's primary-error behavior for non-ExceptionGroup users.
    """
    tasks = [asyncio.ensure_future(c) for c in coros]
    if not tasks:
        return
    try:
        await asyncio.gather(*tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise
