"""Piece geometry: sizes, ranges, HTTP Range parsing.

Parity with reference client/daemon/peer/piece_manager.go (computePieceSize —
piece size scales up with content length so huge files don't explode into
millions of pieces) and pkg/net/http/range.go (Range header parse/format).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

DEFAULT_PIECE_SIZE = 4 << 20  # 4 MiB
MAX_PIECE_SIZE = 64 << 20
# Content-length thresholds at which the piece size doubles (reference scales
# piece size by size class: <=256 MiB → 4 MiB pieces, then doubles per 4x).
_SIZE_STEP = 256 << 20


def compute_piece_size(content_length: int) -> int:
    """Piece size for a task: 4 MiB base, doubling per 4x of 256 MiB, cap 64 MiB."""
    if content_length <= 0:
        return DEFAULT_PIECE_SIZE
    size = DEFAULT_PIECE_SIZE
    threshold = _SIZE_STEP
    while content_length > threshold and size < MAX_PIECE_SIZE:
        size *= 2
        threshold *= 4
    return size


def piece_count(content_length: int, piece_size: int) -> int:
    if content_length <= 0:
        return 0
    return (content_length + piece_size - 1) // piece_size


@dataclass(frozen=True, slots=True)
class Range:
    """Byte range [start, start+length), mirroring nethttp.Range."""

    start: int
    length: int

    @property
    def end(self) -> int:  # inclusive, HTTP-style
        return self.start + self.length - 1

    def header(self) -> str:
        return f"bytes={self.start}-{self.end}"


def piece_range(piece_index: int, piece_size: int, content_length: int) -> Range:
    start = piece_index * piece_size
    length = min(piece_size, content_length - start)
    if length <= 0:
        raise ValueError(f"piece {piece_index} out of range for length {content_length}")
    return Range(start, length)


_RANGE_RE = re.compile(r"^\s*bytes\s*=\s*(\d*)\s*-\s*(\d*)\s*$")


def parse_http_range(header: str, total: int) -> Range:
    """Parse a single-part HTTP Range header against a known total size."""
    if total < 0:
        raise ValueError("total size must be known to resolve a Range header")
    m = _RANGE_RE.match(header)
    if not m:
        raise ValueError(f"unsupported Range header: {header!r}")
    first, last = m.group(1), m.group(2)
    if first == "" and last == "":
        raise ValueError(f"empty Range: {header!r}")
    if first == "":  # suffix form: last N bytes
        n = int(last)
        if n <= 0:
            raise ValueError("zero-length suffix range")
        n = min(n, total)
        return Range(total - n, n)
    start = int(first)
    if start >= total > 0:
        raise ValueError(f"range start {start} beyond size {total}")
    end = int(last) if last else total - 1
    end = min(end, total - 1)
    if end < start:
        raise ValueError(f"inverted range: {header!r}")
    return Range(start, end - start + 1)


def parse_range_spec(spec: str) -> Range:
    """Parse a user-facing ``start-end`` spec (dfget --range), end inclusive."""
    m = re.match(r"^(\d+)-(\d+)$", spec.strip())
    if not m:
        raise ValueError(f"invalid range spec {spec!r}, want start-end")
    start, end = int(m.group(1)), int(m.group(2))
    if end < start:
        raise ValueError(f"inverted range spec: {spec!r}")
    return Range(start, end - start + 1)
