"""Tiny shared statistics helpers (observability consumers)."""

from __future__ import annotations

from typing import Sequence


def quantile(sorted_vals: Sequence, q: float) -> float:
    """Nearest-rank quantile over an ALREADY-SORTED sequence; 0.0 when
    empty. Shared by loophealth's /debug/loop summaries and dftrace's stage
    table so the p50/p95 figures the two print always agree."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * (len(sorted_vals) - 1))))
    return float(sorted_vals[idx])
