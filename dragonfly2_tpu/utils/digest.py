"""Digest parse / compute / verify in ``algo:hex`` form.

Parity with reference pkg/digest (md5/sha1/sha256, ``md5:xxx`` string format,
used for piece validation in client/daemon/storage and task metadata).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import BinaryIO, Iterable

ALGORITHMS = ("sha256", "sha1", "md5", "sha512", "crc32")

_HEX_LEN = {"md5": 32, "sha1": 40, "sha256": 64, "sha512": 128, "crc32": 8}


class InvalidDigestError(ValueError):
    pass


@dataclass(frozen=True, slots=True)
class Digest:
    algorithm: str
    encoded: str

    def __str__(self) -> str:
        return f"{self.algorithm}:{self.encoded}"

    def verify_bytes(self, data: bytes) -> bool:
        return compute(self.algorithm, [data]).encoded == self.encoded


def parse(s: str) -> Digest:
    algo, sep, enc = s.partition(":")
    if not sep or algo not in ALGORITHMS:
        raise InvalidDigestError(f"invalid digest string: {s!r}")
    enc = enc.lower()
    want = _HEX_LEN[algo]
    if len(enc) != want or any(c not in "0123456789abcdef" for c in enc):
        raise InvalidDigestError(f"invalid {algo} hex (want {want} chars): {s!r}")
    return Digest(algo, enc)


def _hasher(algorithm: str):
    if algorithm == "crc32":
        import zlib

        class _CRC32:
            def __init__(self) -> None:
                self.v = 0

            def update(self, data: bytes) -> None:
                self.v = zlib.crc32(data, self.v)

            def hexdigest(self) -> str:
                return f"{self.v:08x}"

        return _CRC32()
    if algorithm not in ALGORITHMS:
        raise InvalidDigestError(f"unsupported algorithm: {algorithm}")
    return hashlib.new(algorithm)


def compute(algorithm: str, chunks: Iterable[bytes]) -> Digest:
    h = _hasher(algorithm)
    for chunk in chunks:
        h.update(chunk)
    return Digest(algorithm, h.hexdigest())


def compute_file(algorithm: str, f: BinaryIO, *, bufsize: int = 1 << 20) -> Digest:
    h = _hasher(algorithm)
    while True:
        chunk = f.read(bufsize)
        if not chunk:
            break
        h.update(chunk)
    return Digest(algorithm, h.hexdigest())


def sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()
