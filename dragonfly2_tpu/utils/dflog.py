"""Rotating per-component structured logging.

Parity with reference internal/dflog (logcore.go:25-64, logger.go:33-79):
zap SugaredLoggers per component (core / gc / storage-gc / grpc / job …)
with lumberjack rotation and WithPeer/WithTask context. Python-native here:
stdlib logging with per-component RotatingFileHandlers under one log dir, a
key=value context formatter, and `with_context` adapters that stamp
peer/task/host ids onto every line a subsystem emits.

Services call setup_logging() at boot (--log-dir / YAML); without a log dir
everything stays on the console exactly as before — file logging is opt-in,
matching the reference's console+file default.
"""

from __future__ import annotations

import logging
import logging.handlers
from pathlib import Path
from typing import Any, Mapping

# component -> logger-name prefixes routed to its file (ref logcore.go's
# CoreLogger / GrpcLogger / GCLogger / StorageGCLogger / JobLogger split)
COMPONENT_PREFIXES: dict[str, tuple[str, ...]] = {
    "core": (),  # fallback for everything unmatched
    "rpc": ("dragonfly2_tpu.rpc",),
    "gc": ("dragonfly2_tpu.utils.gcreg",),
    "storage": ("dragonfly2_tpu.daemon.storage",),
    "scheduler": ("dragonfly2_tpu.scheduler", "scheduler"),
    "daemon": ("dragonfly2_tpu.daemon", "daemon"),
    "manager": ("dragonfly2_tpu.manager", "manager"),
    "trainer": ("dragonfly2_tpu.trainer",),
}

_FORMAT = "%(asctime)s %(levelname)s %(name)s %(message)s"


class _ComponentFilter(logging.Filter):
    """Route records to exactly one component file: the longest matching
    prefix wins; `core` takes what nothing else claimed."""

    def __init__(self, component: str):
        super().__init__()
        self.component = component

    def filter(self, record: logging.LogRecord) -> bool:
        best = "core"
        best_len = -1
        for comp, prefixes in COMPONENT_PREFIXES.items():
            for p in prefixes:
                if record.name.startswith(p) and len(p) > best_len:
                    best, best_len = comp, len(p)
        return best == self.component


def setup_logging(
    log_dir: str | Path | None = None,
    *,
    level: int = logging.INFO,
    max_bytes: int = 4 << 20,
    backups: int = 5,
    console: bool = True,
) -> list[logging.Handler]:
    """Install console + per-component rotating file handlers on the root
    logger (idempotent: previously-installed dflog handlers are replaced)."""
    root = logging.getLogger()
    root.setLevel(level)
    for h in list(root.handlers):
        if getattr(h, "_dflog", False):
            root.removeHandler(h)
            h.close()
    installed: list[logging.Handler] = []
    if console and not any(
        isinstance(h, logging.StreamHandler) and not isinstance(h, logging.FileHandler)
        for h in root.handlers
    ):
        ch = logging.StreamHandler()
        ch.setFormatter(logging.Formatter(_FORMAT))
        ch._dflog = True
        root.addHandler(ch)
        installed.append(ch)
    if log_dir is not None:
        d = Path(log_dir)
        d.mkdir(parents=True, exist_ok=True)
        for component in COMPONENT_PREFIXES:
            fh = logging.handlers.RotatingFileHandler(
                d / f"{component}.log", maxBytes=max_bytes, backupCount=backups
            )
            fh.setFormatter(logging.Formatter(_FORMAT))
            fh.addFilter(_ComponentFilter(component))
            fh._dflog = True
            root.addHandler(fh)
            installed.append(fh)
    return installed


class ContextAdapter(logging.LoggerAdapter):
    """Stamps key=value context onto every line (ref dflog WithPeer /
    WithTask / WithHost: structured peer/task context on each record)."""

    def process(self, msg: Any, kwargs: Mapping[str, Any]):
        ctx = " ".join(f"{k}={v}" for k, v in (self.extra or {}).items())
        return (f"[{ctx}] {msg}", kwargs) if ctx else (msg, kwargs)


def with_context(logger: logging.Logger, **ctx: Any) -> ContextAdapter:
    """`log = with_context(logger, task_id=tid[:12], peer_id=pid)` — every
    later log line carries the ids without repeating them at call sites."""
    short = {
        k: (v[:16] if isinstance(v, str) and len(v) > 16 else v) for k, v in ctx.items()
    }
    return ContextAdapter(logger, short)
