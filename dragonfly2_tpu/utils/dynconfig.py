"""Dynconfig: cached remote config with disk cache, TTL refresh, observers.

Reference equivalent: internal/dynconfig/dynconfig.go:44-78 (generic cached
manager-sourced config; specialized by scheduler/config/dynconfig.go and
client/config/dynconfig_manager.go). Fetch from the manager, persist a disk
cache so services boot while the manager is down, refresh on a TTL, and
notify registered observers on change.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from pathlib import Path
from typing import Awaitable, Callable

logger = logging.getLogger(__name__)

Fetcher = Callable[[], Awaitable[dict]]
Observer = Callable[[dict], None]


class Dynconfig:
    def __init__(
        self,
        fetch: Fetcher,
        *,
        cache_path: str | Path | None = None,
        refresh_interval: float = 60.0,
    ):
        self._fetch = fetch
        self.cache_path = Path(cache_path) if cache_path else None
        self.refresh_interval = refresh_interval
        self._data: dict = {}
        self._observers: list[Observer] = []
        self._task: asyncio.Task | None = None
        self._loaded_at = 0.0

    @property
    def data(self) -> dict:
        return self._data

    def register(self, observer: Observer) -> None:
        """Observer fires on every successful refresh that changes the data."""
        self._observers.append(observer)

    async def load(self) -> dict:
        """Initial load: remote first, disk cache fallback (ref Get path).

        Observers always fire once here — refresh() only notifies on change,
        and consumers wired purely via register() must still see the boot
        config even when it came from the disk cache."""
        notified = False
        try:
            notified = await self.refresh()
        except Exception as e:
            if not self._load_cache():
                raise
            logger.warning("dynconfig: using disk cache, fetch failed: %s", e)
        if not notified:
            self._notify()
        return self._data

    async def refresh(self) -> bool:
        """Fetch; returns True when the config changed."""
        data = await self._fetch()
        self._loaded_at = time.time()
        if data == self._data:
            return False
        self._data = data
        self._store_cache()
        self._notify()
        return True

    def _notify(self) -> None:
        for obs in self._observers:
            try:
                obs(self._data)
            except Exception:
                logger.exception("dynconfig observer failed")

    def _load_cache(self) -> bool:
        if self.cache_path is None or not self.cache_path.exists():
            return False
        try:
            self._data = json.loads(self.cache_path.read_text())
            return True
        except (json.JSONDecodeError, OSError):
            return False

    def _store_cache(self) -> None:
        if self.cache_path is None:
            return
        self.cache_path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self.cache_path.with_suffix(".tmp")
        tmp.write_text(json.dumps(self._data))
        tmp.replace(self.cache_path)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.refresh_interval)
            try:
                await self.refresh()
            except Exception as e:
                logger.warning("dynconfig refresh failed: %s", e)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
