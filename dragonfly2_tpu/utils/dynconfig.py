"""Dynconfig: cached remote config with disk cache, TTL refresh, observers.

Reference equivalent: internal/dynconfig/dynconfig.go:44-78 (generic cached
manager-sourced config; specialized by scheduler/config/dynconfig.go and
client/config/dynconfig_manager.go). Fetch from the manager, persist a disk
cache so services boot while the manager is down, refresh on a TTL, and
notify registered observers on change.

Manager-outage autonomy (ISSUE 17): the disk cache is STALENESS-STAMPED —
`{"data": ..., "saved_at": unix_time}` — so a consumer serving through a
manager blackout can say (and export) exactly how old its last-good snapshot
is, instead of presenting cached config as fresh. `staleness_s()` answers
the age; `from_cache` says whether the current data ever confirmed against
the manager this process lifetime. The module-level `store_snapshot` /
`load_snapshot` helpers share the same stamped format with other last-good
caches (the daemon's scheduler address book).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Awaitable, Callable

logger = logging.getLogger(__name__)

Fetcher = Callable[[], Awaitable[dict]]
Observer = Callable[[dict], None]


@dataclass
class Snapshot:
    """One staleness-stamped last-good cache entry."""

    data: dict
    saved_at: float  # unix time the data was last confirmed fresh

    def staleness_s(self, now: float | None = None) -> float:
        now = now if now is not None else time.time()
        return max(0.0, now - self.saved_at)


def store_snapshot(path: str | Path, data: dict) -> None:
    """Atomically persist `data` with a freshness stamp (tmp + rename)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps({"data": data, "saved_at": time.time()}))
    tmp.replace(path)


def load_snapshot(path: str | Path) -> Snapshot | None:
    """Read a stamped snapshot; a legacy plain-dict cache (pre-stamp format)
    still loads, aged by its file mtime. None on missing/corrupt."""
    path = Path(path)
    if not path.exists():
        return None
    try:
        raw = json.loads(path.read_text())
    except (json.JSONDecodeError, OSError):
        return None
    if not isinstance(raw, dict):
        return None
    if "data" in raw and "saved_at" in raw:
        data = raw["data"]
        if not isinstance(data, dict):
            return None
        try:
            return Snapshot(data, float(raw["saved_at"]))
        except (TypeError, ValueError):
            return None
    try:
        mtime = path.stat().st_mtime
    except OSError:
        mtime = 0.0
    return Snapshot(raw, mtime)


class Dynconfig:
    def __init__(
        self,
        fetch: Fetcher,
        *,
        cache_path: str | Path | None = None,
        refresh_interval: float = 60.0,
    ):
        self._fetch = fetch
        self.cache_path = Path(cache_path) if cache_path else None
        self.refresh_interval = refresh_interval
        self._data: dict = {}
        self._observers: list[Observer] = []
        self._task: asyncio.Task | None = None
        self._loaded_at = 0.0  # when _data was last confirmed fresh
        # True while _data came from the disk cache and has NOT been
        # confirmed against the manager this process lifetime
        self.from_cache = False

    @property
    def data(self) -> dict:
        return self._data

    def staleness_s(self, now: float | None = None) -> float | None:
        """Age of the current config: seconds since the last successful
        manager fetch, or — when serving from the disk cache — since that
        cache was written. None before any load succeeded at all."""
        if not self._loaded_at:
            return None
        now = now if now is not None else time.time()
        return max(0.0, now - self._loaded_at)

    def register(self, observer: Observer) -> None:
        """Observer fires on every successful refresh that changes the data."""
        self._observers.append(observer)

    async def load(self) -> dict:
        """Initial load: remote first, disk cache fallback (ref Get path).

        Observers always fire once here — refresh() only notifies on change,
        and consumers wired purely via register() must still see the boot
        config even when it came from the disk cache."""
        notified = False
        try:
            notified = await self.refresh()
        except Exception as e:
            if not self._load_cache():
                raise
            logger.warning(
                "dynconfig: using disk cache (age %.0fs), fetch failed: %s",
                self.staleness_s() or 0.0, e,
            )
        if not notified:
            self._notify()
        return self._data

    async def refresh(self) -> bool:
        """Fetch; returns True when the config changed."""
        data = await self._fetch()
        self._loaded_at = time.time()
        self.from_cache = False
        if data == self._data:
            return False
        self._data = data
        self._store_cache()
        self._notify()
        return True

    def _notify(self) -> None:
        for obs in self._observers:
            try:
                obs(self._data)
            except Exception:
                logger.exception("dynconfig observer failed")

    def _load_cache(self) -> bool:
        if self.cache_path is None:
            return False
        snap = load_snapshot(self.cache_path)
        if snap is None:
            return False
        self._data = snap.data
        self._loaded_at = snap.saved_at
        self.from_cache = True
        return True

    def _store_cache(self) -> None:
        if self.cache_path is None:
            return
        store_snapshot(self.cache_path, self._data)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.refresh_interval)
            try:
                await self.refresh()
            except Exception as e:
                logger.warning("dynconfig refresh failed: %s", e)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
