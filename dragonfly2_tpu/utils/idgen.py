"""Task / peer / host ID generation.

Parity with reference pkg/idgen/task_id.go:37-95 and peer_id.go:27-37:
task IDs are content-addressed (sha256 over the URL with filtered query
params plus download-affecting metadata) so that every peer asking for the
same object lands on the same task; peer IDs are host-scoped and unique per
download attempt; seed peers carry a marker suffix so schedulers can
distinguish them without a lookup.
"""

from __future__ import annotations

import hashlib
import os
import socket
from urllib.parse import parse_qsl, urlencode, urlsplit, urlunsplit

_SEED_PEER_SUFFIX = "_seed"


def filter_query(url: str, filters: tuple[str, ...] | list[str] = ()) -> str:
    """Drop the named query parameters from *url* (order-preserving).

    Used so that signed URLs (expiry tokens etc.) map to one task identity,
    mirroring the reference's filtered-query task keying.
    """
    if not filters:
        return url
    parts = urlsplit(url)
    drop = set(filters)
    params = parse_qsl(parts.query, keep_blank_values=True)
    kept = [(k, v) for k, v in params if k not in drop]
    if len(kept) == len(params):
        # No-op filter lists must not change the task identity: a re-encode
        # can alter equivalent encodings (%20 vs +) and split the task key.
        return url
    return urlunsplit(parts._replace(query=urlencode(kept)))


def task_id(
    url: str,
    *,
    filters: tuple[str, ...] | list[str] = (),
    tag: str = "",
    application: str = "",
    digest: str = "",
    piece_range: str = "",
) -> str:
    """Content-addressed task ID: sha256 over the filtered URL + meta."""
    h = hashlib.sha256()
    h.update(filter_query(url, filters).encode())
    for part in (tag, application, digest, piece_range):
        h.update(b"\x00")
        h.update(part.encode())
    return h.hexdigest()


def persistent_cache_task_id(
    content_digest: str, tag: str = "", application: str = "", piece_size: int = 0
) -> str:
    """Task ID for imported cache objects, keyed by content digest not URL.

    piece_size is part of the identity: the id alone determines the task's
    piece geometry cluster-wide, so two publishers of identical bytes with
    different piece sizes must land on DIFFERENT tasks — merging them would
    hand children one index-keyed digest map spanning two geometries."""
    h = hashlib.sha256()
    h.update(content_digest.encode())
    h.update(b"\x00")
    h.update(tag.encode())
    h.update(b"\x00")
    h.update(application.encode())
    h.update(b"\x00")
    h.update(str(piece_size).encode())
    return h.hexdigest()


def host_id(hostname: str, port: int | None = None) -> str:
    """Stable host identity (reference pkg/idgen/host_id.go)."""
    if port is None:
        return hostname
    return f"{hostname}-{port}"


def peer_id(ip: str | None = None, hostname: str | None = None, *, seed: bool = False) -> str:
    """Unique per-attempt peer ID: ip-hostname-random[(_seed)]."""
    ip = ip or local_ip()
    hostname = hostname or socket.gethostname()
    rand = os.urandom(8).hex()
    suffix = _SEED_PEER_SUFFIX if seed else ""
    return f"{ip}-{hostname}-{rand}{suffix}"


def is_seed_peer_id(pid: str) -> bool:
    return pid.endswith(_SEED_PEER_SUFFIX)


def local_ip() -> str:
    """Best-effort non-loopback IP; falls back to 127.0.0.1 (offline-safe)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packet is actually sent
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
