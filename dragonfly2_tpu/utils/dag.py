"""Generic thread-safe DAG.

Parity with reference pkg/graph/dag/dag.go:48-78: vertices with typed values,
edge add with cycle rejection, and *random vertex sampling* — the scheduler's
candidate-parent filter draws <=40 random peers from the task DAG per round
(reference scheduler/scheduling/scheduling.go candidate filter).
"""

from __future__ import annotations

import random
import threading
from typing import Generic, Iterator, TypeVar

V = TypeVar("V")


class DAGError(Exception):
    pass


class VertexNotFound(DAGError):
    pass


class VertexExists(DAGError):
    pass


class CycleError(DAGError):
    pass


class Vertex(Generic[V]):
    __slots__ = ("id", "value", "parents", "children")

    def __init__(self, vid: str, value: V):
        self.id = vid
        self.value = value
        self.parents: set[str] = set()
        self.children: set[str] = set()

    def in_degree(self) -> int:
        return len(self.parents)

    def out_degree(self) -> int:
        return len(self.children)


class DAG(Generic[V]):
    def __init__(self) -> None:
        self._v: dict[str, Vertex[V]] = {}
        self._lock = threading.RLock()
        # vertex-list snapshot for the per-round random sample: rebuilt only
        # when vertices change, not O(N) per scheduling round
        self._vlist: list[Vertex[V]] | None = None

    def __len__(self) -> int:
        return len(self._v)

    def __contains__(self, vid: str) -> bool:
        return vid in self._v

    def add_vertex(self, vid: str, value: V) -> None:
        with self._lock:
            if vid in self._v:
                raise VertexExists(vid)
            vertex = self._v[vid] = Vertex(vid, value)
            # append-in-place instead of invalidating: a growing swarm adds a
            # vertex per registration, and a None'd snapshot costs an O(N)
            # rebuild inside the NEXT scheduling round's candidate draw —
            # O(N²) across a flash crowd (measured by the swarm simulator at
            # 10^5 peers). Deletes still invalidate (rarer, and removal from
            # a list is O(N) anyway).
            if self._vlist is not None:
                self._vlist.append(vertex)

    def delete_vertex(self, vid: str) -> None:
        with self._lock:
            vertex = self._v.pop(vid, None)
            if vertex is None:
                return
            self._vlist = None
            for p in vertex.parents:
                self._v[p].children.discard(vid)
            for c in vertex.children:
                self._v[c].parents.discard(vid)

    def vertex(self, vid: str) -> Vertex[V]:
        try:
            return self._v[vid]
        except KeyError:
            raise VertexNotFound(vid) from None

    def vertices(self) -> dict[str, Vertex[V]]:
        with self._lock:
            return dict(self._v)

    def values(self) -> Iterator[V]:
        with self._lock:
            vs = list(self._v.values())
        return (v.value for v in vs)

    def first_match(self, pred) -> V | None:
        """First value satisfying pred, scanned under the lock WITHOUT the
        values() snapshot copy — the has-available-peer probe runs on every
        registration and usually matches the first vertex; copying 10^5
        vertices first made it O(N) per register (swarm-simulator finding)."""
        with self._lock:
            for v in self._v.values():
                if pred(v.value):
                    return v.value
        return None

    def add_edge(self, from_id: str, to_id: str) -> None:
        """Add from->to; rejects self-loops and edges that would close a cycle."""
        with self._lock:
            if from_id == to_id:
                raise CycleError(f"self edge {from_id}")
            src, dst = self.vertex(from_id), self.vertex(to_id)
            if to_id in src.children:
                return
            if self._reachable(to_id, from_id):
                raise CycleError(f"{from_id}->{to_id} closes a cycle")
            src.children.add(to_id)
            dst.parents.add(from_id)

    def delete_edge(self, from_id: str, to_id: str) -> None:
        with self._lock:
            if from_id in self._v:
                self._v[from_id].children.discard(to_id)
            if to_id in self._v:
                self._v[to_id].parents.discard(from_id)

    def delete_in_edges(self, vid: str) -> None:
        with self._lock:
            vertex = self.vertex(vid)
            for p in vertex.parents:
                self._v[p].children.discard(vid)
            vertex.parents.clear()

    def can_add_edge(self, from_id: str, to_id: str) -> bool:
        with self._lock:
            if from_id == to_id or from_id not in self._v or to_id not in self._v:
                return False
            if to_id in self._v[from_id].children:
                return False
            return not self._reachable(to_id, from_id)

    def _reachable(self, start: str, target: str) -> bool:
        stack, seen = [start], {start}
        while stack:
            cur = stack.pop()
            if cur == target:
                return True
            for c in self._v[cur].children:
                if c not in seen:
                    seen.add(c)
                    stack.append(c)
        return False

    def lineage(self, vid: str) -> set[str]:
        """All ancestors + descendants of vid (used by scheduling filters)."""
        out: set[str] = set()
        with self._lock:
            for attr in ("parents", "children"):
                stack = list(getattr(self.vertex(vid), attr))
                while stack:
                    cur = stack.pop()
                    if cur in out:
                        continue
                    out.add(cur)
                    stack.extend(getattr(self._v[cur], attr))
        return out

    def parent_values(self, vid: str) -> list[V]:
        """Values of vid's direct parents, snapshotted under the DAG lock:
        callers on other threads (the scheduler's round-dispatcher workers)
        must never iterate a vertex's live parent set while add_edge /
        delete_vertex mutate it."""
        with self._lock:
            return [self._v[p].value for p in self.vertex(vid).parents]

    def child_values(self, vid: str) -> list[V]:
        """Values of vid's direct children; see parent_values."""
        with self._lock:
            return [self._v[c].value for c in self.vertex(vid).children]

    def random_vertices(self, n: int, rng: random.Random | None = None) -> list[Vertex[V]]:
        """Sample up to n distinct vertices uniformly (scheduler candidate draw)."""
        with self._lock:
            if self._vlist is None:
                self._vlist = list(self._v.values())
            vs = self._vlist
        if n >= len(vs):
            return list(vs)
        return (rng or random).sample(vs, n)

    def source_vertices(self) -> list[Vertex[V]]:
        with self._lock:
            return [v for v in self._v.values() if not v.parents]

    def sink_vertices(self) -> list[Vertex[V]]:
        with self._lock:
            return [v for v in self._v.values() if not v.children]
