"""Plugin loading for evaluator / searcher / source extensions.

Parity with reference internal/dfplugin/dfplugin.go + the evaluator plugin
hook (scheduler/scheduling/evaluator/plugin.go:1-39): the reference dlopens
Go .so plugins from a plugin dir; the Python-native equivalent is an import
path — ``"pkg.module:attr"`` — resolved at boot. A factory attr is CALLED
(with optional kwargs), anything else is used as-is.

Specs appear in two places:
  * evaluator: ``new_evaluator("plugin:pkg.mod:make_evaluator")``
  * source clients: DRAGONFLY_SOURCE_PLUGINS env =
    ``"scheme=pkg.mod:factory,scheme2=..."`` — each factory returns a
    ResourceClient registered for its scheme

Loaded objects are duck-checked against the interface they plug into, so a
typo'd spec fails at boot with a clear error, not at first use.
"""

from __future__ import annotations

import importlib
import logging
from typing import Any, Iterable

logger = logging.getLogger(__name__)


class PluginError(Exception):
    pass


def load_object(spec: str, *, call_factories: bool = True, **factory_kwargs: Any) -> Any:
    """Resolve "pkg.module:attr" → the attr (called if callable)."""
    module_path, sep, attr = spec.partition(":")
    if not sep or not module_path or not attr:
        raise PluginError(f"bad plugin spec {spec!r}: want 'pkg.module:attr'")
    try:
        module = importlib.import_module(module_path)
    except ImportError as e:
        raise PluginError(f"plugin module {module_path!r} not importable: {e}") from e
    try:
        obj = getattr(module, attr)
    except AttributeError as e:
        raise PluginError(f"plugin {module_path!r} has no attribute {attr!r}") from e
    if call_factories and callable(obj):
        try:
            obj = obj(**factory_kwargs)
        except Exception as e:
            raise PluginError(f"plugin factory {spec!r} raised: {e}") from e
    return obj


def require_methods(obj: Any, methods: Iterable[str], *, spec: str, kind: str) -> Any:
    """Duck-type interface check with a boot-time error message."""
    missing = [m for m in methods if not callable(getattr(obj, m, None))]
    if missing:
        raise PluginError(
            f"{kind} plugin {spec!r} ({type(obj).__name__}) lacks required "
            f"methods: {missing}"
        )
    return obj


def parse_plugin_map(raw: str) -> dict[str, str]:
    """"key=pkg.mod:attr,key2=..." → {key: spec} (the env-var form)."""
    out: dict[str, str] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, spec = part.partition("=")
        if not sep or not key or not spec:
            raise PluginError(f"bad plugin map entry {part!r}: want 'key=pkg.mod:attr'")
        out[key.strip()] = spec.strip()
    return out
