"""Piece bitmap.

Parity with the reference's piece Bitmap (client/daemon/peer, pkg/container
bitset helpers): tracks which pieces of a task are finished; cheap union /
difference drives "which pieces can this parent give me that I don't have".
"""

from __future__ import annotations

from typing import Iterator


class Bitset:
    __slots__ = ("_bits", "_count")

    def __init__(self, bits: int = 0):
        self._bits = bits
        self._count = bits.bit_count()

    @classmethod
    def from_indices(cls, indices) -> "Bitset":
        b = 0
        for i in indices:
            b |= 1 << i
        return cls(b)

    def set(self, i: int) -> bool:
        """Set bit i; returns True if it was newly set."""
        mask = 1 << i
        if self._bits & mask:
            return False
        self._bits |= mask
        self._count += 1
        return True

    def clear(self, i: int) -> None:
        mask = 1 << i
        if self._bits & mask:
            self._bits &= ~mask
            self._count -= 1

    def test(self, i: int) -> bool:
        return bool(self._bits >> i & 1)

    def count(self) -> int:
        return self._count

    def is_empty(self) -> bool:
        return self._bits == 0

    def indices(self) -> Iterator[int]:
        bits, i = self._bits, 0
        while bits:
            if bits & 1:
                yield i
            bits >>= 1
            i += 1

    def missing_until(self, total: int) -> Iterator[int]:
        """Indices in [0, total) not set — the pieces still to download."""
        for i in range(total):
            if not self.test(i):
                yield i

    def difference(self, other: "Bitset") -> "Bitset":
        return Bitset(self._bits & ~other._bits)

    def union(self, other: "Bitset") -> "Bitset":
        return Bitset(self._bits | other._bits)

    def intersection(self, other: "Bitset") -> "Bitset":
        return Bitset(self._bits & other._bits)

    def copy(self) -> "Bitset":
        return Bitset(self._bits)

    def to_int(self) -> int:
        return self._bits

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Bitset) and self._bits == other._bits

    def __repr__(self) -> str:
        return f"Bitset({sorted(self.indices())})"
