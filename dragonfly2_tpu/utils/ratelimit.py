"""Token-bucket rate limiting.

Parity with reference rate limits: per-peer download 512 MB/s, total
download/upload 1 GiB/s (client/config/constants.go:45-47) and the 10k QPS /
20k burst gRPC server limiter (pkg/rpc/scheduler/server/server.go:43-44).
"""

from __future__ import annotations

import asyncio
import time


class TokenBucket:
    """Async token bucket. rate = tokens/sec, burst = bucket capacity."""

    def __init__(self, rate: float, burst: float | None = None):
        if rate <= 0:
            raise ValueError("rate must be > 0")
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else rate)
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = asyncio.Lock()

    # Lock discipline (dflint DF023 suppressions below): _tokens is mutated
    # both under the asyncio lock (acquire, to serialize WAITERS across its
    # sleeps) and without it (the sync paths: try_acquire/set_rate run on the
    # loop thread with no await inside, so they are atomic w.r.t. coroutine
    # interleaving). acquire() re-checks the balance after every sleep, so
    # tokens taken by a sync caller mid-wait extend the wait instead of racing.

    def _refill(self) -> None:
        now = time.monotonic()
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)  # dflint: disable=DF023 sync path, no await between read and write
        self._last = now

    def try_acquire(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n  # dflint: disable=DF023 sync path, no await between read and write
            return True
        return False

    async def acquire(self, n: float = 1.0) -> None:
        if n > self.burst:
            # A request larger than the bucket drains in chunks.
            remaining = n
            while remaining > 0:
                chunk = min(remaining, self.burst)
                await self.acquire(chunk)
                remaining -= chunk
            return
        async with self._lock:
            # Loop instead of clamping: tokens taken by try_acquire() during the
            # sleep must extend the wait, not be forgiven as debt. `take`
            # re-clamps to the CURRENT burst each pass — set_rate() may shrink
            # the bucket below n mid-wait (traffic-shaper reallocation) and a
            # fixed n would then never be satisfiable.
            while n > 0:
                self._refill()
                take = min(n, self.burst)
                if self._tokens >= take:
                    self._tokens -= take
                    n -= take
                    continue
                await asyncio.sleep((take - self._tokens) / self.rate)

    def set_rate(self, rate: float, burst: float | None = None) -> None:
        """Retarget the bucket (traffic-shaper reallocation). Accrued tokens
        are settled at the OLD rate first; a waiter inside acquire() picks up
        the new rate on its next loop iteration."""
        if rate <= 0:
            raise ValueError("rate must be > 0")
        self._refill()
        self.rate = float(rate)
        if burst is not None:
            self.burst = float(burst)
            self._tokens = min(self._tokens, self.burst)  # dflint: disable=DF023 sync path, no await between read and write

    @property
    def available(self) -> float:
        self._refill()
        return self._tokens
