"""Token-bucket rate limiting.

Parity with reference rate limits: per-peer download 512 MB/s, total
download/upload 1 GiB/s (client/config/constants.go:45-47) and the 10k QPS /
20k burst gRPC server limiter (pkg/rpc/scheduler/server/server.go:43-44).
"""

from __future__ import annotations

import asyncio
import time


class TokenBucket:
    """Async token bucket. rate = tokens/sec, burst = bucket capacity."""

    def __init__(self, rate: float, burst: float | None = None):
        if rate <= 0:
            raise ValueError("rate must be > 0")
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else rate)
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = asyncio.Lock()

    def _refill(self) -> None:
        now = time.monotonic()
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
        self._last = now

    def try_acquire(self, n: float = 1.0) -> bool:
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    async def acquire(self, n: float = 1.0) -> None:
        if n > self.burst:
            # A request larger than the bucket drains in chunks.
            remaining = n
            while remaining > 0:
                chunk = min(remaining, self.burst)
                await self.acquire(chunk)
                remaining -= chunk
            return
        async with self._lock:
            # Loop instead of clamping: tokens taken by try_acquire() during the
            # sleep must extend the wait, not be forgiven as debt.
            while True:
                self._refill()
                if self._tokens >= n:
                    self._tokens -= n
                    return
                await asyncio.sleep((n - self._tokens) / self.rate)

    @property
    def available(self) -> float:
        self._refill()
        return self._tokens
