"""Delta clock for federation gossip: ONE implementation of the
stamp-on-mutate / tombstone-on-delete / cap-and-prune protocol that
NetworkTopology and BandwidthHistory both gossip with — two hand-kept copies
of the same semantics would silently desynchronize the moment one grew a
change (per-origin purge, TTL, ...) the other missed.

The owner stamps every LOCAL mutation with its store's post-bump version
counter; `since(w)` enumerates keys a peer with watermark `w` has not seen
(the owner decides per key whether that is live stats or a tombstone by
looking at its own store); deletes are stamped via `stamp_tombstone`, and
`prune()` bounds retained tombstone stamps at `tombstone_cap`, dropping the
OLDEST — a peer that last synced before a pruned stamp keeps the stale
remote entry until that key churns again (the bounded-memory tradeoff;
regularly-syncing peers are always far past the prune horizon).

Complexity contract (10^5-peer swarm-simulator finding): `stamp` re-inserts
the key so the dict's insertion order IS ascending stamp order, which makes
`since(w)` O(keys changed past w) via reverse iteration — the enumeration
now costs what the payload does. The original scanned EVERY stamp per
gossip exchange and EVERY stamp again per prune, which turned steady-state
gossip ticks and host churn into the cluster's top two CPU items at scale.
"""

from __future__ import annotations

from typing import Callable, Hashable, Iterator

DEFAULT_TOMBSTONE_CAP = 4096


class DeltaClock:
    __slots__ = ("seq", "dead", "tombstone_cap")

    def __init__(self, tombstone_cap: int = DEFAULT_TOMBSTONE_CAP):
        # INVARIANT: iteration order of `seq` is ascending stamp order —
        # stamp() pops before it reinserts, so a re-stamped key moves to the
        # end. since() depends on this to enumerate from the newest side.
        self.seq: dict[Hashable, int] = {}
        # keys whose current stamp is a tombstone (stamped by
        # stamp_tombstone, not yet pruned, not re-stamped live)
        self.dead: set[Hashable] = set()
        self.tombstone_cap = tombstone_cap

    def stamp(self, key: Hashable, version: int) -> None:
        """Stamp a LIVE mutation of `key` (re-creation clears tombstone)."""
        self.seq.pop(key, None)
        self.seq[key] = version
        self.dead.discard(key)

    def stamp_tombstone(self, key: Hashable, version: int) -> None:
        """Stamp a DELETE of `key` — gossiped as a tombstone until pruned."""
        self.seq.pop(key, None)
        self.seq[key] = version
        self.dead.add(key)

    def since(self, watermark: int) -> Iterator[Hashable]:
        """Keys mutated after `watermark`, O(keys changed): walks from the
        newest stamp backward and stops at the first at-or-below the
        watermark (insertion order is ascending stamp order)."""
        out = []
        for key in reversed(self.seq):
            if self.seq[key] <= watermark:
                break
            out.append(key)
        return reversed(out)

    def prune(self, is_live: Callable[[Hashable], bool] | None = None) -> None:
        """Drop the oldest dead-key stamps past the cap (live keys keep
        their stamp for the key's lifetime; tombstones exist only to gossip
        deletes). O(1) under the cap; O(scan to the excess) above it.
        `is_live` is accepted for compatibility and used as a cross-check
        filter when provided (a key it calls live is never pruned).

        Amortization: when the cap trips, prune 25% BELOW it — the scan
        walks live-key stamps older than the tombstones it wants, and
        pruning exactly one tombstone per delete re-paid that walk on every
        subsequent delete (swarm-simulator finding). The retained-tombstone
        bound stays tombstone_cap exactly; the hysteresis only buys the
        next cap//4 deletes scan-free."""
        if len(self.dead) <= self.tombstone_cap:
            return
        excess = len(self.dead) - (self.tombstone_cap - self.tombstone_cap // 4)
        # seq order is ascending stamp order: the first dead keys seen ARE
        # the oldest tombstones
        doomed = []
        for key in self.seq:
            if key in self.dead and (is_live is None or not is_live(key)):
                doomed.append(key)
                if len(doomed) >= excess:
                    break
        for key in doomed:
            del self.seq[key]
            self.dead.discard(key)

    def __len__(self) -> int:
        return len(self.seq)
