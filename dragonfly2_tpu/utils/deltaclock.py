"""Delta clock for federation gossip: ONE implementation of the
stamp-on-mutate / tombstone-on-delete / cap-and-prune protocol that
NetworkTopology and BandwidthHistory both gossip with — two hand-kept copies
of the same semantics would silently desynchronize the moment one grew a
change (per-origin purge, TTL, ...) the other missed.

The owner stamps every LOCAL mutation with its store's post-bump version
counter; `since(w)` enumerates keys a peer with watermark `w` has not seen
(the owner decides per key whether that is live stats or a tombstone by
looking at its own store); `prune(is_live)` bounds retained tombstone stamps
at `tombstone_cap`, dropping the OLDEST — a peer that last synced before a
pruned stamp keeps the stale remote entry until that key churns again (the
bounded-memory tradeoff; regularly-syncing peers are always far past the
prune horizon).
"""

from __future__ import annotations

from typing import Callable, Hashable, Iterator

DEFAULT_TOMBSTONE_CAP = 4096


class DeltaClock:
    __slots__ = ("seq", "tombstone_cap")

    def __init__(self, tombstone_cap: int = DEFAULT_TOMBSTONE_CAP):
        self.seq: dict[Hashable, int] = {}
        self.tombstone_cap = tombstone_cap

    def stamp(self, key: Hashable, version: int) -> None:
        self.seq[key] = version

    def since(self, watermark: int) -> Iterator[Hashable]:
        """Keys mutated after `watermark` (O(all stamps) scan; the PAYLOAD
        is O(changed), which is the property the gossip depends on)."""
        for key, seq in self.seq.items():
            if seq > watermark:
                yield key

    def prune(self, is_live: Callable[[Hashable], bool]) -> None:
        """Drop the oldest dead-key stamps past the cap (live keys keep
        their stamp for the key's lifetime; tombstones exist only to gossip
        deletes)."""
        dead = [k for k in self.seq if not is_live(k)]
        if len(dead) <= self.tombstone_cap:
            return
        dead.sort(key=self.seq.__getitem__)
        for k in dead[: len(dead) - self.tombstone_cap]:
            del self.seq[k]

    def __len__(self) -> int:
        return len(self.seq)
