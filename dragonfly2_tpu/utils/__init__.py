"""Shared infrastructure kernel (reference pkg/ + internal/ equivalents)."""
