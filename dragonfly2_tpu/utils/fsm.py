"""Minimal finite-state machine.

Parity with the looplab/fsm usage in reference scheduler/resource/peer.go:50-243
and task.go: every peer/task/host transition is gated by an FSM so illegal
control-plane transitions surface as errors instead of corrupt state.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable


class TransitionError(Exception):
    def __init__(self, event: str, state: str):
        super().__init__(f"event {event!r} inappropriate in current state {state!r}")
        self.event = event
        self.state = state


class Event:
    __slots__ = ("name", "src", "dst")

    def __init__(self, name: str, src: Iterable[str], dst: str):
        self.name = name
        self.src = frozenset([src] if isinstance(src, str) else src)
        self.dst = dst


class FSM:
    """Tiny synchronous FSM with per-event and wildcard callbacks."""

    def __init__(
        self,
        initial: str,
        events: Iterable[Event],
        callbacks: dict[str, Callable[["FSM", str, str, str], None]] | None = None,
    ):
        self._state = initial
        self._events: dict[str, Event] = {e.name: e for e in events}
        self._callbacks = callbacks or {}
        self._lock = threading.RLock()

    @property
    def current(self) -> str:
        return self._state

    def is_(self, state: str) -> bool:
        return self._state == state

    def can(self, event: str) -> bool:
        e = self._events.get(event)
        return e is not None and self._state in e.src

    def fire(self, event: str) -> None:
        with self._lock:
            e = self._events.get(event)
            if e is None or self._state not in e.src:
                raise TransitionError(event, self._state)
            src = self._state
            self._state = e.dst
            cb = self._callbacks.get(event) or self._callbacks.get("*")
            if cb is not None:
                cb(self, event, src, e.dst)
