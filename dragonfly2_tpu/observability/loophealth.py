"""Event-loop health telemetry: scheduled-callback lag + worker utilization.

ROADMAP #1's open claim is HOST-shaped: "the loop's per-round glue plus one
worker saturate the GIL". This module is the direct instrument for it — a
monitor that schedules a callback every `interval` seconds and records how
LATE the loop actually ran it (drift = observed - expected). On a healthy
loop the lag is microseconds; a loop starved by GIL-holding threads, a
blocking call, or simple overload shows up as a fat lag tail long before
anything times out. The same monitor samples the round dispatcher's worker
utilization (busy/total) so "the loop is lagging AND the workers are idle"
vs "both are pegged" is answerable from one endpoint.

Everything lands in the default metrics registry as histograms
(`dragonfly_loop_lag_seconds`, `dragonfly_loop_dispatcher_utilization`) plus
an in-memory ring served by GET /debug/loop (observability.server) as
p50/p95/max summaries. Cost: one loop callback per interval (default 250 ms
= 4 clock reads/s), nothing on any hot path.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Any, Callable, Optional

from dragonfly2_tpu.observability.metrics import MetricsRegistry, default_registry
from dragonfly2_tpu.utils.stats import quantile as _quantile

DEFAULT_INTERVAL_S = 0.25
_RING = 512  # ~2 min of samples at the default cadence


class LoopHealthMonitor:
    """Samples event-loop scheduling lag (and, when a probe is attached,
    dispatcher-worker utilization) on a fixed cadence.

    `dispatcher_probe` is any zero-arg callable returning (busy, total)
    worker counts — `monitor.attach_dispatcher(d)` wires a RoundDispatcher's
    `busy`/`workers` pair. The probe runs on the event loop, so it may read
    loop-owned state without locks.
    """

    def __init__(
        self,
        *,
        interval: float = DEFAULT_INTERVAL_S,
        registry: MetricsRegistry | None = None,
        ring: int = _RING,
    ):
        self.interval = interval
        reg = registry or default_registry()
        # lag buckets: µs-grade healthy ticks up to multi-second stalls
        self._lag_hist = reg.histogram(
            "lag_seconds",
            "observed minus expected delay of a scheduled loop callback",
            subsystem="loop",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
        )
        self._util_hist = reg.histogram(
            "dispatcher_utilization",
            "fraction of round-dispatcher workers busy at sample time",
            subsystem="loop",
            buckets=(0.0, 0.25, 0.5, 0.75, 0.9, 1.0),
        )
        self._lag_ring: deque = deque(maxlen=ring)
        self._util_ring: deque = deque(maxlen=ring)
        self._dispatcher_probe: Optional[Callable[[], tuple]] = None
        self._handle: Any = None
        self._expected_at = 0.0
        self._started_at = 0.0
        self.samples = 0
        self.max_lag_s = 0.0

    # ---- wiring ----

    def attach_dispatcher(self, dispatcher: Any) -> None:
        """Sample a RoundDispatcher's worker occupancy each tick (any object
        with `busy` and `workers` attributes works)."""
        self._dispatcher_probe = lambda: (dispatcher.busy, dispatcher.workers)

    def start(self) -> None:
        """Begin sampling on the RUNNING loop. Idempotent."""
        if self._handle is not None:
            return
        loop = asyncio.get_running_loop()
        self._started_at = time.monotonic()
        self._expected_at = self._started_at + self.interval
        self._handle = loop.call_later(self.interval, self._tick, loop)

    def stop(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        # drop the probe: the process-wide singleton must not pin a
        # shut-down dispatcher's object graph (Scheduling → pool →
        # evaluator) across in-process restarts; the composition root
        # re-attaches at the next boot
        self._dispatcher_probe = None

    @property
    def running(self) -> bool:
        return self._handle is not None

    # ---- sampling ----

    def _tick(self, loop) -> None:
        now = time.monotonic()
        # drift of THIS callback: the loop promised to run us at
        # _expected_at; everything between then and now is time the loop
        # spent elsewhere (other callbacks, a GIL-holding thread, a stall)
        lag = max(0.0, now - self._expected_at)
        self.samples += 1
        self.max_lag_s = max(self.max_lag_s, lag)
        self._lag_hist.observe(lag)
        self._lag_ring.append(lag)
        if self._dispatcher_probe is not None:
            try:
                busy, total = self._dispatcher_probe()
                util = busy / total if total else 0.0
            except Exception:  # noqa: BLE001 — a dead dispatcher must not kill sampling
                self._dispatcher_probe = None
            else:
                self._util_hist.observe(util)
                self._util_ring.append(util)
        # schedule relative to NOW (not expected): a long stall must cost
        # one fat sample, not a burst of back-to-back catch-up ticks that
        # each read as near-zero lag
        self._expected_at = now + self.interval
        self._handle = loop.call_later(self.interval, self._tick, loop)

    # ---- reporting ----

    def stats(self) -> dict:
        lags = sorted(self._lag_ring)
        out = {
            "running": self.running,
            "interval_s": self.interval,
            "samples": self.samples,
            "uptime_s": round(time.monotonic() - self._started_at, 1)
            if self._started_at
            else 0.0,
            "lag_p50_ms": round(_quantile(lags, 0.50) * 1e3, 3),
            "lag_p95_ms": round(_quantile(lags, 0.95) * 1e3, 3),
            "lag_max_ms": round(self.max_lag_s * 1e3, 3),
        }
        if self._util_ring:
            utils = sorted(self._util_ring)
            out["dispatcher_utilization_p50"] = round(_quantile(utils, 0.50), 3)
            out["dispatcher_utilization_p95"] = round(_quantile(utils, 0.95), 3)
        return out


_default: LoopHealthMonitor | None = None


def default_monitor() -> LoopHealthMonitor:
    """Process-wide monitor (composition roots start it; /debug/loop reads
    it). Created lazily so importing this module costs nothing."""
    global _default
    if _default is None:
        _default = LoopHealthMonitor()
    return _default
