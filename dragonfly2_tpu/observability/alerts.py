"""Declarative SLO alert rules evaluated against the local timeseries rings.

The reference leaves alerting to an external Prometheus Alertmanager; this
is the in-process equivalent: a small rule engine over MetricsRecorder's
windowed rates/quantiles, so every service can answer "is anything wrong
RIGHT NOW" without any external stack. Active alerts are exported as
`dragonfly_alert_active{name}` (scraped like any metric) and carried in the
stats frame the manager aggregates — dftop shows them cluster-wide, and the
check.sh metrics-smoke leg gates on an induced one flipping within one
evaluation interval.

A rule is data, not code:

    AlertRule(name="scorer_error_rate", kind="ratio",
              metric="dragonfly_scheduler_ml_base_fallback_total",
              labels={"reason": "scorer_error"},
              denom="dragonfly_scheduler_schedule_duration_seconds",
              op=">", bound=0.05, window_s=60, for_s=0)

kinds:
  rate      per-second counter increase over window_s (histograms: count)
  ratio     rate(metric)/rate(denom), guarded by min_denom_rate — a cluster
            serving no rounds never alerts on a 0/0
  quantile  bucket-interpolated q over window_s (histograms only)
  value     latest sampled value (gauges)

`for_s` is Prometheus `for:`: the bound must stay breached that long before
the alert activates (0 = first breached evaluation activates — the rates are
already windowed, so momentary noise is pre-smoothed).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

from dragonfly2_tpu.observability.metrics import default_registry
from dragonfly2_tpu.observability.sketches import PSI_MAJOR
from dragonfly2_tpu.observability.timeseries import MetricsRecorder

DEFAULT_EVAL_INTERVAL_S = 5.0

ALERT_ACTIVE = default_registry().gauge(
    "alert_active",
    "SLO alert state (1 = firing) per rule name (observability/alerts.py)",
    labels=("name",),
)


@dataclass
class AlertRule:
    name: str
    metric: str
    bound: float
    kind: str = "rate"            # rate | ratio | quantile | value
    op: str = ">"                 # ">" or "<"
    labels: Optional[Mapping[str, str]] = None
    denom: Optional[str] = None   # ratio denominator metric
    denom_labels: Optional[Mapping[str, str]] = None
    q: float = 0.95               # quantile kind
    window_s: float = 60.0
    for_s: float = 0.0
    # ratio guard: below this denominator rate the ratio is statistically
    # meaningless (an idle scheduler must not alert on its first error)
    min_denom_rate: float = 0.05
    description: str = ""

    def __post_init__(self):
        if self.kind not in ("rate", "ratio", "quantile", "value"):
            raise ValueError(f"unknown alert kind {self.kind!r}")
        if self.op not in (">", "<"):
            raise ValueError(f"alert op must be > or <, got {self.op!r}")
        if self.kind == "ratio" and not self.denom:
            raise ValueError(f"ratio rule {self.name!r} needs a denom metric")


@dataclass
class _RuleState:
    rule: AlertRule
    active: bool = False
    breached_since: Optional[float] = None
    since: Optional[float] = None
    value: Optional[float] = None
    extra: dict = field(default_factory=dict)


def default_rules() -> list[AlertRule]:
    """The built-in SLO set. Every rule names a family that exists today;
    rules whose family never shows up in the recorder simply stay inactive,
    so one rule set serves scheduler, daemon, and trainer processes."""
    return [
        AlertRule(
            name="loop_lag_p95",
            kind="quantile", q=0.95,
            metric="dragonfly_loop_lag_seconds",
            bound=0.25, window_s=60.0, for_s=10.0,
            description="event-loop scheduling lag p95 over 250 ms",
        ),
        AlertRule(
            name="scorer_error_rate",
            kind="ratio",
            metric="dragonfly_scheduler_ml_base_fallback_total",
            labels={"reason": "scorer_error"},
            denom="dragonfly_scheduler_schedule_duration_seconds",
            bound=0.05, window_s=60.0,
            description="ml scorer exceptions per scheduling round over 5%",
        ),
        AlertRule(
            name="base_fallback_rate",
            kind="ratio",
            metric="dragonfly_scheduler_ml_base_fallback_total",
            denom="dragonfly_scheduler_schedule_duration_seconds",
            bound=0.5, window_s=60.0,
            description="rounds served by the base fallback over 50% "
                        "(native/jax serving degraded)",
        ),
        AlertRule(
            name="piece_failure_ratio",
            kind="ratio",
            metric="dragonfly_scheduler_piece_result_total",
            labels={"success": "false"},
            denom="dragonfly_scheduler_piece_result_total",
            bound=0.2, window_s=60.0,
            description="failed piece reports over 20% of all piece reports",
        ),
        AlertRule(
            name="federation_sync_failures",
            kind="rate",
            metric="dragonfly_scheduler_federation_syncs_total",
            labels={"result": "error"},
            bound=0.0, window_s=60.0, for_s=10.0,
            description="any federation sync errors sustained in the window",
        ),
        AlertRule(
            name="sim_departed_parent",
            kind="rate",
            metric="dragonfly_sim_departed_parent_rounds_total",
            bound=0.0, window_s=60.0,
            # an INVARIANT alert, not an SLO: a scheduling round handing out
            # a peer that cleanly left the cluster is wrong at any rate. The
            # family only exists in processes that import the simulator
            # (dragonfly2_tpu.sim.metrics), so the rule stays inactive
            # everywhere else — scenario packs assert on it through the same
            # recorder→engine path production would page through.
            description="simulated scheduling rounds handed out a departed "
                        "peer (virtual-clock swarm invariant violation)",
        ),
        AlertRule(
            name="feature_drift",
            kind="value",
            metric="dragonfly_feature_drift_max",
            # ONE decision boundary with classify_psi()/dfml/dfmodel
            bound=PSI_MAJOR,
            window_s=60.0, for_s=0.0,
            # the UNLABELED max gauge, not dragonfly_feature_drift{feature}:
            # value-kind sums matching label sets (PromQL sum-by), and a sum
            # of 16 per-feature PSIs would fire on collective noise; the max
            # is the decision variable (0.25 = conventional "major shift").
            # The per-feature detail stays queryable at /debug/ts.
            description="live scoring-feature distribution drifted past "
                        "PSI 0.25 vs the serving model's training reference "
                        "(population shift — retrain or investigate; "
                        "per-feature detail in dragonfly_feature_drift)",
        ),
        AlertRule(
            name="scheduler_degraded",
            kind="value",
            metric="dragonfly_scheduler_degradation_level",
            bound=0.5, window_s=60.0, for_s=0.0,
            # the brownout ladder (scheduler/degradation.py) already applies
            # sustain/cool hysteresis before moving the gauge, so the rule
            # fires on the first evaluation that sees rung >= 1 — the ladder
            # engaging IS the page-worthy event, the per-rung detail lives in
            # the stats frame / dftop degradation column
            description="scheduler brownout ladder engaged (load shedding "
                        "active; see scheduler_degradation_level rung and "
                        "README 'Overload & degradation')",
        ),
        AlertRule(
            name="piece_tls_handshake_failures",
            kind="rate",
            metric="dragonfly_dfdaemon_piece_tls_handshake_failures_total",
            bound=0.0, window_s=60.0, for_s=15.0,
            # a RATE rule, not a failure/success ratio: when a cert rollover
            # goes wrong every handshake fails and a ratio's denominator
            # (completed handshakes) goes to zero — exactly when the alert
            # must fire. Sustained-for filters the stray flaky parent.
            description="data-plane TLS handshake failures sustained in the "
                        "window (cert rollover / cipher mismatch suspect)",
        ),
    ]


class AlertEngine:
    """Evaluates rules against a MetricsRecorder on a fixed cadence.

    start() rides the event loop (call_later); evaluate_once(now=...) is the
    synchronous entry for tests and the smoke leg. Thread-safe: the stats
    frame builder and /debug endpoints read active() while the loop ticks.
    """

    def __init__(
        self,
        recorder: MetricsRecorder,
        rules: list[AlertRule] | None = None,
        *,
        interval: float = DEFAULT_EVAL_INTERVAL_S,
        export: bool = True,
    ):
        self.recorder = recorder
        self.interval = interval
        # `export`: write dragonfly_alert_active{name} on every evaluation.
        # The PROCESS's serving engine (default_engine) exports; an ad-hoc
        # engine over a private recorder (bench probes, scratch analyses)
        # must pass export=False or it would stomp the serving engine's
        # firing state in the shared gauge — two engines share rule NAMES,
        # not rule STATE.
        self.export = export
        self._states = [_RuleState(r) for r in (rules if rules is not None else default_rules())]
        self._lock = threading.Lock()
        self._handle: Any = None
        self.evaluations = 0
        # export every rule as 0 up front: the gauge answers "is this rule
        # known and quiet" vs "was this rule never evaluated"
        if self.export:
            for st in self._states:
                ALERT_ACTIVE.set(0.0, name=st.rule.name)

    # ---- evaluation ----

    def _rule_value(self, rule: AlertRule, now: float) -> tuple[float | None, dict]:
        r = self.recorder
        if rule.kind == "rate":
            return r.rate(rule.metric, rule.labels, window_s=rule.window_s, now=now), {}
        if rule.kind == "value":
            return r.latest(rule.metric, rule.labels), {}
        if rule.kind == "quantile":
            hw = r.hist_window(
                rule.metric, rule.labels, window_s=rule.window_s, now=now, q=rule.q
            )
            if hw is None:
                return None, {}
            return hw.get("pq"), {}
        # ratio
        num = r.rate(rule.metric, rule.labels, window_s=rule.window_s, now=now)
        den = r.rate(rule.denom, rule.denom_labels, window_s=rule.window_s, now=now)
        if num is None or den is None or den < rule.min_denom_rate:
            return None, {"num_rate": num, "denom_rate": den}
        return num / den, {"num_rate": num, "denom_rate": den}

    def evaluate_once(self, now: float | None = None) -> list[str]:
        """One pass over every rule; returns the names currently firing and
        keeps `dragonfly_alert_active{name}` one-for-one with them."""
        now = now if now is not None else time.time()
        firing: list[str] = []
        with self._lock:
            self.evaluations += 1
            for st in self._states:
                rule = st.rule
                value, extra = self._rule_value(rule, now)
                st.value = value
                st.extra = extra
                breached = value is not None and (
                    value > rule.bound if rule.op == ">" else value < rule.bound
                )
                if breached:
                    if st.breached_since is None:
                        st.breached_since = now
                    if now - st.breached_since >= rule.for_s:
                        if not st.active:
                            st.since = now
                        st.active = True
                else:
                    st.breached_since = None
                    st.active = False
                    st.since = None
                if self.export:
                    ALERT_ACTIVE.set(1.0 if st.active else 0.0, name=rule.name)
                if st.active:
                    firing.append(rule.name)
        return firing

    def active(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "name": st.rule.name,
                    "value": st.value,
                    "bound": st.rule.bound,
                    "op": st.rule.op,
                    "since": st.since,
                    "description": st.rule.description,
                }
                for st in self._states
                if st.active
            ]

    def status(self) -> dict:
        with self._lock:
            return {
                "interval_s": self.interval,
                "evaluations": self.evaluations,
                "rules": [
                    {
                        "name": st.rule.name,
                        "kind": st.rule.kind,
                        "metric": st.rule.metric,
                        "op": st.rule.op,
                        "bound": st.rule.bound,
                        "window_s": st.rule.window_s,
                        "for_s": st.rule.for_s,
                        "value": st.value,
                        "active": st.active,
                        "since": st.since,
                    }
                    for st in self._states
                ],
            }

    # ---- lifecycle ----

    def start(self) -> None:
        import asyncio

        if self._handle is not None:
            return
        loop = asyncio.get_running_loop()
        self._handle = loop.call_later(self.interval, self._tick, loop)

    def stop(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None

    @property
    def running(self) -> bool:
        return self._handle is not None

    def _tick(self, loop) -> None:
        try:
            self.evaluate_once()
        except Exception:  # noqa: BLE001 — a bad rule must not kill evaluation
            import logging

            logging.getLogger(__name__).exception("alert evaluation failed")
        self._handle = loop.call_later(self.interval, self._tick, loop)


_default: AlertEngine | None = None


def default_engine() -> AlertEngine:
    """Process-wide engine over the default recorder + built-in rules
    (composition roots start it; /debug/alerts and stats frames read it)."""
    global _default
    if _default is None:
        import os

        from dragonfly2_tpu.observability.timeseries import default_recorder

        interval = float(
            os.environ.get("DRAGONFLY_ALERT_INTERVAL", DEFAULT_EVAL_INTERVAL_S)
        )
        _default = AlertEngine(default_recorder(), interval=interval)
    return _default
