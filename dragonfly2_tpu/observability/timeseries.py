"""Time-series layer above /metrics: bounded in-process rings + rate queries.

Every number the services export today is an instantaneous process-local
value: the Prometheus text endpoint (observability/metrics.py) answers "what
is the counter NOW", never "how fast is it moving" or "what did the last ten
minutes look like". The reference assumes an external Prometheus/Grafana
stack stores that history; this reproduction has no such luxury — honest
throughput accounting needs windowed rates, not lifetime totals (PAPERS.md
"Scalable Training of Language Models using JAX pjit and TPUv4" keeps MFU
over timed windows for the same reason), and the rollout health gates,
SLO alerts, and dftop all read windows.

MetricsRecorder samples a MetricsRegistry every ~2 s into one bounded ring
per (metric family, label set):

  counters    cumulative values; rate() sums adjacent deltas over the query
              window (each delta clamped >= 0, so a counter reset after an
              in-process service restart reads as a missing interval, not a
              huge negative rate)
  gauges      raw values; latest()/window mean
  histograms  cumulative (count, sum, per-bucket counts); hist_window()
              subtracts the oldest in-window sample from the newest and
              interpolates p50/p95 from the bucket deltas — a TRUE windowed
              quantile, not the lifetime one the text endpoint implies

Bounds are hard: retention_s/interval samples per ring (default ~10 min),
max_series label sets total — past the cap new series are counted in
`dropped_series` and never allocated, so a label-cardinality accident costs
a counter, not the heap. Sampling cost is measured every tick
(`last_sample_cost_us`) and is the number bench.py's metrics_plane section
pins ≤1% of the sample interval.

Served by GET /debug/ts (observability/server.py) and consumed by
observability/alerts.py (SLO rules) and build_stats_frame() — the compact
per-service frame the manager aggregates cluster-wide (rpc `cluster_stats`,
read by cli/dftop.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Iterable, Mapping

from dragonfly2_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)

DEFAULT_INTERVAL_S = 2.0
DEFAULT_RETENTION_S = 600.0
DEFAULT_MAX_SERIES = 4096
DEFAULT_WINDOW_S = 60.0


class _Series:
    """One (family, label set) ring. Points are tuples:
    scalar kinds (counter/gauge): (t, value)
    histogram: (t, count, total, bucket_counts_tuple)"""

    __slots__ = ("kind", "labels", "buckets", "points")

    def __init__(self, kind: str, labels: tuple, samples_cap: int, buckets=None):
        self.kind = kind
        self.labels = labels  # ((k, v), ...) sorted
        self.buckets = buckets  # histogram upper bounds, else None
        self.points: deque = deque(maxlen=samples_cap)


def _labels_match(series_labels: tuple, want: Mapping[str, str] | None) -> bool:
    """want=None matches everything; otherwise every given (k, v) must be
    present in the series' label set (partial match → aggregation over the
    remaining labels, the PromQL sum-by shape)."""
    if not want:
        return True
    have = dict(series_labels)
    return all(have.get(k) == str(v) for k, v in want.items())


class MetricsRecorder:
    """Samples one MetricsRegistry into bounded per-series rings.

    start() schedules the sampler on the running event loop (call_later,
    the loophealth pattern — sampling on the loop keeps the walk free of
    cross-thread registry surprises and costs ~one tick per interval);
    sample_once() is the synchronous entry tests and bench use directly.
    All query methods are thread-safe (alert engines and RPC handlers read
    while the loop samples).
    """

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        *,
        interval: float = DEFAULT_INTERVAL_S,
        retention_s: float = DEFAULT_RETENTION_S,
        max_series: int = DEFAULT_MAX_SERIES,
    ):
        self.registry = registry or default_registry()
        self.interval = interval
        self.retention_s = retention_s
        self.max_series = max_series
        # ring length = retention/interval, clamped: a fast interval (smoke
        # cadences, stress probes) with the default 10-min retention must
        # not balloon every ring to tens of thousands of points — 4096
        # points is the hard per-series ceiling, retention shrinks to fit
        self._samples_cap = max(2, min(4096, int(retention_s / max(interval, 1e-3)) + 1))
        self._series: dict[tuple, _Series] = {}
        self._lock = threading.Lock()
        self._handle: Any = None
        self.samples = 0
        # DISTINCT refused series (not refusals-per-tick: re-counting the
        # same over-cap label set every 2 s would report an 18k-series
        # "explosion" after an hour when exactly 10 were ever refused). The
        # tracking set is itself bounded at 4x max_series — past that the
        # count undercounts and the overflow flag says so.
        self._dropped_keys: set[tuple] = set()
        self.dropped_overflow = False
        self.last_sample_cost_us = 0.0
        self.started_at = 0.0

    @property
    def dropped_series(self) -> int:
        return len(self._dropped_keys)

    # ---- sampling ----

    def sample_once(self, now: float | None = None) -> float:
        """One full registry walk; returns the walk's cost in seconds."""
        t0 = time.perf_counter()
        t = now if now is not None else time.time()
        for fam in self.registry.families():
            if isinstance(fam, Histogram):
                kind = "histogram"
            elif isinstance(fam, Counter):
                kind = "counter"
            elif isinstance(fam, Gauge):
                kind = "gauge"
            else:
                continue
            for key, child in fam._snapshot_children():
                skey = (fam.name, key)
                s = self._series.get(skey)
                if s is None:
                    with self._lock:
                        s = self._series.get(skey)
                        if s is None:
                            if len(self._series) >= self.max_series:
                                if len(self._dropped_keys) < 4 * self.max_series:
                                    self._dropped_keys.add(skey)
                                else:
                                    self.dropped_overflow = True
                                continue
                            s = self._series[skey] = _Series(
                                kind,
                                tuple(sorted(fam._labels_of(key).items())),
                                self._samples_cap,
                                buckets=getattr(fam, "buckets", None),
                            )
                if kind == "histogram":
                    # snapshot under the child lock — same torn-histogram
                    # rule Histogram.render follows
                    with child._lock:  # type: ignore[attr-defined]
                        point = (
                            t,
                            child.count,  # type: ignore[attr-defined]
                            child.total,  # type: ignore[attr-defined]
                            tuple(child.counts),  # type: ignore[attr-defined]
                        )
                else:
                    point = (t, float(child.value))  # type: ignore[attr-defined]
                s.points.append(point)
        self.samples += 1
        cost = time.perf_counter() - t0
        self.last_sample_cost_us = cost * 1e6
        return cost

    def start(self) -> None:
        """Begin sampling on the RUNNING loop. Idempotent."""
        import asyncio

        if self._handle is not None:
            return
        loop = asyncio.get_running_loop()
        self.started_at = time.time()
        self._handle = loop.call_later(self.interval, self._tick, loop)

    def stop(self) -> None:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None

    @property
    def running(self) -> bool:
        return self._handle is not None

    def _tick(self, loop) -> None:
        try:
            self.sample_once()
        except Exception:  # noqa: BLE001 — a torn family must not kill sampling
            import logging

            logging.getLogger(__name__).exception("timeseries sample failed")
        self._handle = loop.call_later(self.interval, self._tick, loop)

    # ---- queries ----

    def _matching(self, name: str, labels: Mapping[str, str] | None) -> list[_Series]:
        with self._lock:
            return [
                s
                for (fam_name, _key), s in self._series.items()
                if fam_name == name and _labels_match(s.labels, labels)
            ]

    def _window_points(self, s: _Series, window_s: float, now: float) -> list:
        cutoff = now - window_s
        # list(deque) is one GIL-held C call — the atomic snapshot that lets
        # alert engines / RPC handlers read while the loop thread appends
        # (iterating the live deque would RuntimeError on a concurrent append)
        return [p for p in list(s.points) if p[0] >= cutoff]

    def rate(
        self,
        name: str,
        labels: Mapping[str, str] | None = None,
        *,
        window_s: float = DEFAULT_WINDOW_S,
        now: float | None = None,
    ) -> float | None:
        """Per-second increase of a counter (or a histogram's observation
        count) over the window, summed across matching label sets. Each
        adjacent delta is clamped >= 0 so a counter reset costs the one
        interval it happened in, never a negative rate. None when no series
        has >= 2 in-window samples (absent != zero — callers distinguish
        "no data" from "rate 0")."""
        now = now if now is not None else time.time()
        total = 0.0
        span = 0.0
        seen = False
        for s in self._matching(name, labels):
            pts = self._window_points(s, window_s, now)
            if len(pts) < 2:
                continue
            seen = True
            # p[1] is the counter/gauge value — or, for histogram points,
            # the observation count: one extraction serves every kind
            vals = [p[1] for p in pts]
            for a, b in zip(vals, vals[1:]):
                total += max(0.0, b - a)
            span = max(span, pts[-1][0] - pts[0][0])
        if not seen or span <= 0:
            return None
        return total / span

    def latest(
        self, name: str, labels: Mapping[str, str] | None = None
    ) -> float | None:
        """Most recent sampled value, summed across matching label sets
        (gauges/counters; histograms answer with their observation count)."""
        out = None
        for s in self._matching(name, labels):
            if not s.points:
                continue
            p = s.points[-1]
            v = float(p[1])
            out = v if out is None else out + v
        return out

    def hist_window(
        self,
        name: str,
        labels: Mapping[str, str] | None = None,
        *,
        window_s: float = DEFAULT_WINDOW_S,
        now: float | None = None,
        q: float | None = None,
    ) -> dict | None:
        """Windowed histogram summary: observation count/rate, mean, and
        bucket-interpolated p50/p95 over the window's bucket-count deltas
        (merged across matching label sets). `q` adds a "pq" key with that
        quantile (the alert engine's arbitrary-q entry). None when no data."""
        now = now if now is not None else time.time()
        buckets: tuple | None = None
        dcounts: list[float] | None = None
        count_d = 0.0
        total_d = 0.0
        span = 0.0
        for s in self._matching(name, labels):
            if s.kind != "histogram" or s.buckets is None:
                continue
            pts = self._window_points(s, window_s, now)
            if len(pts) < 2:
                continue
            first, last = pts[0], pts[-1]
            if buckets is None:
                buckets = s.buckets
                dcounts = [0.0] * len(buckets)
            if s.buckets != buckets or dcounts is None:
                continue  # incompatible bucket layouts never merge
            count_d += max(0.0, last[1] - first[1])
            total_d += max(0.0, last[2] - first[2])
            for i, (a, b) in enumerate(zip(first[3], last[3])):
                dcounts[i] += max(0.0, b - a)
            span = max(span, last[0] - first[0])
        if buckets is None or dcounts is None or span <= 0:
            return None
        # Histogram bucket counts are CUMULATIVE-le (observe() increments
        # EVERY bucket whose bound covers the value), so the windowed deltas
        # are cumulative too — difference adjacent deltas into the disjoint
        # per-bucket masses bucket_quantile expects. Feeding it the
        # cumulative vector deflated every windowed quantile the moment a
        # window's observations spanned more than one bucket.
        masses = [
            max(0.0, dcounts[i] - (dcounts[i - 1] if i else 0.0))
            for i in range(len(dcounts))
        ]
        out = {
            "count": count_d,
            "rate_per_s": count_d / span,
            "mean": (total_d / count_d) if count_d else 0.0,
            "p50": bucket_quantile(buckets, masses, count_d, 0.50),
            "p95": bucket_quantile(buckets, masses, count_d, 0.95),
            "window_s": span,
        }
        if q is not None:
            out["pq"] = bucket_quantile(buckets, masses, count_d, q)
        return out

    def series(self) -> list[dict]:
        with self._lock:
            items = list(self._series.items())
        return [
            {
                "name": name,
                "labels": dict(s.labels),
                "kind": s.kind,
                "points": len(s.points),
            }
            for (name, _key), s in items
        ]

    def query(
        self,
        name: str,
        labels: Mapping[str, str] | None = None,
        *,
        since: float | None = None,
    ) -> list[dict]:
        """Raw points for matching series (the /debug/ts range API)."""
        out = []
        for s in self._matching(name, labels):
            pts: Iterable = list(s.points)  # atomic snapshot (see _window_points)
            if since is not None:
                pts = [p for p in pts if p[0] >= since]
            if s.kind == "histogram":
                points = [
                    {"t": p[0], "count": p[1], "sum": p[2]} for p in pts
                ]
            else:
                points = [{"t": p[0], "value": p[1]} for p in pts]
            out.append(
                {"name": name, "labels": dict(s.labels), "kind": s.kind, "points": points}
            )
        return out

    def stats(self) -> dict:
        with self._lock:
            n = len(self._series)
        return {
            "running": self.running,
            "interval_s": self.interval,
            "retention_s": self.retention_s,
            "series": n,
            "max_series": self.max_series,
            "dropped_series": self.dropped_series,
            "dropped_overflow": self.dropped_overflow,
            "samples": self.samples,
            "last_sample_cost_us": round(self.last_sample_cost_us, 1),
        }


def bucket_quantile(
    buckets: tuple, dcounts: list[float], total: float, q: float
) -> float:
    """Quantile from bucketed counts, linearly interpolated inside the
    landing bucket (lower bound = previous bucket's upper bound, 0 for the
    first). Observations past the last finite bucket answer with that
    bucket's bound — the honest ceiling of what bucketed data can say.
    THE shared bucket-quantile: hist_window() above and the rollout
    shadow-divergence p99 (scheduler/rollout.delta_hist_quantile) both
    delegate here, so the same distribution never reads differently from
    /debug/ts vs `dfmodel status`."""
    if total <= 0:
        return 0.0
    want = q * total
    cum = 0.0
    lo = 0.0
    for b, c in zip(buckets, dcounts):
        if cum + c >= want and c > 0:
            frac = (want - cum) / c
            return lo + (b - lo) * min(1.0, max(0.0, frac))
        cum += c
        lo = b
    return float(buckets[-1]) if buckets else 0.0


# ---------------------------------------------------------------------------
# stats frame: the compact per-service report the manager aggregates


def build_stats_frame(
    recorder: MetricsRecorder,
    *,
    service: str,
    hostname: str = "",
    alerts=None,
    window_s: float = DEFAULT_WINDOW_S,
    extra: Mapping[str, Any] | None = None,
) -> dict:
    """One compact frame of this process's windowed health, riding the
    existing manager keepalive tick (rpc/manager.py `keepalive(stats=...)`).

    Only keys whose metric families exist in the recorder are emitted, so a
    daemon's frame carries byte rates and a scheduler's carries round rates
    without any per-service frame schema. Everything is a small flat number
    (or short string) — the manager keeps a ring of these per member and
    dftop renders them directly; frame size is pinned by bench metrics_plane.
    """
    r = recorder
    rates: dict[str, float] = {}

    def put(key: str, val: float | None, nd: int = 3) -> None:
        if val is not None:
            rates[key] = round(val, nd)

    # scheduler plane
    sched = r.hist_window(
        "dragonfly_scheduler_schedule_duration_seconds", window_s=window_s
    )
    if sched is not None:
        put("rounds_per_s", sched["rate_per_s"], 2)
        put("round_p95_ms", sched["p95"] * 1e3, 2)
    put("pieces_ok_per_s", r.rate(
        "dragonfly_scheduler_piece_result_total", {"success": "true"}, window_s=window_s
    ), 2)
    put("pieces_failed_per_s", r.rate(
        "dragonfly_scheduler_piece_result_total", {"success": "false"}, window_s=window_s
    ), 3)
    put("base_fallback_per_s", r.rate(
        "dragonfly_scheduler_ml_base_fallback_total", window_s=window_s
    ), 3)
    put("scorer_errors_per_s", r.rate(
        "dragonfly_scheduler_ml_base_fallback_total", {"reason": "scorer_error"},
        window_s=window_s,
    ), 3)
    # daemon plane (bytes → MB/s)
    down = r.rate("dragonfly_dfdaemon_download_bytes_total", window_s=window_s)
    up = r.rate("dragonfly_dfdaemon_upload_bytes_total", window_s=window_s)
    put("piece_down_mb_per_s", None if down is None else down / (1 << 20), 3)
    put("piece_up_mb_per_s", None if up is None else up / (1 << 20), 3)
    put("tasks_per_s", r.rate(
        "dragonfly_dfdaemon_task_result_total", window_s=window_s
    ), 3)
    # data-plane TLS health: handshake volume + how much of it resumed (the
    # fast-path contract is resumed/total ≥ 0.9 under reconnect storms)
    put("tls_handshakes_per_s", r.rate(
        "dragonfly_dfdaemon_piece_tls_handshakes_total", window_s=window_s
    ), 3)
    put("tls_resumed_per_s", r.rate(
        "dragonfly_dfdaemon_piece_tls_handshakes_total", {"resumed": "true"},
        window_s=window_s,
    ), 3)
    put("tls_handshake_failures_per_s", r.rate(
        "dragonfly_dfdaemon_piece_tls_handshake_failures_total", window_s=window_s
    ), 3)
    # trainer plane (ISSUE 15): a trainer member shows live learner work —
    # keys appear only in processes where the dragonfly_train_* families
    # have children (the only-present-families schema, like everything here)
    put("train_steps_per_s", r.rate(
        "dragonfly_train_steps_total", window_s=window_s
    ), 2)
    put("train_examples_per_s", r.rate(
        "dragonfly_train_examples_total", window_s=window_s
    ), 1)
    runs = r.latest("dragonfly_train_runs_total")
    if runs is not None:
        rates["train_runs_total"] = int(runs)
    put("train_last_loss", r.latest("dragonfly_train_last_run_loss"), 5)
    # ML-plane drift (ISSUE 15): max per-feature PSI vs the serving model's
    # training reference — the number the feature_drift alert gates on
    put("feature_drift_max", r.latest("dragonfly_feature_drift_max"), 4)
    # brownout ladder (ISSUE 17): current rung + admission shed rate — dftop
    # shows which schedulers are degraded cluster-wide from these two keys
    deg = r.latest("dragonfly_scheduler_degradation_level")
    if deg is not None:
        rates["degradation_level"] = int(deg)
    put("admission_shed_per_s", r.rate(
        "dragonfly_scheduler_admission_shed_total", window_s=window_s
    ), 3)
    mgr_down = r.latest("dragonfly_scheduler_manager_unreachable")
    if mgr_down is not None and mgr_down >= 1.0:
        rates["manager_unreachable"] = 1
    # loop health
    lag = r.hist_window("dragonfly_loop_lag_seconds", window_s=window_s)
    if lag is not None:
        put("loop_lag_p95_ms", lag["p95"] * 1e3, 3)
    util = r.hist_window("dragonfly_loop_dispatcher_utilization", window_s=window_s)
    if util is not None:
        put("dispatcher_utilization", util["mean"], 3)
    # federation sync health
    put("federation_syncs_ok_per_s", r.rate(
        "dragonfly_scheduler_federation_syncs_total", {"result": "ok"},
        window_s=window_s,
    ), 3)
    put("federation_syncs_err_per_s", r.rate(
        "dragonfly_scheduler_federation_syncs_total", {"result": "error"},
        window_s=window_s,
    ), 3)

    frame: dict[str, Any] = {
        "service": service,
        "ts": round(time.time(), 3),
        "window_s": window_s,
        "rates": rates,
    }
    if hostname:
        frame["hostname"] = hostname
    peers = r.latest("dragonfly_scheduler_federation_peers")
    if peers is not None:
        frame["federation_peers"] = int(peers)
    mode = _one_hot_mode(r, "dragonfly_scheduler_ml_serving_mode", "mode")
    if mode is not None:
        frame["serving_mode"] = mode
    # wire posture labels for the daemon's byte rates: which cipher piece
    # MB/s is riding, and what the write-behind governor decided
    cipher = _one_hot_mode(r, "dragonfly_dfdaemon_piece_cipher", "cipher")
    if cipher is not None:
        frame["piece_cipher"] = cipher
    wb = _one_hot_mode(r, "dragonfly_dfdaemon_write_behind_mode", "mode")
    if wb is not None:
        frame["write_behind"] = wb
    state = _one_hot_mode(r, "dragonfly_scheduler_model_rollout_state", "state")
    if state is not None:
        frame["rollout_state"] = state
    if alerts is not None:
        frame["alerts"] = [a["name"] for a in alerts.active()]
    if extra:
        frame.update(extra)
    return frame


def _one_hot_mode(r: MetricsRecorder, name: str, label: str) -> str | None:
    """Resolve a one-hot gauge family ({mode} with exactly one 1) to its
    active label value."""
    active = None
    seen = False
    for s in r._matching(name, None):
        if not s.points:
            continue
        seen = True
        if s.points[-1][1] >= 1.0:
            active = dict(s.labels).get(label)
    return active if seen else None


# ---------------------------------------------------------------------------
# process-wide default (composition roots start it; /debug/ts reads it)

_default: MetricsRecorder | None = None


def default_recorder() -> MetricsRecorder:
    global _default
    if _default is None:
        import os

        interval = float(os.environ.get("DRAGONFLY_TS_INTERVAL", DEFAULT_INTERVAL_S))
        _default = MetricsRecorder(interval=interval)
    return _default
