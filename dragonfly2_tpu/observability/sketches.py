"""Streaming per-feature histogram sketches + PSI feature-drift scoring.

The ML plane's blind spot (ISSUE 15): nothing compares what a model was
TRAINED on against what it is SCORING now. A scheduler can serve a model for
weeks while the cluster underneath it drifts — new regions come online (the
location/idc columns move), probe RTTs re-center after a topology change, a
release changes piece sizing — and the first visible symptom is degraded
placement, not a number. The standard instrument is a population-stability
comparison of the per-feature input distributions:

  FeatureSketch   one fixed-bin histogram per feature column, streaming and
                  bounded: (F, bins+2) int64 counts — underflow + overflow
                  bins catch values outside the normalized [lo, hi) band the
                  feature schema promises (models/features.py builds ~[0,1]).
                  update() is one vectorized bincount per matrix, so feeding
                  it from the scoring hot path costs microseconds.

  psi()           Population Stability Index per feature between a reference
                  and a live sketch: sum((p-q) * ln(p/q)) over bins with
                  probability clamping. Conventional thresholds: < 0.1
                  stable, 0.1-0.25 moderate shift, > 0.25 major shift (the
                  built-in `feature_drift` alert fires at 0.25).

  DriftDetector   the serving-side harness: the TRAINING-reference sketch
                  (frozen at dataset finalize, shipped digest-covered inside
                  the model artifact — trainer/dataset.py, trainer/
                  artifacts.py) vs a live sketch fed with sampled feature
                  matrices from the evaluator's _prepare. Every
                  `compute_every` sampled updates it recomputes PSI and
                  exports dragonfly_feature_drift{feature} plus the
                  _max gauge the alert rule reads.

Clock discipline (DF029): stamps come from an injected utils.clock.Clock, so
the same detector runs under the swarm simulator's VirtualClock — drift
"periodicity" is counted in sampled updates, not wall seconds, which makes it
deterministic for tests and free of wall reads on the scoring path.

Thread safety: the evaluator's _prepare runs on round-dispatcher worker
threads; update/observe/compute hold one small lock (~100 ns uncontended,
noise next to the numpy work they guard).
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Mapping, Sequence

import numpy as np

from dragonfly2_tpu.observability.metrics import default_registry
from dragonfly2_tpu.utils import clock as clockmod

logger = logging.getLogger(__name__)

DEFAULT_BINS = 16
# PSI probability clamp: a bin empty on one side contributes a large-but-
# finite term instead of an infinity a single stray sample could produce
PSI_EPS = 1e-4
# conventional PSI decision thresholds (documented in README)
PSI_MODERATE = 0.1
PSI_MAJOR = 0.25

FEATURE_DRIFT = default_registry().gauge(
    "feature_drift",
    "PSI between the serving model's training-reference feature "
    "distribution and the live scoring distribution, per feature "
    "(observability/sketches.py; >0.25 = major population shift)",
    labels=("feature",),
)
FEATURE_DRIFT_MAX = default_registry().gauge(
    "feature_drift_max",
    "Max per-feature PSI vs the training reference (the `feature_drift` "
    "alert rule's input; labeled per-feature detail in "
    "dragonfly_feature_drift)",
)


class FeatureSketch:
    """Fixed-bin streaming histogram over the columns of a feature matrix.

    Memory is BOUNDED by construction: (num_features, bins + 2) int64 —
    ~2.3 KB at the 16-feature x 16-bin default — regardless of how many rows
    ever stream through. Bin 0 is underflow (< lo), bin -1 overflow (>= hi);
    the interior bins split [lo, hi) uniformly. NaN rows land in overflow
    (a non-finite feature IS an anomaly worth seeing).
    """

    __slots__ = (
        "names", "lo", "hi", "bins", "counts", "rows", "created_at",
        "updated_at", "_clock", "_scale", "_col_offsets",
    )

    def __init__(
        self,
        num_features: int,
        *,
        names: Sequence[str] | None = None,
        bins: int = DEFAULT_BINS,
        lo: float = 0.0,
        hi: float = 1.0,
        clock: clockmod.Clock | None = None,
    ):
        if names is not None and len(names) != num_features:
            raise ValueError(
                f"{len(names)} names for {num_features} features"
            )
        if hi <= lo:
            raise ValueError(f"bad sketch range [{lo}, {hi})")
        self.names = tuple(names) if names is not None else tuple(
            f"f{i}" for i in range(num_features)
        )
        self.lo = float(lo)
        self.hi = float(hi)
        self.bins = int(bins)
        self.counts = np.zeros((num_features, bins + 2), np.int64)
        self.rows = 0
        self._clock = clock or clockmod.SYSTEM
        self.created_at = self._clock.time()
        self.updated_at = self.created_at
        # hot-path precomputes: update() runs inside the scoring round at
        # stride-sampled cadence — every numpy dispatch avoided there counts
        self._scale = float(bins) / (self.hi - self.lo)
        self._col_offsets = (
            np.arange(num_features, dtype=np.int64) * (bins + 2)
        )[None, :]

    @property
    def num_features(self) -> int:
        return self.counts.shape[0]

    def _bin_indices(self, feats: np.ndarray) -> np.ndarray:
        # floor-then-int keeps negatives honest (plain int truncation would
        # send (-1, 0) to bin 0's interior side). The clip happens in FLOAT
        # space, BEFORE the int cast: a huge finite value (an epoch-ns
        # timestamp leaking through a broken normalization) overflows the
        # int64 cast to INT64_MIN and would masquerade as underflow —
        # clipped first, it lands on the overflow/underflow extreme it
        # actually belongs to.
        with np.errstate(invalid="ignore"):
            idxf = np.floor((feats - self.lo) * self._scale)
            np.clip(idxf, -1.0, float(self.bins), out=idxf)
            idx = idxf.astype(np.int64)
        idx += 1
        # NaN survives the float clip and casts to INT64_MIN; force it into
        # overflow — a non-finite feature IS an anomaly worth seeing. The
        # isfinite scan costs one vector pass.
        bad = ~np.isfinite(feats)
        if bad.any():
            idx[bad] = self.bins + 1
        return idx

    def update(self, feats: np.ndarray) -> int:
        """Fold a [rows, num_features] (or [num_features]) matrix in; returns
        rows folded. One flattened bincount — no Python per-row work."""
        f = np.asarray(feats)
        if f.ndim == 1:
            f = f[None, :]
        if f.shape[1] != self.num_features:
            raise ValueError(
                f"matrix has {f.shape[1]} features, sketch {self.num_features}"
            )
        if not len(f):
            return 0
        width = self.bins + 2
        # column-major flattening: one bincount covers every (column, bin)
        flat = self._bin_indices(f)
        flat += self._col_offsets
        self.counts += np.bincount(
            flat.ravel(), minlength=self.num_features * width
        ).reshape(self.num_features, width)
        self.rows += len(f)
        self.updated_at = self._clock.time()
        return len(f)

    def merge(self, other: "FeatureSketch") -> None:
        if (
            other.num_features != self.num_features
            or other.bins != self.bins
            or other.lo != self.lo
            or other.hi != self.hi
        ):
            raise ValueError("incompatible sketch layouts never merge")
        self.counts += other.counts
        self.rows += other.rows
        self.updated_at = self._clock.time()

    def distribution(self) -> np.ndarray:
        """Per-feature bin probabilities [num_features, bins+2] (uniform when
        the sketch is empty — PSI vs anything equally empty reads 0)."""
        totals = self.counts.sum(axis=1, keepdims=True).astype(np.float64)
        width = self.bins + 2
        out = np.full(self.counts.shape, 1.0 / width, np.float64)
        nz = totals[:, 0] > 0
        out[nz] = self.counts[nz] / totals[nz]
        return out

    # ---- (de)serialization: JSON-safe, shipped inside model artifacts ----

    def to_dict(self) -> dict:
        return {
            "names": list(self.names),
            "lo": self.lo,
            "hi": self.hi,
            "bins": self.bins,
            "rows": self.rows,
            "created_at": self.created_at,
            "counts": self.counts.tolist(),
        }

    @classmethod
    def from_dict(
        cls, d: Mapping, *, clock: clockmod.Clock | None = None
    ) -> "FeatureSketch":
        counts = np.asarray(d["counts"], np.int64)
        sk = cls(
            counts.shape[0], names=d.get("names"), bins=int(d["bins"]),
            lo=float(d["lo"]), hi=float(d["hi"]), clock=clock,
        )
        if counts.shape != sk.counts.shape:
            raise ValueError(f"sketch counts shape {counts.shape} invalid")
        sk.counts = counts
        sk.rows = int(d.get("rows", int(counts[0].sum()) if len(counts) else 0))
        if "created_at" in d:
            sk.created_at = float(d["created_at"])
        return sk


def psi(
    reference: FeatureSketch, live: FeatureSketch, *, eps: float = PSI_EPS
) -> np.ndarray:
    """Per-feature Population Stability Index between two compatible
    sketches: sum((p - q) * ln(p / q)) over bins, probabilities clamped to
    `eps` so an empty-on-one-side bin contributes a large finite term."""
    if (
        reference.num_features != live.num_features
        or reference.bins != live.bins
        or reference.lo != live.lo
        or reference.hi != live.hi
    ):
        raise ValueError("incompatible sketch layouts never compare")
    p = np.clip(reference.distribution(), eps, None)
    q = np.clip(live.distribution(), eps, None)
    return np.sum((q - p) * np.log(q / p), axis=1)


class DriftDetector:
    """Training-reference vs live feature distribution, with PSI export.

    The evaluator calls observe(feats) on every prepared scoring round;
    every `sample_stride`-th call folds the matrix into the live sketch, and
    every `compute_every` folded updates the per-feature PSI is recomputed
    and exported (dragonfly_feature_drift{feature} + _max). Without a
    reference (no model attached, or a pre-sketch artifact) observe() is a
    None-check — the detector costs nothing until a sketch arrives.

    The live sketch RESETS whenever the reference changes (a new model's
    reference must not be compared against traffic scored under the old one)
    and decays by halving once live rows exceed `live_cap` — a bounded
    recency window in row count, not wall time (virtual-clock safe).
    """

    # Defaults sized against the serving round: one ~40-row fold costs
    # ~20µs of numpy, so 1-in-32 rounds keeps the live sketch at ~0.6µs per
    # round (the bench's ≤1% combined acceptance) while still folding
    # thousands of feature rows per second on a busy scheduler.
    def __init__(
        self,
        *,
        sample_stride: int = 32,
        compute_every: int = 32,
        live_cap: int = 200_000,
        clock: clockmod.Clock | None = None,
        export: bool = True,
    ):
        self.sample_stride = max(1, int(sample_stride))
        self.compute_every = max(1, int(compute_every))
        self.live_cap = int(live_cap)
        self.export = export
        self._clock = clock or clockmod.SYSTEM
        self._lock = threading.Lock()
        self._ref: FeatureSketch | None = None
        self._live: FeatureSketch | None = None
        self.reference_version = ""
        self._calls = 0
        self._folds = 0
        self.updates = 0
        self.computes = 0
        self._scores: np.ndarray | None = None
        self.computed_at: float | None = None

    @property
    def reference(self) -> FeatureSketch | None:
        return self._ref

    def set_reference(
        self, sketch: FeatureSketch | None, *, version: str = ""
    ) -> None:
        """Install (or clear, with None) the training-reference sketch —
        called by the model-install path with the artifact's sketch. Resets
        the live sketch and the exported scores."""
        with self._lock:
            old = self._ref
            self._ref = sketch
            self.reference_version = version if sketch is not None else ""
            self._live = None
            self._scores = None
            self.computed_at = None
            self._calls = 0
            self._folds = 0
        if self.export:
            # zero stale per-feature gauges — BOTH the outgoing reference's
            # features (a cleared detector must not leave last week's PSI
            # frozen on /metrics) and the incoming one's
            for sk in (old, sketch):
                if sk is not None:
                    for name in sk.names:
                        FEATURE_DRIFT.set(0.0, feature=name)
            FEATURE_DRIFT_MAX.set(0.0)
        logger.info(
            "feature-drift reference %s (%s)",
            "cleared" if sketch is None else "installed",
            version or "unversioned",
        )

    def observe(self, feats: np.ndarray) -> None:
        """Sampled live-sketch feed — the evaluator's per-round hook. Never
        raises (a drift bookkeeping bug must not fail a scheduling round)."""
        try:
            with self._lock:
                ref = self._ref
                if ref is None:
                    return
                self._calls += 1
                if self._calls % self.sample_stride:
                    return
                live = self._live
                if live is None:
                    live = self._live = FeatureSketch(
                        ref.num_features, names=ref.names, bins=ref.bins,
                        lo=ref.lo, hi=ref.hi, clock=self._clock,
                    )
                live.update(feats)
                self.updates += 1
                if self.live_cap > 0 and live.rows > self.live_cap:
                    # halve instead of reset: the window keeps shape while
                    # bounding the weight of ancient traffic
                    live.counts //= 2
                    live.rows = int(live.counts[0].sum()) if live.num_features else 0
                self._folds += 1
                if self._folds % self.compute_every == 0:
                    self._compute_locked()
        except Exception:
            logger.exception("feature-drift observe failed")

    def compute(self) -> dict[str, float] | None:
        """Force a PSI recompute now (tests / debug endpoints); returns the
        per-feature scores or None without reference/live data."""
        with self._lock:
            return self._compute_locked()

    def _compute_locked(self) -> dict[str, float] | None:
        # callers hold self._lock (observe()'s periodic trigger and
        # compute() both acquire it before entering)
        ref, live = self._ref, self._live
        if ref is None or live is None or live.rows == 0:
            return None
        scores = psi(ref, live)
        self._scores = scores  # dflint: disable=DF023 caller holds self._lock (see method docstring contract)
        self.computes += 1
        self.computed_at = self._clock.time()  # dflint: disable=DF023 caller holds self._lock
        if self.export:
            for name, s in zip(ref.names, scores):
                FEATURE_DRIFT.set(float(s), feature=name)
            FEATURE_DRIFT_MAX.set(float(scores.max()) if len(scores) else 0.0)
        return {n: float(s) for n, s in zip(ref.names, scores)}

    def scores(self) -> dict[str, float] | None:
        with self._lock:
            if self._scores is None or self._ref is None:
                return None
            return {
                n: float(s) for n, s in zip(self._ref.names, self._scores)
            }

    def max_score(self) -> float | None:
        with self._lock:
            if self._scores is None or not len(self._scores):
                return None
            return float(self._scores.max())

    def snapshot(self) -> dict:
        """JSON-safe state for /debug/decisions, dfml, and dfmodel status."""
        with self._lock:
            ref, live = self._ref, self._live
            scores = self._scores
            out: dict = {
                "reference_version": self.reference_version,
                "reference_rows": ref.rows if ref is not None else None,
                "live_rows": live.rows if live is not None else 0,
                "sample_stride": self.sample_stride,
                "compute_every": self.compute_every,
                "updates": self.updates,
                "computes": self.computes,
                "computed_at": self.computed_at,
            }
            if scores is not None and ref is not None:
                per = {n: round(float(s), 5) for n, s in zip(ref.names, scores)}
                out["psi"] = per
                out["psi_max"] = round(float(scores.max()), 5) if len(scores) else 0.0
                out["drifted"] = sorted(
                    n for n, s in per.items() if s > PSI_MAJOR
                )
            return out


def classify_psi(score: float) -> str:
    """Human label for one PSI score (README-documented thresholds)."""
    if not math.isfinite(score):
        return "invalid"
    if score > PSI_MAJOR:
        return "major"
    if score > PSI_MODERATE:
        return "moderate"
    return "stable"
