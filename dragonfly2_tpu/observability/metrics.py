"""Typed metrics with Prometheus text-format exposition.

The reference exposes Prometheus counters/gauges/histograms per service on a
dedicated metrics port (scheduler/metrics/metrics.go:46-179,
client/daemon/metrics/metrics.go, trainer/metrics/metrics.go). This is the
same model without the prometheus client dependency: a registry of named
metric families, label support, histogram buckets, and a text/plain v0.0.4
render suitable for any scraper.

Thread-safety: family creation holds the registry/family lock, and every
child mutation (Counter.inc / Gauge.set / Histogram.observe) holds a small
per-child lock. The services are asyncio loops, but hot mutators also run on
threads since PR 7 — dispatcher workers count scheduling metrics, pipeline
hash shards and storage writers touch daemon counters — and a bare
``self.value += x`` is a read-modify-write the GIL can preempt mid-update
(increments silently lost under contention; pinned by the multi-threaded
counter regression test). An uncontended Lock acquire is ~100 ns, noise next
to the dict lookups around it.
"""

from __future__ import annotations

import threading
import time
from typing import Iterable, Mapping, Optional, Sequence

_DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _fmt_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    kind = ""

    def __init__(self, name: str, help_: str, label_names: Sequence[str]):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._children: dict[tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Mapping[str, str]) -> tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"metric {self.name}: labels {sorted(labels)} != declared {sorted(self.label_names)}"
            )
        return tuple(str(labels[k]) for k in self.label_names)

    def _labels_of(self, key: tuple[str, ...]) -> dict[str, str]:
        return dict(zip(self.label_names, key))

    def _snapshot_children(self) -> list:
        """Sorted (key, child) pairs under the family lock: a worker thread
        recording a NEW label set resizes the child dict, and iterating it
        bare would raise RuntimeError mid-scrape."""
        with self._lock:
            return sorted(self._children.items())

    def render(self) -> Iterable[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def labels(self, **labels: str) -> "Counter._Child":
        key = self._key(labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, Counter._Child())
        return child  # type: ignore[return-value]

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if self.label_names:
            self.labels(**labels).inc(amount)
        else:
            self.labels().inc(amount)

    @property
    def value(self) -> float:
        with self._lock:  # a thread creating a new child resizes the dict
            children = list(self._children.values())
        return sum(c.value for c in children)  # type: ignore[attr-defined]

    class _Child:
        __slots__ = ("value", "_lock")

        def __init__(self) -> None:
            self.value = 0.0
            self._lock = threading.Lock()

        def inc(self, amount: float = 1.0) -> None:
            if amount < 0:
                raise ValueError("counter cannot decrease")
            with self._lock:  # += is a preemptible read-modify-write
                self.value += amount

    def render(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} counter"
        if not self._children and not self.label_names:
            yield f"{self.name} 0"
        for key, child in self._snapshot_children():
            yield f"{self.name}{_fmt_labels(self._labels_of(key))} {_fmt_value(child.value)}"  # type: ignore[attr-defined]


class Gauge(_Metric):
    kind = "gauge"

    def labels(self, **labels: str) -> "Gauge._Child":
        key = self._key(labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, Gauge._Child())
        return child  # type: ignore[return-value]

    def set(self, value: float, **labels: str) -> None:
        self.labels(**labels).set(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        self.labels(**labels).inc(amount)

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.labels(**labels).inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:  # a thread creating a new child resizes the dict
            children = list(self._children.values())
        return sum(c.value for c in children)  # type: ignore[attr-defined]

    class _Child:
        __slots__ = ("value", "_lock")

        def __init__(self) -> None:
            self.value = 0.0
            self._lock = threading.Lock()

        def set(self, value: float) -> None:
            self.value = float(value)  # dflint: disable=DF023 a gauge set is one STORE (no read-modify-write), atomic under the GIL; only inc's += needs the lock

        def inc(self, amount: float = 1.0) -> None:
            with self._lock:  # += is a preemptible read-modify-write
                self.value += amount

    def render(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} gauge"
        if not self._children and not self.label_names:
            yield f"{self.name} 0"
        for key, child in self._snapshot_children():
            yield f"{self.name}{_fmt_labels(self._labels_of(key))} {_fmt_value(child.value)}"  # type: ignore[attr-defined]


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str,
        label_names: Sequence[str],
        buckets: Sequence[float] = _DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))

    def labels(self, **labels: str) -> "Histogram._Child":
        key = self._key(labels)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, Histogram._Child(self.buckets))
        return child  # type: ignore[return-value]

    def observe(self, value: float, **labels: str) -> None:
        self.labels(**labels).observe(value)

    def time(self, **labels: str) -> "_HistTimer":
        return _HistTimer(self.labels(**labels))

    class _Child:
        __slots__ = ("buckets", "counts", "total", "count", "_lock")

        def __init__(self, buckets: tuple[float, ...]):
            self.buckets = buckets
            self.counts = [0] * len(buckets)
            self.total = 0.0
            self.count = 0
            self._lock = threading.Lock()

        def observe(self, value: float) -> None:
            # one lock for the whole observation: sum/count/buckets must
            # move together or a concurrent render sees a torn histogram
            with self._lock:
                self.total += value
                self.count += 1
                for i, b in enumerate(self.buckets):
                    if value <= b:
                        self.counts[i] += 1

    def render(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} histogram"
        for key, child in self._snapshot_children():
            base = self._labels_of(key)
            # snapshot under the child lock: a scrape racing observe() must
            # never see buckets from one observation and sum/count from
            # another (the very torn state the lock exists to prevent)
            with child._lock:  # type: ignore[attr-defined]
                counts = list(child.counts)  # type: ignore[attr-defined]
                count, total = child.count, child.total  # type: ignore[attr-defined]
            for b, c in zip(child.buckets, counts):  # type: ignore[attr-defined]
                lab = dict(base, le=_fmt_value(b))
                yield f"{self.name}_bucket{_fmt_labels(lab)} {c}"
            lab = dict(base, le="+Inf")
            yield f"{self.name}_bucket{_fmt_labels(lab)} {count}"
            yield f"{self.name}_sum{_fmt_labels(base)} {_fmt_value(total)}"
            yield f"{self.name}_count{_fmt_labels(base)} {count}"


class _HistTimer:
    def __init__(self, child: "Histogram._Child"):
        self._child = child
        self._start = 0.0

    def __enter__(self) -> "_HistTimer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._child.observe(time.perf_counter() - self._start)


class MetricsRegistry:
    """Named metric families for one service process."""

    def __init__(self, namespace: str = "dragonfly"):
        self.namespace = namespace
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if existing.kind != metric.kind:
                    raise ValueError(f"metric {metric.name} re-registered as different kind")
                return existing
            self._metrics[metric.name] = metric
            return metric

    def _name(self, subsystem: str, name: str) -> str:
        parts = [p for p in (self.namespace, subsystem, name) if p]
        return "_".join(parts)

    def counter(self, name: str, help_: str = "", *, subsystem: str = "", labels: Sequence[str] = ()) -> Counter:
        return self._register(Counter(self._name(subsystem, name), help_, labels))  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "", *, subsystem: str = "", labels: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(self._name(subsystem, name), help_, labels))  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        help_: str = "",
        *,
        subsystem: str = "",
        labels: Sequence[str] = (),
        buckets: Sequence[float] = _DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._register(Histogram(self._name(subsystem, name), help_, labels, buckets))  # type: ignore[return-value]

    def get(self, full_name: str) -> Optional[_Metric]:
        return self._metrics.get(full_name)

    def families(self) -> list[_Metric]:
        """Snapshot of the registered families (the timeseries recorder walks
        this every sample tick; a service registering a NEW family mid-walk
        must not raise RuntimeError under it)."""
        with self._lock:
            return list(self._metrics.values())

    def render_text(self) -> str:
        lines: list[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].render())
        return "\n".join(lines) + "\n"


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default


def metrics_http_handler(registry: MetricsRegistry | None = None):
    """aiohttp handler for GET /metrics (text/plain; version=0.0.4)."""
    from aiohttp import web

    reg = registry or _default

    async def handler(_req):
        return web.Response(
            text=reg.render_text(),
            content_type="text/plain",
            charset="utf-8",
        )

    return handler
