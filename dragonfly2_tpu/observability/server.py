"""Debug/metrics HTTP endpoint shared by all services.

The reference gives every service a dedicated metrics port plus pprof/statsview
(cmd/dependency/dependency.go:95-102). Equivalent here: a tiny aiohttp app with
  GET /metrics      Prometheus text exposition
  GET /healthz      liveness
  GET /debug/spans  last finished tracing spans as JSON
started via `start_debug_server(port=...)` from any service composition root.
"""

from __future__ import annotations

from aiohttp import web

from dragonfly2_tpu.observability.metrics import MetricsRegistry, default_registry
from dragonfly2_tpu.observability.tracing import Tracer, default_tracer


def make_debug_app(
    registry: MetricsRegistry | None = None, tracer: Tracer | None = None
) -> web.Application:
    from dragonfly2_tpu.observability.metrics import metrics_http_handler

    reg = registry or default_registry()
    tr = tracer or default_tracer()
    app = web.Application()
    metrics = metrics_http_handler(reg)

    async def healthz(_req: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def spans(_req: web.Request) -> web.Response:
        return web.json_response([s.to_dict() for s in tr.finished()])

    app.router.add_get("/metrics", metrics)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/debug/spans", spans)
    return app


class DebugServer:
    def __init__(
        self,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: MetricsRegistry | None = None,
        tracer: Tracer | None = None,
    ):
        self.host = host
        self.port = port
        self._app = make_debug_app(registry, tracer)
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        self._runner = web.AppRunner(self._app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


async def start_debug_server(
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    registry: MetricsRegistry | None = None,
    tracer: Tracer | None = None,
) -> DebugServer:
    srv = DebugServer(host=host, port=port, registry=registry, tracer=tracer)
    await srv.start()
    return srv
