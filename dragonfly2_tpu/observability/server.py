"""Debug/metrics HTTP endpoint shared by all services.

The reference gives every service a dedicated metrics port plus pprof/statsview
(cmd/dependency/dependency.go:95-102). Equivalent here: a tiny aiohttp app with
  GET /metrics            Prometheus text exposition
  GET /healthz            liveness
  GET /debug/spans        last finished tracing spans as JSON
  GET /debug/stacks       every thread's stack + every asyncio task's frame
                          (the /debug/pprof/goroutine analogue)
  GET /debug/profile?seconds=N   cProfile the event-loop thread for N seconds,
                          pstats text by cumulative time (the pprof CPU
                          profile analogue)
started via `start_debug_server(port=...)` from any service composition root.
"""

from __future__ import annotations

from aiohttp import web

from dragonfly2_tpu.observability.metrics import MetricsRegistry, default_registry
from dragonfly2_tpu.observability.tracing import Tracer, default_tracer


def _dump_stacks() -> str:
    """All thread stacks + live asyncio tasks with their awaiting frames."""
    import asyncio
    import sys
    import traceback

    out: list[str] = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ---")
        out.extend(ln.rstrip() for ln in traceback.format_stack(frame))
    try:
        tasks = asyncio.all_tasks()
    except RuntimeError:
        tasks = set()
    out.append(f"--- asyncio tasks ({len(tasks)}) ---")
    for t in tasks:
        out.append(repr(t))
        stack = t.get_stack(limit=8)
        for frame in stack:
            out.extend(
                ln.rstrip() for ln in traceback.format_stack(frame, limit=1)
            )
    return "\n".join(out) + "\n"


def make_debug_app(
    registry: MetricsRegistry | None = None, tracer: Tracer | None = None
) -> web.Application:
    from dragonfly2_tpu.observability.metrics import metrics_http_handler

    reg = registry or default_registry()
    tr = tracer or default_tracer()
    app = web.Application()
    metrics = metrics_http_handler(reg)
    profiling = {"active": False}

    async def healthz(_req: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def spans(_req: web.Request) -> web.Response:
        return web.json_response([s.to_dict() for s in tr.finished()])

    async def stacks(_req: web.Request) -> web.Response:
        return web.Response(text=_dump_stacks(), content_type="text/plain")

    async def profile(req: web.Request) -> web.Response:
        import asyncio
        import cProfile
        import io
        import pstats

        try:
            seconds = min(60.0, max(0.1, float(req.query.get("seconds", "5"))))
        except ValueError:
            raise web.HTTPBadRequest(text="seconds must be a number")
        if profiling["active"]:
            raise web.HTTPConflict(text="a profile is already running")
        profiling["active"] = True
        pr = cProfile.Profile()
        try:
            pr.enable()
            await asyncio.sleep(seconds)
            pr.disable()
        finally:
            profiling["active"] = False
        buf = io.StringIO()
        pstats.Stats(pr, stream=buf).sort_stats("cumulative").print_stats(60)
        return web.Response(text=buf.getvalue(), content_type="text/plain")

    app.router.add_get("/metrics", metrics)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/debug/spans", spans)
    app.router.add_get("/debug/stacks", stacks)
    app.router.add_get("/debug/profile", profile)
    return app


class DebugServer:
    def __init__(
        self,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: MetricsRegistry | None = None,
        tracer: Tracer | None = None,
    ):
        self.host = host
        self.port = port
        self._app = make_debug_app(registry, tracer)
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        self._runner = web.AppRunner(self._app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


async def start_debug_server(
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    registry: MetricsRegistry | None = None,
    tracer: Tracer | None = None,
) -> DebugServer:
    srv = DebugServer(host=host, port=port, registry=registry, tracer=tracer)
    await srv.start()
    return srv
