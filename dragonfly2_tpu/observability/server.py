"""Debug/metrics HTTP endpoint shared by all services.

The reference gives every service a dedicated metrics port plus pprof/statsview
(cmd/dependency/dependency.go:95-102). Equivalent here: a tiny aiohttp app with
  GET /metrics            Prometheus text exposition
  GET /healthz            liveness
  GET /debug/spans        last finished tracing spans as JSON
  GET /debug/loop         event-loop lag + dispatcher-worker utilization
                          (observability.loophealth)
  GET /debug/ts[?name=N&window=S]
                          timeseries recorder (observability.timeseries):
                          no name → recorder stats + series catalog; with a
                          family name → raw ring points plus the windowed
                          rate / histogram summary
  GET /debug/alerts       SLO rule engine state (observability.alerts)
  GET /debug/decisions[?task=T&child=C&limit=N&features=0]
                          sampled scoring decision records + feature-drift
                          state (scheduler processes; `dfml explain` replays
                          these — scheduler/evaluator.DecisionRecorder)
  GET /debug/stacks       every thread's stack + every asyncio task's frame
                          (the /debug/pprof/goroutine analogue)
  GET /debug/profile?seconds=N[&mode=sample&hz=H]
                          mode=cprofile (default): cProfile the event-loop
                          thread, pstats by cumulative time. mode=sample: a
                          sampling profiler over sys._current_frames() that
                          sees EVERY thread — dispatcher workers, hash
                          shards, writers — which cProfile structurally
                          cannot (it hooks only the calling thread)
started via `start_debug_server(port=...)` from any service composition root.
"""

from __future__ import annotations

from aiohttp import web

from dragonfly2_tpu.observability.loophealth import LoopHealthMonitor, default_monitor
from dragonfly2_tpu.observability.metrics import MetricsRegistry, default_registry
from dragonfly2_tpu.observability.tracing import Tracer, default_tracer


def _dump_stacks() -> str:
    """All thread stacks + live asyncio tasks with their awaiting frames."""
    import asyncio
    import sys
    import traceback

    out: list[str] = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ---")
        out.extend(ln.rstrip() for ln in traceback.format_stack(frame))
    try:
        tasks = asyncio.all_tasks()
    except RuntimeError:
        tasks = set()
    out.append(f"--- asyncio tasks ({len(tasks)}) ---")
    for t in tasks:
        out.append(repr(t))
        stack = t.get_stack(limit=8)
        for frame in stack:
            out.extend(
                ln.rstrip() for ln in traceback.format_stack(frame, limit=1)
            )
    return "\n".join(out) + "\n"


def _sample_threads(seconds: float, hz: float) -> str:
    """Sampling profiler over ALL threads (runs on a worker thread so a busy
    event loop cannot starve its own measurement): every 1/hz seconds, grab
    sys._current_frames() and count (thread, function) hits — leaf frame =
    self time, any frame = cumulative. cProfile only instruments the thread
    that enables it, so post-PR 7 round CPU on dispatcher workers was
    invisible to /debug/profile; this mode sees every thread the process
    owns, including libgomp-adjacent native stubs parked in ctypes calls."""
    import sys
    import threading
    import time as _time
    from collections import Counter

    me = threading.get_ident()
    leaf: Counter = Counter()
    cum: Counter = Counter()
    names = {}
    period = 1.0 / hz
    deadline = _time.monotonic() + seconds
    ticks = 0
    while _time.monotonic() < deadline:
        ticks += 1
        names.update({t.ident: t.name for t in threading.enumerate()})
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # the sampler itself is noise
            tname = names.get(tid, str(tid))
            depth = 0
            seen = set()
            while frame is not None and depth < 64:
                code = frame.f_code
                key = (tname, f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{code.co_firstlineno})")
                if depth == 0:
                    leaf[key] += 1
                if key not in seen:  # recursion must not double-count
                    cum[key] += 1
                    seen.add(key)
                frame = frame.f_back
                depth += 1
        _time.sleep(period)
    # percentages are PER-THREAD occupancy (hits / ticks): a function
    # burning 100% of one worker reads 100%, not 100/nthreads — dividing by
    # total thread-samples diluted hot workers by the idle thread count
    out = [
        f"sampling profile: {seconds}s at {hz:.0f} Hz, {ticks} ticks "
        "(pct = fraction of ticks that thread sat in that frame)\n"
    ]
    for title, counter in (("self (leaf frames)", leaf), ("cumulative (any frame)", cum)):
        out.append(f"--- {title} ---")
        for (tname, where), n in counter.most_common(40):
            pct = 100.0 * n / max(1, ticks)
            out.append(f"{pct:6.1f}%  {n:6d}  [{tname}] {where}")
        out.append("")
    return "\n".join(out) + "\n"


def make_debug_app(
    registry: MetricsRegistry | None = None,
    tracer: Tracer | None = None,
    loophealth: LoopHealthMonitor | None = None,
    recorder=None,
    alerts=None,
    decisions=None,
) -> web.Application:
    from dragonfly2_tpu.observability.alerts import default_engine
    from dragonfly2_tpu.observability.metrics import metrics_http_handler
    from dragonfly2_tpu.observability.timeseries import default_recorder

    reg = registry or default_registry()
    tr = tracer or default_tracer()
    lh = loophealth or default_monitor()
    rec = recorder or default_recorder()
    eng = alerts or default_engine()
    app = web.Application()
    metrics = metrics_http_handler(reg)
    profiling = {"active": False}

    async def healthz(_req: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def spans(_req: web.Request) -> web.Response:
        return web.json_response([s.to_dict() for s in tr.finished()])

    async def loop_health(_req: web.Request) -> web.Response:
        return web.json_response(lh.stats())

    async def timeseries(req: web.Request) -> web.Response:
        name = req.query.get("name")
        if not name:
            return web.json_response(
                {"recorder": rec.stats(), "series": rec.series()}
            )
        try:
            window = min(
                rec.retention_s, max(1.0, float(req.query.get("window", "60")))
            )
        except ValueError:
            raise web.HTTPBadRequest(text="window must be a number of seconds")
        out = {
            "name": name,
            "rate_per_s": rec.rate(name, window_s=window),
            "latest": rec.latest(name),
            "histogram": rec.hist_window(name, window_s=window),
            "series": rec.query(name),
        }
        return web.json_response(out)

    async def alerts_status(_req: web.Request) -> web.Response:
        return web.json_response(eng.status())

    async def decision_records(req: web.Request) -> web.Response:
        # decisions: a SchedulerService (composition roots pass theirs) — a
        # non-scheduler process answers with a typed "not here" instead of 404
        # so curl against the wrong port is self-explaining
        if decisions is None:
            return web.json_response(
                {"error": "no decision recorder in this process"}, status=404
            )
        try:
            limit = min(256, max(1, int(req.query.get("limit", "16"))))
        except ValueError:
            raise web.HTTPBadRequest(text="limit must be an integer")
        return web.json_response(decisions.decision_records(
            task_id=req.query.get("task") or None,
            child=req.query.get("child") or None,
            limit=limit,
            with_features=req.query.get("features", "1") != "0",
        ))

    async def stacks(_req: web.Request) -> web.Response:
        return web.Response(text=_dump_stacks(), content_type="text/plain")

    async def profile(req: web.Request) -> web.Response:
        import asyncio
        import cProfile
        import io
        import pstats

        try:
            seconds = min(60.0, max(0.1, float(req.query.get("seconds", "5"))))
            hz = min(1000.0, max(10.0, float(req.query.get("hz", "200"))))
        except ValueError:
            raise web.HTTPBadRequest(text="seconds/hz must be numbers")
        mode = req.query.get("mode", "cprofile")
        if mode not in ("cprofile", "sample"):
            raise web.HTTPBadRequest(text="mode must be cprofile or sample")
        if profiling["active"]:
            raise web.HTTPConflict(text="a profile is already running")
        profiling["active"] = True
        try:
            if mode == "sample":
                text = await asyncio.to_thread(_sample_threads, seconds, hz)
                return web.Response(text=text, content_type="text/plain")
            pr = cProfile.Profile()
            pr.enable()
            await asyncio.sleep(seconds)
            pr.disable()
        finally:
            profiling["active"] = False
        buf = io.StringIO()
        pstats.Stats(pr, stream=buf).sort_stats("cumulative").print_stats(60)
        return web.Response(text=buf.getvalue(), content_type="text/plain")

    app.router.add_get("/metrics", metrics)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/debug/spans", spans)
    app.router.add_get("/debug/loop", loop_health)
    app.router.add_get("/debug/ts", timeseries)
    app.router.add_get("/debug/alerts", alerts_status)
    app.router.add_get("/debug/decisions", decision_records)
    app.router.add_get("/debug/stacks", stacks)
    app.router.add_get("/debug/profile", profile)
    return app


class DebugServer:
    def __init__(
        self,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: MetricsRegistry | None = None,
        tracer: Tracer | None = None,
        loophealth: LoopHealthMonitor | None = None,
        recorder=None,
        alerts=None,
        decisions=None,
    ):
        self.host = host
        self.port = port
        self._app = make_debug_app(
            registry, tracer, loophealth, recorder, alerts, decisions
        )
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        self._runner = web.AppRunner(self._app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


async def start_debug_server(
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    registry: MetricsRegistry | None = None,
    tracer: Tracer | None = None,
    loophealth: LoopHealthMonitor | None = None,
    recorder=None,
    alerts=None,
    decisions=None,
) -> DebugServer:
    srv = DebugServer(
        host=host, port=port, registry=registry, tracer=tracer,
        loophealth=loophealth, recorder=recorder, alerts=alerts,
        decisions=decisions,
    )
    await srv.start()
    return srv
