"""Observability: metrics registry (Prometheus text exposition) + span tracing.

Parity with the reference's Prometheus-per-service + OpenTelemetry-everywhere
stance (SURVEY.md §5; scheduler/metrics/metrics.go:46-179,
client/daemon/metrics/metrics.go, cmd/dependency/dependency.go:39,73 jaeger
bootstrap) — built dependency-free: a small typed registry with text
exposition, and a contextvar-based tracer writing JSON-lines spans.
"""

from dragonfly2_tpu.observability.alerts import AlertEngine, AlertRule, default_engine
from dragonfly2_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from dragonfly2_tpu.observability.timeseries import (
    MetricsRecorder,
    build_stats_frame,
    default_recorder,
)
from dragonfly2_tpu.observability.tracing import Span, Tracer, default_tracer

__all__ = [
    "AlertEngine",
    "AlertRule",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRecorder",
    "MetricsRegistry",
    "build_stats_frame",
    "default_engine",
    "default_recorder",
    "default_registry",
    "Span",
    "Tracer",
    "default_tracer",
]
