"""Contextvar span tracer with JSON-lines export and wire propagation.

Parity with the reference's OpenTelemetry usage (otelgrpc interceptors on
every RPC chain, explicit spans with typed attributes on peer tasks and
preheat jobs — peertask_conductor.go:182-208, manager/job/preheat.go:91-93,
client/config/constants_otel.go). Dependency-free design:

- `Tracer.span(name, **attrs)` opens a child of the current contextvar span;
  nesting follows Python async context automatically.
- Trace context propagates across processes as a `{"trace_id", "span_id"}`
  dict carried in RPC payloads / HTTP headers (W3C-traceparent-shaped ids).
- Finished spans go to an exporter: in-memory ring (tests, /debug) and/or
  JSON-lines file (the jaeger-exporter stand-in — one dict per span with
  trace_id, span_id, parent_id, name, start, duration_ms, attrs, status).
"""

from __future__ import annotations

import contextvars
import json
import os
import secrets
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "dragonfly_current_span", default=None
)

TRACEPARENT_HEADER = "traceparent"


def _gen_trace_id() -> str:
    return secrets.token_hex(16)


def _gen_span_id() -> str:
    return secrets.token_hex(8)


@dataclass
class SpanContext:
    trace_id: str
    span_id: str

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_dict(cls, d: Mapping[str, Any] | None) -> Optional["SpanContext"]:
        if not d or "trace_id" not in d:
            return None
        return cls(trace_id=str(d["trace_id"]), span_id=str(d.get("span_id", "")))

    @classmethod
    def from_traceparent(cls, header: str | None) -> Optional["SpanContext"]:
        if not header:
            return None
        parts = header.split("-")
        if len(parts) != 4:
            return None
        return cls(trace_id=parts[1], span_id=parts[2])


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start", "end",
        "attrs", "status", "error", "_tracer", "_token",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        parent_id: str,
        attrs: dict[str, Any],
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _gen_span_id()
        self.parent_id = parent_id
        self.start = time.time()
        self.end = 0.0
        self.attrs = attrs
        self.status = "ok"
        self.error = ""
        self._tracer = tracer
        self._token: Optional[contextvars.Token] = None

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.status = "error"
            self.error = f"{exc_type.__name__}: {exc}"
        self.end = time.time()
        if self._token is not None:
            _current_span.reset(self._token)
        self._tracer._export(self)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "duration_ms": round((self.end - self.start) * 1000, 3),
            "attrs": self.attrs,
            "status": self.status,
            "error": self.error,
        }


@dataclass
class Tracer:
    """Per-process tracer. `service` tags every span; spans export to an
    in-memory ring always, and to a JSON-lines file when `path` is set
    (DRAGONFLY_TRACE_FILE env overrides)."""

    service: str = "dragonfly"
    path: str = ""
    ring_size: int = 2048
    _ring: deque = field(default_factory=lambda: deque(maxlen=2048), repr=False)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)
    _fh: Any = field(default=None, repr=False)

    def __post_init__(self) -> None:
        self._ring = deque(maxlen=self.ring_size)
        self.path = self.path or os.environ.get("DRAGONFLY_TRACE_FILE", "")

    def span(self, name: str, parent: SpanContext | None = None, **attrs: Any) -> Span:
        """Open a span. Parent resolution: explicit remote context > current
        contextvar span > new root."""
        cur = _current_span.get()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif cur is not None:
            trace_id, parent_id = cur.trace_id, cur.span_id
        else:
            trace_id, parent_id = _gen_trace_id(), ""
        attrs.setdefault("service", self.service)
        return Span(self, name, trace_id, parent_id, attrs)

    @staticmethod
    def current() -> Optional[Span]:
        return _current_span.get()

    @staticmethod
    def current_context() -> Optional[SpanContext]:
        s = _current_span.get()
        return s.context if s is not None else None

    def _export(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)
            if self.path:
                if self._fh is None:
                    # line-buffered writes, flushed by the OS page cache; no
                    # per-span fsync/flush so exporting never stalls the
                    # event loop on a contended disk
                    self._fh = open(self.path, "a", encoding="utf-8", buffering=1 << 16)
                self._fh.write(json.dumps(span.to_dict()) + "\n")

    def finished(self) -> list[Span]:
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None


_default = Tracer()


def default_tracer() -> Tracer:
    return _default
