"""Contextvar span tracer with JSON-lines export and wire propagation.

Parity with the reference's OpenTelemetry usage (otelgrpc interceptors on
every RPC chain, explicit spans with typed attributes on peer tasks and
preheat jobs — peertask_conductor.go:182-208, manager/job/preheat.go:91-93,
client/config/constants_otel.go). Dependency-free design:

- `Tracer.span(name, **attrs)` opens a child of the current contextvar span;
  nesting follows Python async context automatically.
- Trace context propagates across processes as a W3C traceparent string
  (rpc/core.py carries it in the frame's "t" key; the HTTP piece/metadata
  paths carry the standard `traceparent` header).
- Head-based sampling: the ROOT span draws once against `sample_rate` and
  every descendant — local child or remote continuation — inherits the
  decision through the context's sampled flag (the traceparent trace-flags
  byte), so a trace is recorded all-or-nothing across the cluster. An
  unsampled span costs an object + a contextvar set/reset and nothing else:
  no id generation, no clock reads, no export.
- Finished sampled spans go to an exporter: in-memory ring (tests, /debug)
  and/or JSON-lines file (the jaeger-exporter stand-in — one dict per span
  with trace_id, span_id, parent_id, name, start, duration_ms, attrs,
  status), and/or OTLP/JSON batches (file or collector endpoint).
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import queue as queue_mod
import random
import secrets
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "dragonfly_current_span", default=None
)

TRACEPARENT_HEADER = "traceparent"

# Sample rate service composition roots apply when the config carries none:
# 1-in-100 traces recorded end to end, the rest cost one unsampled-root draw
# per entry point. Library/test Tracer() instances keep sample_rate=1.0.
DEFAULT_SERVICE_SAMPLE_RATE = 0.01


def _gen_trace_id() -> str:
    return secrets.token_hex(16)


def _gen_span_id() -> str:
    return secrets.token_hex(8)


@dataclass
class SpanContext:
    trace_id: str
    span_id: str
    sampled: bool = True

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "sampled": self.sampled,
        }

    def traceparent(self) -> str:
        # trace-flags 01 = sampled (W3C trace context); the flag IS the
        # all-or-nothing head-sampling decision riding the wire
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"

    @classmethod
    def from_dict(cls, d: Mapping[str, Any] | None) -> Optional["SpanContext"]:
        if not d or "trace_id" not in d:
            return None
        return cls(
            trace_id=str(d["trace_id"]),
            span_id=str(d.get("span_id", "")),
            sampled=bool(d.get("sampled", True)),
        )

    @classmethod
    def from_traceparent(cls, header: str | None) -> Optional["SpanContext"]:
        if not header:
            return None
        parts = header.split("-")
        if len(parts) != 4:
            return None
        return cls(
            trace_id=parts[1],
            span_id=parts[2],
            sampled=parts[3] != "00",
        )


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start", "end",
        "attrs", "status", "error", "sampled", "_tracer", "_token",
    )

    def __init__(
        self,
        tracer: "Tracer",
        name: str,
        trace_id: str,
        parent_id: str,
        attrs: dict[str, Any],
        sampled: bool = True,
    ):
        self.name = name
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.sampled = sampled
        if sampled:
            self.span_id = _gen_span_id()
            self.start = time.time()
        else:
            # unsampled spans still hold the trace lineage for propagation
            # (children and remote continuations inherit the decision) but
            # skip id generation and clock reads — this is what makes the
            # unsampled hot path cost an object + contextvar churn only
            self.span_id = ""
            self.start = 0.0
        self.end = 0.0
        self.attrs = attrs
        self.status = "ok"
        self.error = ""
        self._tracer = tracer
        self._token: Optional[contextvars.Token] = None

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, self.sampled)

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._token is not None:
            _current_span.reset(self._token)
        if not self.sampled:
            return
        if exc is not None:
            self.status = "error"
            self.error = f"{exc_type.__name__}: {exc}"
        self.end = time.time()
        self._tracer._export(self)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "duration_ms": round((self.end - self.start) * 1000, 3),
            "attrs": self.attrs,
            "status": self.status,
            "error": self.error,
        }


def _otlp_value(v: Any) -> dict:
    """Python value → OTLP AnyValue."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # int64 is a JSON string per OTLP spec
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def spans_to_otlp(spans: list["Span"], service: str) -> dict:
    """Batch of finished spans → one OTLP/JSON ExportTraceServiceRequest —
    the body Jaeger's (and any collector's) OTLP HTTP ingest accepts on
    POST /v1/traces (the reference bootstraps a Jaeger exporter via --jaeger,
    cmd/dependency/dependency.go:72-95; this is its collector-compatible
    equivalent without an SDK dependency)."""
    status_code = {"ok": 1, "error": 2}
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": [
                        {"key": "service.name", "value": {"stringValue": service}}
                    ]
                },
                "scopeSpans": [
                    {
                        "scope": {"name": "dragonfly2_tpu.observability"},
                        "spans": [
                            {
                                "traceId": s.trace_id,
                                "spanId": s.span_id,
                                **(
                                    {"parentSpanId": s.parent_id}
                                    if s.parent_id
                                    else {}
                                ),
                                "name": s.name,
                                "kind": 1,  # SPAN_KIND_INTERNAL
                                "startTimeUnixNano": str(int(s.start * 1e9)),
                                "endTimeUnixNano": str(int(s.end * 1e9)),
                                "attributes": [
                                    {"key": k, "value": _otlp_value(v)}
                                    for k, v in s.attrs.items()
                                ],
                                "status": (
                                    {"code": status_code.get(s.status, 0)}
                                    | ({"message": s.error} if s.error else {})
                                ),
                            }
                            for s in spans
                        ],
                    }
                ],
            }
        ]
    }


@dataclass
class Tracer:
    """Per-process tracer. `service` tags every span; spans export to an
    in-memory ring always, to a JSON-lines file when `path` is set
    (DRAGONFLY_TRACE_FILE env overrides), and — when `otlp_path` or
    `otlp_endpoint` is set — as OTLP/JSON ExportTraceServiceRequest batches
    (one request per line in the file; HTTP POST to <endpoint>/v1/traces for
    the endpoint, e.g. a Jaeger collector's OTLP port).

    `sample_rate` is the head-sampling probability drawn ONCE per root span;
    descendants (local and remote) inherit the decision. 1.0 records
    everything (library/test default), 0.0 records nothing while keeping
    propagation wired; service boots default to
    DEFAULT_SERVICE_SAMPLE_RATE via configure_default_tracer."""

    service: str = "dragonfly"
    path: str = ""
    otlp_path: str = ""
    otlp_endpoint: str = ""
    otlp_batch: int = 64
    otlp_max_age_s: float = 10.0  # flush a partial batch once its oldest span ages past this
    ring_size: int = 2048
    sample_rate: float = 1.0
    rng: Any = None  # random.random-compatible draw source (tests seed it)
    _ring: deque = field(default_factory=lambda: deque(maxlen=2048), repr=False)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)
    _fh: Any = field(default=None, repr=False)
    _otlp_fh: Any = field(default=None, repr=False)
    _otlp_buf: list = field(default_factory=list, repr=False)
    _otlp_buf_since: float = field(default=0.0, repr=False)
    _otlp_queue: Any = field(default=None, repr=False)
    _otlp_worker: Any = field(default=None, repr=False)

    def __post_init__(self) -> None:
        self._ring = deque(maxlen=self.ring_size)
        self.path = self.path or os.environ.get("DRAGONFLY_TRACE_FILE", "")
        self.otlp_path = self.otlp_path or os.environ.get("DRAGONFLY_OTLP_FILE", "")
        self.otlp_endpoint = self.otlp_endpoint or os.environ.get(
            "DRAGONFLY_OTLP_ENDPOINT", ""
        )
        env_rate = os.environ.get("DRAGONFLY_TRACE_SAMPLE", "")
        if env_rate:
            try:
                self.sample_rate = min(1.0, max(0.0, float(env_rate)))
            except ValueError:
                pass
        if self.rng is None:
            self.rng = random.random

    def span(self, name: str, parent: SpanContext | None = None, **attrs: Any) -> Span:
        """Open a span. Parent resolution: explicit remote context > current
        contextvar span > new root. The sampling decision is made at the
        root only — children inherit it, which is what makes a trace
        all-or-nothing across processes."""
        cur = _current_span.get()
        if parent is not None:
            trace_id, parent_id, sampled = parent.trace_id, parent.span_id, parent.sampled
        elif cur is not None:
            trace_id, parent_id, sampled = cur.trace_id, cur.span_id, cur.sampled
        else:
            sampled = self.sample_rate >= 1.0 or (
                self.sample_rate > 0.0 and self.rng() < self.sample_rate
            )
            if sampled:
                trace_id, parent_id = _gen_trace_id(), ""
            else:
                # lineage id still propagates downstream so remote peers see
                # a context (and its not-sampled flag) rather than opening
                # fresh roots of their own; a cheap counter-free id suffices
                trace_id, parent_id = "0" * 32, ""
        if sampled:
            attrs.setdefault("service", self.service)
        return Span(self, name, trace_id, parent_id, attrs, sampled)

    @staticmethod
    def current() -> Optional[Span]:
        return _current_span.get()

    @staticmethod
    def current_context() -> Optional[SpanContext]:
        s = _current_span.get()
        return s.context if s is not None else None

    def _export(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)
            if self.path:
                if self._fh is None:
                    self._fh = open(self.path, "a", encoding="utf-8", buffering=1 << 16)
                    # the exporter worker flushes this fh on its poll tick:
                    # per-span flushes would stall the loop on a contended
                    # disk, but a LIVE service's span file must be readable
                    # by dftrace within ~a second — 64 KiB of spans sitting
                    # in the userspace buffer until process exit made the
                    # file useless mid-incident (found in verification)
                    self._ensure_otlp_worker()
                self._fh.write(json.dumps(span.to_dict()) + "\n")
            if self.otlp_path or self.otlp_endpoint:
                if not self._otlp_buf:
                    self._otlp_buf_since = time.monotonic()
                    # the single long-lived exporter worker owns the age
                    # flush (its queue wait doubles as the age timer) — the
                    # earlier shape started one threading.Timer per batch,
                    # thread churn on every partial batch (DF026's smell)
                    self._ensure_otlp_worker()
                self._otlp_buf.append(span)
                if len(self._otlp_buf) >= self.otlp_batch:
                    self._flush_otlp_locked()

    def _flush_otlp_locked(self, *, sync: bool = False) -> None:
        if not self._otlp_buf:
            return
        batch, self._otlp_buf = self._otlp_buf, []
        req = spans_to_otlp(batch, self.service)
        if self.otlp_path:
            if self._otlp_fh is None:
                self._otlp_fh = open(
                    self.otlp_path, "a", encoding="utf-8", buffering=1 << 16
                )
            self._otlp_fh.write(json.dumps(req) + "\n")
        if self.otlp_endpoint:
            if sync:
                # shutdown path: POST in the caller's thread so the final
                # batch lands before the interpreter exits
                self._post_otlp(req)
            else:
                # ONE long-lived exporter thread drains a bounded queue: a
                # slow/unreachable collector must cost a constant (dropped
                # batches), never an unbounded thread pile-up
                self._ensure_otlp_worker()
                try:
                    self._otlp_queue.put_nowait(req)
                except queue_mod.Full:  # drop the batch, don't block the loop
                    pass

    def _ensure_otlp_worker(self) -> None:
        if self._otlp_worker is None or not self._otlp_worker.is_alive():
            if self._otlp_queue is None:
                self._otlp_queue = queue_mod.Queue(maxsize=64)
            self._otlp_worker = threading.Thread(
                target=self._otlp_worker_loop, daemon=True
            )
            self._otlp_worker.start()

    def _otlp_worker_loop(self) -> None:
        """The single exporter worker: drains POST batches AND serves every
        time-based flush — the OTLP age flush (a partial batch that never
        reaches otlp_batch still exports within ~otlp_max_age_s; no
        per-batch timer threads) and the buffered file handles (span/OTLP
        files stay dftrace-readable while the process runs)."""
        poll = max(0.05, min(self.otlp_max_age_s / 4.0, 1.0))
        while True:
            try:
                req = self._otlp_queue.get(timeout=poll)
            except queue_mod.Empty:
                with self._lock:
                    if (
                        self._otlp_buf
                        and time.monotonic() - self._otlp_buf_since
                        >= self.otlp_max_age_s
                    ):
                        self._flush_otlp_locked()
                    if self._otlp_fh is not None:
                        self._otlp_fh.flush()
                    if self._fh is not None:
                        self._fh.flush()
                continue
            if req is None:
                return
            self._post_otlp(req)

    def _post_otlp(self, req: dict) -> None:
        import urllib.request

        try:
            r = urllib.request.Request(
                self.otlp_endpoint.rstrip("/") + "/v1/traces",
                data=json.dumps(req).encode(),
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(r, timeout=10).close()
        except Exception as e:  # noqa: BLE001 — tracing must never take a service down
            logging.getLogger(__name__).debug("otlp export failed: %s", e)

    def flush_otlp(self, *, sync: bool = False) -> None:
        """Force out any buffered OTLP batch (shutdown / tests)."""
        with self._lock:
            self._flush_otlp_locked(sync=sync)
            if self._otlp_fh is not None:
                self._otlp_fh.flush()

    def finished(self) -> list[Span]:
        with self._lock:
            return list(self._ring)

    def close(self) -> None:
        with self._lock:
            self._flush_otlp_locked(sync=True)
        # sentinel + join OUTSIDE the lock: the worker's idle tick takes the
        # same lock, so holding it here would deadline-race the join — a
        # slow collector during the sync flush above would leave the worker
        # parked on the lock, unable to consume the sentinel, and every
        # process exit would burn the full join timeout
        if self._otlp_worker is not None and self._otlp_queue is not None:
            self._otlp_queue.put(None)  # drain-then-exit sentinel
            self._otlp_worker.join(timeout=10)
            self._otlp_worker = None
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None
            if self._otlp_fh is not None:
                self._otlp_fh.flush()
                self._otlp_fh.close()
                self._otlp_fh = None


_default = Tracer()


def default_tracer() -> Tracer:
    return _default


from dragonfly2_tpu.utils.config import cfgfield  # noqa: E402 — section schema below


@dataclass
class TracingSection:
    """YAML `tracing:` section shared by scheduler/daemon/manager configs —
    the validated-config equivalent of the reference's --jaeger flag
    (cmd/dependency/dependency.go:72-95)."""

    otlp_file: Optional[str] = cfgfield(
        None, help="append OTLP/JSON trace batches to this file"
    )
    otlp_endpoint: Optional[str] = cfgfield(
        None,
        help="POST OTLP/JSON batches to this collector base URL "
             "(e.g. http://jaeger:4318)",
    )
    trace_file: Optional[str] = cfgfield(
        None, help="append finished spans as JSON lines to this file "
                   "(the dftrace input format)"
    )
    sample_rate: Optional[float] = cfgfield(
        None, minimum=0.0, maximum=1.0,
        help="head-sampling probability per trace root (default 0.01; "
             "1.0 records everything, 0.0 disables recording)",
    )


def configure_default_tracer(
    service: str = "",
    *,
    otlp_file: str | None = None,
    otlp_endpoint: str | None = None,
    trace_file: str | None = None,
    sample_rate: float | None = None,
) -> Tracer:
    """Apply config-surface tracing options to the process tracer at boot.
    Registers an atexit close so partially-filled OTLP batches flush on
    shutdown — a low-traffic process must not export nothing. Service boots
    get head sampling at DEFAULT_SERVICE_SAMPLE_RATE unless the config (or
    DRAGONFLY_TRACE_SAMPLE) says otherwise."""
    import atexit

    t = default_tracer()
    if service:
        t.service = service
    if otlp_file:
        t.otlp_path = otlp_file
    if otlp_endpoint:
        t.otlp_endpoint = otlp_endpoint
    if trace_file:
        t.path = trace_file
    if sample_rate is not None:
        t.sample_rate = min(1.0, max(0.0, sample_rate))
    elif not os.environ.get("DRAGONFLY_TRACE_SAMPLE"):
        t.sample_rate = DEFAULT_SERVICE_SAMPLE_RATE
    # condition on the tracer's RESOLVED outputs, not the arguments: exports
    # configured via DRAGONFLY_TRACE_FILE/DRAGONFLY_OTLP_* env (no config
    # args) must flush at exit too, or their buffered tails are lost
    if t.path or t.otlp_path or t.otlp_endpoint:
        atexit.register(t.close)
    return t
