"""Neighbor gather + masked mean: the GraphSAGE aggregation hot op.

Graph layout is TPU-first (SURVEY.md §7): instead of the reference's Redis
FIFO probe lists per (src, dst) edge (scheduler/networktopology/probes.go),
the topology graph is a *dense padded neighbor table* — `neighbors[N, K]`
int32 with a boolean mask — so aggregation is static-shaped gather + masked
mean + matmul, all of which XLA tiles onto the MXU with no dynamic shapes.

The XLA path below is the default; ops.neighbor_agg_pallas holds the fused
MXU kernel for the same contract, auto-selected by `neighbor_aggregate`
on TPU for VMEM-sized graphs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def neighbor_gather(h: jnp.ndarray, neighbors: jnp.ndarray) -> jnp.ndarray:
    """Gather node states for each padded neighbor slot.

    h: [N, H] node states; neighbors: [N, K] int32 indices (padding may point
    anywhere valid, typically 0 — the mask zeroes its contribution).
    Returns [N, K, H].
    """
    return jnp.take(h, neighbors, axis=0)


def masked_mean(x: jnp.ndarray, mask: jnp.ndarray, *, eps: float = 1e-6) -> jnp.ndarray:
    """Mean over axis 1 counting only mask==1 slots. x: [N, K, H], mask: [N, K]."""
    m = mask.astype(x.dtype)[..., None]
    total = jnp.sum(x * m, axis=1)
    count = jnp.sum(m, axis=1)
    return total / (count + eps)


def neighbor_aggregate(
    h: jnp.ndarray, neighbors: jnp.ndarray, mask: jnp.ndarray, *, impl: str = "auto"
) -> jnp.ndarray:
    """Gather + masked mean: [N, H] -> [N, H] neighborhood means.

    impl: "auto" (Pallas on TPU when the graph fits VMEM, else XLA),
    "pallas", or "xla".
    """
    if impl != "xla":
        from dragonfly2_tpu.ops import neighbor_agg_pallas as pk

        if impl == "pallas" or (impl == "auto" and pk.supports_pallas(h)):
            return pk.neighbor_aggregate_pallas(h, neighbors, mask)
    return masked_mean(neighbor_gather(h, neighbors), mask)


def segment_mean(values: jnp.ndarray, segment_ids: jnp.ndarray, num_segments: int) -> jnp.ndarray:
    """COO-style aggregation for data prep: mean of values rows per segment.

    Used when building the padded neighbor table from raw probe records
    (edge list form), not in the training step itself.
    """
    total = jax.ops.segment_sum(values, segment_ids, num_segments)
    count = jax.ops.segment_sum(jnp.ones_like(values[..., :1]), segment_ids, num_segments)
    return total / jnp.maximum(count, 1.0)
