"""TPU kernels for the GNN hot ops (XLA reference paths + Pallas variants)."""

from dragonfly2_tpu.ops.neighbor_agg import masked_mean, neighbor_gather  # noqa: F401
