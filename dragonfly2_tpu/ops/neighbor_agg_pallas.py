"""Fused Pallas TPU kernel for neighbor gather + masked mean.

Same contract as ops.neighbor_agg.neighbor_aggregate ([N, H] states,
[N, K] padded neighbor table + mask → [N, H] neighborhood means), fused so
the [N, K, H] gathered intermediate never exists in HBM.

Formulation is MXU-native (no per-row dynamic gathers, which Mosaic lowers
poorly): each grid step owns a TILE_N row block, builds a sparse selection
matrix A[TILE_N, N] where A[r, c] = #times node c appears as a masked-in
neighbor of row r (K static one-hot compares on the VPU), then computes the
neighborhood *sums* as one A @ h matmul on the MXU and divides by the mask
count. FLOP cost is TILE_N·N·H per tile — wasteful versus a perfect gather
(density K/N) but it rides the 128×128 systolic array instead of scalar
loads; it wins whenever h fits VMEM (clusters up to a few thousand hosts,
the scheduler's whole operating range — guarded by MAX_PALLAS_NODES).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE_N = 128
# A[TILE_N, N] + h[N, H] + out[TILE_N, H] must fit VMEM together; budget
# conservatively at 12 MB of the ~16 MB. Past that the XLA gather path wins
# anyway (selection matrix density collapses).
VMEM_BUDGET_BYTES = 12 << 20


def _agg_kernel(nbr_ref, mask_ref, h_ref, out_ref, *, k: int, eps: float):
    """One row-tile: A = Σ_k onehot(nbr[:, k])·mask[:, k]; out = A@h / count."""
    n = h_ref.shape[0]
    tile = nbr_ref.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (tile, n), 1)
    acc = jnp.zeros((tile, n), jnp.float32)
    for kk in range(k):  # K is small and static: unrolled VPU compares
        idx = nbr_ref[:, kk][:, None]  # [tile, 1]
        m = mask_ref[:, kk][:, None].astype(jnp.float32)
        acc = acc + jnp.where(col == idx, m, 0.0)  # dflint: disable=DF012 K<=16 static unroll IS the kernel design
    sums = jnp.dot(acc, h_ref[:].astype(jnp.float32), preferred_element_type=jnp.float32)
    count = jnp.sum(mask_ref[:].astype(jnp.float32), axis=1, keepdims=True)
    out_ref[:] = (sums / (count + eps)).astype(out_ref.dtype)


def _forward(h, neighbors, mask, *, eps: float, interpret: bool):
    n, hdim = h.shape
    k = neighbors.shape[1]
    n_pad = max(TILE_N, ((n + TILE_N - 1) // TILE_N) * TILE_N)
    nbr = jnp.zeros((n_pad, k), jnp.int32).at[:n].set(neighbors.astype(jnp.int32))
    msk = jnp.zeros((n_pad, k), jnp.float32).at[:n].set(mask.astype(jnp.float32))

    grid = (n_pad // TILE_N,)
    out = pl.pallas_call(
        functools.partial(_agg_kernel, k=k, eps=eps),
        out_shape=jax.ShapeDtypeStruct((n_pad, hdim), h.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE_N, k), lambda i: (i, 0)),
            pl.BlockSpec((TILE_N, k), lambda i: (i, 0)),
            pl.BlockSpec((n, hdim), lambda i: (0, 0)),  # full h every tile
        ],
        out_specs=pl.BlockSpec((TILE_N, hdim), lambda i: (i, 0)),
        interpret=interpret,
    )(nbr, msk, h)
    return out[:n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _agg(h, neighbors, mask, eps, interpret):
    return _forward(h, neighbors, mask, eps=eps, interpret=interpret)


def _agg_fwd(h, neighbors, mask, eps, interpret):
    return _forward(h, neighbors, mask, eps=eps, interpret=interpret), (h.shape, neighbors, mask)


def _agg_bwd(eps, interpret, res, g):
    """d/dh of the masked mean: scatter-add of g rows, weighted by mask/count.
    XLA segment_sum is the right tool for the (sparse, irregular) backward —
    the MXU trick only pays off in the dense forward."""
    (n, hdim), neighbors, mask = res
    count = jnp.sum(mask.astype(g.dtype), axis=1, keepdims=True) + eps  # [N, 1]
    contrib = (g / count)[:, None, :] * mask.astype(g.dtype)[:, :, None]  # [N, K, H]
    gh = jax.ops.segment_sum(
        contrib.reshape(-1, hdim), neighbors.reshape(-1).astype(jnp.int32), num_segments=n
    ).astype(g.dtype)
    return gh, None, None


_agg.defvjp(_agg_fwd, _agg_bwd)


def neighbor_aggregate_pallas(
    h: jnp.ndarray,
    neighbors: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    eps: float = 1e-6,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused [N, H] -> [N, H] masked neighborhood mean on TPU via Pallas.

    Differentiable w.r.t. h (custom VJP; backward runs the XLA scatter path).
    """
    return _agg(h, neighbors, mask, eps, interpret)


def supports_pallas(h: jnp.ndarray) -> bool:
    """True when the fused kernel applies: TPU backend + VMEM-sized working
    set (accumulator tile + full h + output tile)."""
    n, hdim = h.shape
    n_pad = max(TILE_N, ((n + TILE_N - 1) // TILE_N) * TILE_N)
    itemsize = 4  # accumulator is f32; h tile counted at its own width below
    working_set = (
        TILE_N * n_pad * 4          # selection matrix A (f32)
        + n * hdim * h.dtype.itemsize  # full node states
        + TILE_N * hdim * itemsize  # output tile
    )
    if working_set > VMEM_BUDGET_BYTES:
        return False
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:  # pragma: no cover - no backend at all
        return False
