"""Trainer RPC adapters (ref pkg/rpc/trainer client/server: the Train
client-stream contract, server.go:41-90, unrolled as open/chunk/close)."""

from __future__ import annotations

from typing import Any

import numpy as np

from dragonfly2_tpu.rpc.core import RpcClient, RpcServer
from dragonfly2_tpu.trainer.service import TrainerService, pack_records

TRAINER_METHODS = [
    "train_open", "train_chunk", "train_close", "status", "train_history",
]


def register_trainer(server: RpcServer, service: TrainerService) -> None:
    server.register_service(service, TRAINER_METHODS)


class RemoteTrainerClient:
    def __init__(self, address: str, **kw: Any):
        self._c = RpcClient(address, **kw)

    async def close(self) -> None:
        await self._c.close()

    async def healthy(self) -> bool:
        return await self._c.healthy()

    async def train_open(self, hostname: str = "", scheduler_id: int = 0) -> str:
        out = await self._c.call("train_open", {"hostname": hostname, "scheduler_id": scheduler_id})
        return out["token"]

    async def train_chunk(self, token: str, kind: str, records: np.ndarray) -> int:
        out = await self._c.call(
            "train_chunk", {"token": token, "kind": kind, "data": pack_records(records)}
        )
        return out["rows"]

    async def train_close(self, token: str) -> None:
        await self._c.call("train_close", {"token": token})

    async def status(self) -> dict:
        return await self._c.call("status")

    async def train_history(
        self, *, limit: int = 64, with_curves: bool = True
    ) -> dict:
        """Per-run manifests (ISSUE 15): run id, dataset size, per-model
        steps / final loss / bounded loss curve, wall seconds."""
        return await self._c.call(
            "train_history", {"limit": limit, "with_curves": with_curves}
        )
