"""Wire RPC: msgpack-framed asyncio protocol (unary, multiplexed).

Reference equivalent: pkg/rpc — per-service gRPC client/server wrappers with
interceptor chains (scheduler/server/server.go:43-44 rate limits, retry,
logging). Redesigned: a compact length-prefixed msgpack protocol over
TCP/unix sockets with per-connection multiplexing, retry with linear backoff,
QPS limiting, and keepalive — no protoc codegen step, and the message schema
is the service dataclasses themselves.
"""

from dragonfly2_tpu.rpc.core import RpcClient, RpcError, RpcServer  # noqa: F401
