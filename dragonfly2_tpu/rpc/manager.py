"""Manager RPC adapters: wire client + server over rpc.core.

Reference equivalent: pkg/rpc/manager/{client,server} (client_v1/v2: the
GetScheduler/ListSchedulers/UpdateScheduler/KeepAlive surface schedulers and
daemons call, manager/rpcserver/manager_server_v2.go:95-746).
"""

from __future__ import annotations

from typing import Any, Optional

from dragonfly2_tpu.manager.service import ManagerService
from dragonfly2_tpu.rpc.core import RpcClient, RpcServer

MANAGER_METHODS = [
    "list_schedulers",
    "get_scheduler",
    "update_scheduler",
    "update_seed_peer",
    "keepalive",
    "cluster_config",
    "report_stats",
    "cluster_stats",
    "create_model",
    "activate_model",
    "active_model",
    "list_models",
    "publish_model",
    "promote_model",
    "reject_model",
    "rollback_model",
    "report_shadow",
    "rollout_status",
    "list_applications",
    "get_config",
    "set_config",
    "create_job",
    "job_state",
    "pull_job",
    "complete_job",
    "issue_certificate",
]


class ManagerRpcAdapter:
    """Server side: msgpack payloads -> ManagerService calls."""

    def __init__(self, service: ManagerService, jobs: Any = None):
        self.svc = service
        self.jobs = jobs  # manager.jobs.JobQueue, wired by server

    async def list_schedulers(self, p: dict) -> list[dict]:
        return self.svc.list_schedulers(p.get("ip", ""), p.get("conditions"))

    async def get_scheduler(self, p: dict) -> Optional[dict]:
        return self.svc.get_scheduler(p["hostname"], p["scheduler_cluster_id"])

    async def update_scheduler(self, p: dict) -> dict:
        return self.svc.update_scheduler(
            p["hostname"], p["ip"], p["port"],
            scheduler_cluster_id=p.get("scheduler_cluster_id"),
            idc=p.get("idc", ""), location=p.get("location", ""),
            features=p.get("features"),
        )

    async def update_seed_peer(self, p: dict) -> dict:
        return self.svc.update_seed_peer(
            p["hostname"], p["ip"], p["port"],
            download_port=p.get("download_port", 0),
            object_storage_port=p.get("object_storage_port", 0),
            seed_peer_cluster_id=p.get("seed_peer_cluster_id"),
            peer_type=p.get("type", "super"),
            idc=p.get("idc", ""), location=p.get("location", ""),
        )

    async def keepalive(self, p: dict) -> bool:
        return self.svc.keepalive(
            p["source_type"], p["hostname"], p.get("cluster_id"),
            stats=p.get("stats"),
        )

    async def cluster_config(self, p: dict) -> dict:
        return self.svc.cluster_config(p["scheduler_cluster_id"])

    # ---- cluster metrics plane (ISSUE 12) ----

    async def report_stats(self, p: dict) -> bool:
        return self.svc.report_stats(
            p.get("source_type", ""), p.get("hostname", ""), p.get("frame") or {}
        )

    async def cluster_stats(self, p: dict | None) -> dict:
        history = int((p or {}).get("history", 0))
        return self.svc.cluster_stats(history=min(history, 64))

    async def create_model(self, p: dict) -> dict:
        return self.svc.create_model(
            p["type"], p["version"],
            scheduler_id=p.get("scheduler_id", 0),
            bio=p.get("bio", ""),
            evaluation=p.get("evaluation"),
            artifact_path=p.get("artifact_path", ""),
        )

    async def activate_model(self, p: dict) -> dict:
        return self.svc.activate_model(p["model_id"])

    async def active_model(self, p: dict) -> Optional[dict]:
        return self.svc.active_model(p["type"], p.get("scheduler_id", 0))

    # ---- rollout state machine (ISSUE 11) ----

    async def publish_model(self, p: dict) -> dict:
        return self.svc.publish_model(
            p["type"], p["version"],
            scheduler_id=p.get("scheduler_id", 0),
            bio=p.get("bio", ""),
            evaluation=p.get("evaluation"),
            artifact_path=p.get("artifact_path", ""),
            artifact_digest=p.get("artifact_digest", ""),
        )

    async def promote_model(self, p: dict) -> dict:
        return self.svc.promote_model(p["model_id"])

    async def reject_model(self, p: dict) -> dict:
        return self.svc.reject_model(p["model_id"], p.get("reason", ""))

    async def rollback_model(self, p: dict) -> dict:
        return self.svc.rollback_model(
            p["type"], p.get("scheduler_id", 0), reason=p.get("reason", "")
        )

    async def report_shadow(self, p: dict) -> dict:
        return self.svc.report_shadow(
            p["model_id"], p.get("hostname", ""), p.get("report") or {}
        )

    async def rollout_status(self, p: dict) -> dict:
        return self.svc.rollout_status(p["type"], p.get("scheduler_id", 0))

    async def list_models(self, p: dict) -> list[dict]:
        # allowlist filter keys: db.find interpolates keys as SQL identifiers
        where = {k: v for k, v in (p or {}).items() if k in ("type", "state", "scheduler_id", "version")}
        return self.svc.list_models(**where)

    async def list_applications(self, p: Any) -> list[dict]:
        return self.svc.list_applications()

    async def get_config(self, p: dict) -> Optional[dict]:
        return self.svc.get_config(p["name"])

    async def set_config(self, p: dict) -> dict:
        return self.svc.set_config(p["name"], p["value"], bio=p.get("bio", ""))

    # ---- jobs (preheat): producer + worker pull/complete ----

    async def create_job(self, p: dict) -> dict:
        return await self.jobs.create(
            p["type"], p.get("args") or {},
            scheduler_cluster_ids=p.get("scheduler_cluster_ids") or [],
        )

    async def job_state(self, p: dict) -> Optional[dict]:
        return self.jobs.state(p["job_id"])

    async def pull_job(self, p: dict) -> Optional[dict]:
        return await self.jobs.pull(p["queue"], timeout=p.get("timeout", 30.0))

    async def complete_job(self, p: dict) -> None:
        self.jobs.complete(
            p["job_id"], success=p["success"], result=p.get("result") or {},
            cluster_id=p.get("cluster_id"),
        )

    async def issue_certificate(self, p: dict) -> dict:
        """Issue a leaf cert for a cluster service (ref pkg/rpc/security).
        `ca` + `cert_token` are wired by the server when --ca-dir is set;
        callers must present the cluster bootstrap token — the RPC plane has
        no user auth, and an open issuance endpoint would hand the mTLS trust
        root to any network peer."""
        import hmac as _hmac

        from dragonfly2_tpu.rpc.core import RpcError

        ca = getattr(self, "ca", None)
        if ca is None:
            raise RpcError("manager has no CA configured", code="unavailable")
        token = getattr(self, "cert_token", None)
        if not token:
            raise RpcError(
                "certificate issuance over RPC requires --cert-token on the manager",
                code="permission_denied",
            )
        if not _hmac.compare_digest(str(p.get("token", "")), token):
            raise RpcError("bad bootstrap token", code="permission_denied")
        issued = ca.issue(p.get("name", "service"), sans=tuple(p.get("sans", ())))
        return issued.to_dict()


def register_manager(server: RpcServer, adapter: ManagerRpcAdapter) -> None:
    server.register_service(adapter, MANAGER_METHODS)


class RemoteManagerClient:
    """Client side; method-per-RPC mirror of ManagerService."""

    def __init__(self, address: str, **kw: Any):
        self._c = RpcClient(address, **kw)

    async def close(self) -> None:
        await self._c.close()

    async def healthy(self) -> bool:
        return await self._c.healthy()

    async def list_schedulers(self, ip: str = "", conditions: dict | None = None) -> list[dict]:
        return await self._c.call("list_schedulers", {"ip": ip, "conditions": conditions})

    async def update_scheduler(self, hostname: str, ip: str, port: int, **kw: Any) -> dict:
        return await self._c.call(
            "update_scheduler", {"hostname": hostname, "ip": ip, "port": port, **kw}
        )

    async def update_seed_peer(self, hostname: str, ip: str, port: int, **kw: Any) -> dict:
        return await self._c.call(
            "update_seed_peer", {"hostname": hostname, "ip": ip, "port": port, **kw}
        )

    async def keepalive(
        self,
        source_type: str,
        hostname: str,
        cluster_id: int | None = None,
        *,
        stats: dict | None = None,
    ) -> bool:
        payload: dict[str, Any] = {
            "source_type": source_type, "hostname": hostname, "cluster_id": cluster_id,
        }
        if stats is not None:
            payload["stats"] = stats
        return await self._c.call("keepalive", payload)

    async def report_stats(self, source_type: str, hostname: str, frame: dict) -> bool:
        return await self._c.call(
            "report_stats",
            {"source_type": source_type, "hostname": hostname, "frame": frame},
        )

    async def cluster_stats(self, *, history: int = 0) -> dict:
        return await self._c.call("cluster_stats", {"history": history})

    async def cluster_config(self, scheduler_cluster_id: int) -> dict:
        return await self._c.call("cluster_config", {"scheduler_cluster_id": scheduler_cluster_id})

    async def create_model(self, model_type: str, version: str, **kw: Any) -> dict:
        return await self._c.call("create_model", {"type": model_type, "version": version, **kw})

    async def activate_model(self, model_id: int) -> dict:
        return await self._c.call("activate_model", {"model_id": model_id})

    async def active_model(self, model_type: str, scheduler_id: int = 0) -> Optional[dict]:
        return await self._c.call("active_model", {"type": model_type, "scheduler_id": scheduler_id})

    async def publish_model(self, model_type: str, version: str, **kw: Any) -> dict:
        return await self._c.call("publish_model", {"type": model_type, "version": version, **kw})

    async def promote_model(self, model_id: int) -> dict:
        return await self._c.call("promote_model", {"model_id": model_id})

    async def reject_model(self, model_id: int, reason: str = "") -> dict:
        return await self._c.call("reject_model", {"model_id": model_id, "reason": reason})

    async def rollback_model(
        self, model_type: str, scheduler_id: int = 0, *, reason: str = ""
    ) -> dict:
        return await self._c.call(
            "rollback_model",
            {"type": model_type, "scheduler_id": scheduler_id, "reason": reason},
        )

    async def report_shadow(self, model_id: int, hostname: str, report: dict) -> dict:
        return await self._c.call(
            "report_shadow",
            {"model_id": model_id, "hostname": hostname, "report": report},
        )

    async def rollout_status(self, model_type: str, scheduler_id: int = 0) -> dict:
        return await self._c.call(
            "rollout_status", {"type": model_type, "scheduler_id": scheduler_id}
        )

    async def list_models(self, **where: Any) -> list[dict]:
        return await self._c.call("list_models", where)

    async def list_applications(self) -> list[dict]:
        return await self._c.call("list_applications")

    async def get_config(self, name: str) -> Optional[dict]:
        return await self._c.call("get_config", {"name": name})

    async def set_config(self, name: str, value: dict, bio: str = "") -> dict:
        return await self._c.call("set_config", {"name": name, "value": value, "bio": bio})

    async def create_job(self, job_type: str, args: dict, scheduler_cluster_ids: list[int] | None = None) -> dict:
        return await self._c.call(
            "create_job",
            {"type": job_type, "args": args, "scheduler_cluster_ids": scheduler_cluster_ids or []},
        )

    async def job_state(self, job_id: int) -> Optional[dict]:
        return await self._c.call("job_state", {"job_id": job_id})

    async def pull_job(self, queue: str, timeout: float = 30.0) -> Optional[dict]:
        # server long-polls up to `timeout`; allow transport slack on top
        return await self._c.call(
            "pull_job", {"queue": queue, "timeout": timeout}, timeout=timeout + 10.0
        )

    async def complete_job(
        self, job_id: int, *, success: bool, result: dict | None = None,
        cluster_id: int | None = None,
    ) -> None:
        await self._c.call(
            "complete_job",
            {"job_id": job_id, "success": success, "result": result or {}, "cluster_id": cluster_id},
        )

    async def issue_certificate(
        self, name: str, sans: list[str] | None = None, *, token: str = ""
    ) -> dict:
        """Obtain a leaf cert + key + CA bundle from the manager's CA
        (ref certify's Obtain via pkg/rpc/security). `token` is the cluster
        bootstrap token configured on the manager."""
        return await self._c.call(
            "issue_certificate", {"name": name, "sans": sans or [], "token": token}
        )
