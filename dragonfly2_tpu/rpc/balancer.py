"""Consistent-hash scheduler balancer + dynamic resolver.

Parity with reference pkg/balancer/consistent_hashing.go:51-124 (task-ID
affinity: every RPC about one task lands on the same scheduler, so its
in-memory peer DAG sees the whole task) and pkg/resolver (dynconfig-fed
address list). Design differences for this stack:

- The ring hashes *addresses* with virtual nodes and picks by task id. Calls
  that carry no task id (per-peer reports) route via a peer→address map
  learned at register/announce time — the reference smuggles the task id
  into every request metadata instead; the map avoids widening every call
  signature.
- Host-scoped calls (announce_host, sync_probes) fan out to every scheduler:
  each one keeps its own host table (ref: a daemon announces to all its
  schedulers via per-scheduler streams).
- The resolver polls a callback (usually manager ListSchedulers through
  dynconfig) and rebuilds the ring on membership change; a dead address's
  tasks re-hash to survivors on the next pick.
- Per-target circuit breakers (each RpcClient carries one,
  resilience.breaker) feed placement: NEW keys walk the ring past addresses
  whose breaker is open, so a dead scheduler costs its first callers a
  failure burst and everyone else nothing; learned (sticky) routes are NOT
  rerouted — their state lives on the original scheduler — they fast-fail
  at the breaker until its half-open probe readmits the target.
"""

from __future__ import annotations

import asyncio
import bisect
import hashlib
import logging
from typing import Any, Awaitable, Callable, Iterable, Optional

from dragonfly2_tpu.rpc.core import RpcError
from dragonfly2_tpu.rpc.scheduler import RemoteSchedulerClient

logger = logging.getLogger(__name__)

VIRTUAL_NODES = 120  # ring replicas per address (ref defaultReplicaCount)


def _hash(key: str) -> int:
    return int.from_bytes(hashlib.sha256(key.encode()).digest()[:8], "big")


class ConsistentHashRing:
    """Hash ring with virtual nodes; pick(key) is stable under membership
    churn except for keys owned by the changed address."""

    def __init__(self, addresses: Iterable[str] = (), *, replicas: int = VIRTUAL_NODES):
        self._replicas = replicas
        self._ring: list[tuple[int, str]] = []
        self._addresses: set[str] = set()
        self.reset(addresses)

    def reset(self, addresses: Iterable[str]) -> None:
        self._addresses = set(addresses)
        self._ring = sorted(
            (_hash(f"{addr}#{i}"), addr)
            for addr in self._addresses
            for i in range(self._replicas)
        )

    def add(self, address: str) -> None:
        if address not in self._addresses:
            self.reset(self._addresses | {address})

    def remove(self, address: str) -> None:
        if address in self._addresses:
            self.reset(self._addresses - {address})

    @property
    def addresses(self) -> set[str]:
        return set(self._addresses)

    def pick(self, key: str, avoid: "set[str] | frozenset[str]" = frozenset()) -> str:
        """Owner address for `key`. `avoid` (e.g. addresses whose circuit
        breaker is open) is skipped by walking the ring forward — keys not
        owned by an avoided address keep their placement, and the fallback
        owner is itself consistent, so reroutes are stable too. If every
        address is avoided the natural owner is returned (the breaker there
        will fast-fail, which is still cheaper than no answer)."""
        if not self._ring:
            raise RpcError("no scheduler addresses available", code="unavailable")
        h = _hash(key)
        idx = bisect.bisect_right(self._ring, (h, "￿")) % len(self._ring)
        natural = self._ring[idx][1]
        if natural not in avoid:
            return natural
        for step in range(1, len(self._ring)):
            addr = self._ring[(idx + step) % len(self._ring)][1]
            if addr not in avoid:
                return addr
        return natural


class BalancedSchedulerClient:
    """Task-affine fan-in over N schedulers; daemon-facing interface matches
    RemoteSchedulerClient (daemon.conductor.SchedulerClient protocol)."""

    def __init__(
        self,
        addresses: Iterable[str],
        *,
        resolve: Optional[Callable[[], Awaitable[list[str]]]] = None,
        resolve_interval: float = 30.0,
        client_factory: Callable[[str], Any] = RemoteSchedulerClient,
    ):
        self.ring = ConsistentHashRing(addresses)
        self._clients: dict[str, Any] = {}
        # learned routing: once a task/peer registers on a scheduler, every
        # later call about it goes there even if the ring membership changes
        # mid-download (the state lives on the original scheduler)
        self._peer_addr: dict[str, str] = {}
        self._task_addr: dict[str, str] = {}
        self._map_cap = 20_000  # bound learned maps (entries also evict on peer completion)
        self._factory = client_factory
        self._resolve = resolve
        self._resolve_interval = resolve_interval
        self._resolver_task: asyncio.Task | None = None
        self._retired: list[Any] = []  # evicted clients, closed on close()

    # ---- membership ----

    def start_resolver(self) -> None:
        if self._resolve is not None and self._resolver_task is None:
            self._resolver_task = asyncio.ensure_future(self._resolve_loop())

    async def _resolve_loop(self) -> None:
        while True:
            try:
                addrs = await self._resolve()
                if addrs and set(addrs) != self.ring.addresses:
                    logger.info("scheduler set changed: %s", sorted(addrs))
                    self.ring.reset(addrs)
                    for addr in list(self._clients):
                        if addr not in self.ring.addresses:
                            # retire, don't close: in-flight RPCs on other
                            # coroutines may still hold this client; it is
                            # closed at shutdown
                            self._retired.append(self._clients.pop(addr))
                    self._peer_addr = {
                        p: a for p, a in self._peer_addr.items() if a in self.ring.addresses
                    }
                    self._task_addr = {
                        t: a for t, a in self._task_addr.items() if a in self.ring.addresses
                    }
            except Exception:
                logger.warning("scheduler resolve failed", exc_info=True)
            await asyncio.sleep(self._resolve_interval)

    def _client(self, addr: str) -> Any:
        client = self._clients.get(addr)
        if client is None:
            client = self._clients[addr] = self._factory(addr)
        return client

    def _open_addresses(self) -> set[str]:
        """Addresses whose circuit breaker is currently refusing calls.
        Only instantiated clients can be open (no traffic, no failures); the
        breaker's cooldown lapse re-admits an address so probes still flow."""
        out = set()
        for addr, client in self._clients.items():
            breaker = getattr(client, "breaker", None)
            if breaker is not None and breaker.is_open:
                out.add(addr)
        return out

    @staticmethod
    def _prune(mapping: dict, cap: int) -> None:
        while len(mapping) > cap:  # drop oldest entries (dict insert order)
            mapping.pop(next(iter(mapping)))

    def _learn(self, peer_id: str, task_id: str, addr: str) -> None:
        self._peer_addr[peer_id] = addr
        self._task_addr[task_id] = addr
        self._prune(self._peer_addr, self._map_cap)
        self._prune(self._task_addr, self._map_cap)

    def _for_task(self, task_id: str) -> Any:
        # learned owners stay sticky even through an open breaker: the task's
        # state lives there, and rerouting would answer from a scheduler that
        # has never seen the peer
        addr = self._task_addr.get(task_id)
        if addr is None or addr not in self.ring.addresses:
            addr = self.ring.pick(task_id, avoid=self._open_addresses())
        return self._client(addr)

    def _for_peer(self, peer_id: str) -> Any:
        addr = self._peer_addr.get(peer_id)
        if addr is None or addr not in self.ring.addresses:
            # unknown peer (restart?) — fall back to hashing the peer id so
            # at least routing is deterministic
            addr = self.ring.pick(peer_id, avoid=self._open_addresses())
        return self._client(addr)

    # ---- SchedulerClient protocol ----

    def _owner_for_task(self, task_id: str) -> str:
        """Learned owner first (sticky across membership change), else ring —
        routing NEW tasks away from schedulers whose breaker is open."""
        addr = self._task_addr.get(task_id)
        if addr is None or addr not in self.ring.addresses:
            addr = self.ring.pick(task_id, avoid=self._open_addresses())
        return addr

    async def register_peer(self, peer_id, meta, host):
        addr = self._owner_for_task(meta.task_id)
        self._learn(peer_id, meta.task_id, addr)
        return await self._client(addr).register_peer(peer_id, meta, host)

    async def report_task_metadata(self, task_id, **kw):
        await self._for_task(task_id).report_task_metadata(task_id, **kw)

    async def report_piece_result(self, peer_id, piece_index, **kw):
        await self._for_peer(peer_id).report_piece_result(peer_id, piece_index, **kw)

    async def report_pieces(self, peer_id, reports):
        return await self._for_peer(peer_id).report_pieces(peer_id, reports)

    async def announce_task(self, peer_id, meta, host, **kw):
        addr = self._owner_for_task(meta.task_id)
        self._learn(peer_id, meta.task_id, addr)
        await self._client(addr).announce_task(peer_id, meta, host, **kw)

    async def report_peer_result(self, peer_id, **kw):
        client = self._for_peer(peer_id)
        self._peer_addr.pop(peer_id, None)  # terminal per-peer call: evict
        await client.report_peer_result(peer_id, **kw)

    async def report_batch(self, peer_id, reports, result=None):
        client = self._for_peer(peer_id)
        if result is not None:
            self._peer_addr.pop(peer_id, None)  # terminal when a result rides
        return await client.report_batch(peer_id, reports, result=result)

    async def reschedule(self, peer_id):
        return await self._for_peer(peer_id).reschedule(peer_id)

    async def leave_peer(self, peer_id):
        client = self._for_peer(peer_id)
        self._peer_addr.pop(peer_id, None)
        await client.leave_peer(peer_id)

    async def stat_task(self, task_id):
        return await self._for_task(task_id).stat_task(task_id)

    # ---- host-scoped: fan out to all schedulers ----

    async def announce_host(self, host, stats=None):
        errors = []
        for addr in self.ring.addresses:
            try:
                await self._client(addr).announce_host(host, stats)
            except Exception as e:  # one dead scheduler must not mute the rest
                errors.append((addr, e))
        if errors and len(errors) == len(self.ring.addresses):
            raise errors[0][1]
        for addr, e in errors:
            logger.warning("announce_host to %s failed: %s", addr, e)

    async def sync_probes(self, host_id, results):
        """Probes go to one deterministic owner per host (its topology rows
        live on one scheduler; ref networktopology is per-scheduler)."""
        return await self._client(self.ring.pick(host_id)).sync_probes(host_id, results)

    async def leave_host(self, host_id):
        """Graceful departure fans out: any scheduler may hold this host's
        peers (tasks hash to different owners). Concurrent, not serial — the
        shutdown path must pay at most ONE RPC timeout even when several
        schedulers are unreachable."""

        async def _one(addr):
            try:
                await self._client(addr).leave_host(host_id)
            except Exception as e:
                logger.warning("leave_host to %s failed: %s", addr, e)

        await asyncio.gather(*(_one(a) for a in self.ring.addresses))

    async def healthy(self) -> bool:
        for addr in self.ring.addresses:
            try:
                if await self._client(addr).healthy():
                    return True
            except Exception as e:
                logger.debug("health probe of %s failed: %s", addr, e)
                continue
        return False

    async def close(self):
        import contextlib

        if self._resolver_task is not None:
            self._resolver_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._resolver_task
            self._resolver_task = None
        for client in list(self._clients.values()) + self._retired:
            await client.close()
        self._clients.clear()
        self._retired.clear()


def make_scheduler_client(
    spec: str, *, resolve: Optional[Callable[[], Awaitable[list[str]]]] = None, **kw: Any
):
    """One address → plain client; comma-separated list → balanced client
    (kw forwarded to every per-address client either way)."""
    addrs = [a.strip() for a in spec.split(",") if a.strip()]
    if len(addrs) <= 1 and resolve is None:
        return RemoteSchedulerClient(addrs[0] if addrs else spec, **kw)
    return BalancedSchedulerClient(
        addrs,
        resolve=resolve,
        client_factory=lambda a: RemoteSchedulerClient(a, **kw),
    )
