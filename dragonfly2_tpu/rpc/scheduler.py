"""Scheduler RPC adapters: wire client + server over rpc.core.

Reference equivalent: pkg/rpc/scheduler/{client,server} (client_v1.go:46-53
consistent-hash-balanced clients) + scheduler/rpcserver thin adapters. The
client implements daemon.conductor.SchedulerClient, so engines swap freely
between in-process and wire transports.
"""

from __future__ import annotations

from dataclasses import asdict
from typing import Any

from dragonfly2_tpu.rpc.core import RpcClient, RpcError, RpcServer
from dragonfly2_tpu.scheduler.service import (
    HostInfo,
    ParentInfo,
    RegisterResult,
    SchedulerService,
    TaskMeta,
)

SCHEDULER_METHODS = [
    "register_peer",
    "report_task_metadata",
    "report_piece_result",
    "report_pieces",
    "report_batch",
    "announce_task",
    "report_peer_result",
    "reschedule",
    "leave_peer",
    "leave_host",
    "announce_host",
    "stat_task",
    "sync_probes",
    "federation_sync",
    "federation_state",
    "decision_records",
]


def _result_to_wire(r: RegisterResult) -> dict:
    return asdict(r)  # recursive: ParentInfo entries become dicts too


def _result_from_wire(d: dict) -> RegisterResult:
    parents = [ParentInfo(**p) for p in d.pop("parents", [])]
    return RegisterResult(parents=parents, **d)


class SchedulerRpcAdapter:
    """Server-side: msgpack payloads -> SchedulerService calls."""

    def __init__(self, service: SchedulerService):
        self.svc = service

    async def register_peer(self, p: dict) -> dict:
        out = await self.svc.register_peer(
            p["peer_id"],
            TaskMeta(**{**p["meta"], "filters": tuple(p["meta"].get("filters", ()))}),
            HostInfo(**p["host"]),
        )
        return _result_to_wire(out)

    async def report_task_metadata(self, p: dict) -> None:
        self.svc.report_task_metadata(
            p["task_id"],
            content_length=p["content_length"],
            piece_size=p.get("piece_size"),
            digest=p.get("digest", ""),
            direct_piece=p.get("direct_piece", b""),
        )

    async def report_piece_result(self, p: dict) -> None:
        self.svc.report_piece_result(
            p["peer_id"],
            p["piece_index"],
            success=p["success"],
            cost_ms=p.get("cost_ms", 0.0),
            parent_id=p.get("parent_id", ""),
        )

    async def report_pieces(self, p: dict) -> int:
        # triples arrive as msgpack lists; the applied count rides back so
        # callers (and tests) can observe idempotent re-applies
        if "reports" in p:
            reports = p["reports"]
        else:
            # r05 wire shape (flat index list + one shared cost): accept it —
            # a rolling upgrade must not silently zero an old daemon's batch
            # (a payload with NEITHER key is malformed: KeyError -> rpc error)
            reports = [(i, p.get("cost_ms", 0.0), "") for i in p["piece_indices"]]
        return self.svc.report_pieces(p["peer_id"], reports)

    async def report_batch(self, p: dict) -> int:
        # task-close combo: residual piece triples + the final peer result in
        # one frame (both legs idempotent server-side, so the rpc client's
        # retries re-apply as no-ops)
        return self.svc.report_batch(
            p["peer_id"], p.get("reports", []), result=p.get("result")
        )

    async def announce_task(self, p: dict) -> None:
        self.svc.announce_task(
            p["peer_id"],
            TaskMeta(**{**p["meta"], "filters": tuple(p["meta"].get("filters", ()))}),
            HostInfo(**p["host"]),
            content_length=p["content_length"],
            piece_size=p["piece_size"],
            piece_indices=p["piece_indices"],
            digest=p.get("digest", ""),
        )

    async def report_peer_result(self, p: dict) -> None:
        self.svc.report_peer_result(
            p["peer_id"], success=p["success"], bandwidth_bps=p.get("bandwidth_bps", 0.0)
        )

    async def reschedule(self, p: dict) -> dict:
        try:
            return _result_to_wire(await self.svc.reschedule(p["peer_id"]))
        except KeyError:
            # a restarted (or GC'd) scheduler does not know this peer; the
            # typed code lets the conductor re-register and rebuild the
            # scheduler's view instead of treating this as an internal fault
            raise RpcError(f"unknown peer {p['peer_id']}", code="not_found")

    async def leave_peer(self, p: dict) -> None:
        self.svc.leave_peer(p["peer_id"])

    async def leave_host(self, p: dict) -> None:
        self.svc.leave_host(p["host_id"])

    async def announce_host(self, p: dict) -> None:
        self.svc.announce_host(HostInfo(**p["host"]), p.get("stats"))

    async def stat_task(self, p: dict) -> dict | None:
        return self.svc.stat_task(p["task_id"])

    async def sync_probes(self, p: dict) -> list[dict]:
        return self.svc.sync_probes(p["host_id"], p.get("results", []))

    async def federation_sync(self, p: dict) -> dict:
        from dragonfly2_tpu.observability.tracing import default_tracer

        # named span on the RESPONDER (continues the initiator's
        # federation.sync trace): a cluster trace shows the gossip exchange
        # on BOTH members, which the federation-smoke leg asserts
        with default_tracer().span("federation.apply", origin=p.get("origin", "")):
            return self.svc.federation_sync(
                p.get("origin", ""),
                topo_since=p.get("topo_since", 0),
                bw_since=p.get("bw_since", 0),
                topo_push=p.get("topo_push"),
                bw_push=p.get("bw_push"),
                epoch=p.get("epoch", ""),
            )

    async def federation_state(self, p: Any = None) -> dict:
        return self.svc.federation_state()

    async def decision_records(self, p: dict | None = None) -> dict:
        p = p or {}
        return self.svc.decision_records(
            task_id=p.get("task_id"),
            child=p.get("child"),
            limit=int(p.get("limit", 64)),
            with_features=bool(p.get("with_features", True)),
        )


def serve_scheduler(service: SchedulerService, **server_kw: Any) -> RpcServer:
    server = RpcServer(**server_kw)
    server.register_service(SchedulerRpcAdapter(service), SCHEDULER_METHODS)
    return server


class RemoteSchedulerClient:
    """daemon.conductor.SchedulerClient over the wire."""

    def __init__(self, address: str, **client_kw: Any):
        self._rpc = RpcClient(address, **client_kw)

    @property
    def breaker(self):
        """Per-target circuit breaker (surfaced for the balancer's
        breaker-aware ring placement)."""
        return self._rpc.breaker

    async def register_peer(self, peer_id: str, meta: TaskMeta, host: HostInfo) -> RegisterResult:
        out = await self._rpc.call(
            "register_peer",
            {"peer_id": peer_id, "meta": asdict(meta), "host": asdict(host)},
        )
        return _result_from_wire(out)

    async def report_task_metadata(self, task_id, *, content_length, piece_size, digest="", direct_piece=b""):
        await self._rpc.call(
            "report_task_metadata",
            {"task_id": task_id, "content_length": content_length,
             "piece_size": piece_size, "digest": digest, "direct_piece": direct_piece},
        )

    async def report_piece_result(self, peer_id, piece_index, *, success, cost_ms=0.0, parent_id=""):
        await self._rpc.call(
            "report_piece_result",
            {"peer_id": peer_id, "piece_index": piece_index, "success": success,
             "cost_ms": cost_ms, "parent_id": parent_id},
        )

    async def report_pieces(self, peer_id, reports):
        triples = [list(r) for r in reports]
        # both wire shapes ride every flush during a mixed-version rollout:
        # an r05 adapter reads the flat piece_indices + one shared cost
        # (per-piece costs degrade to the mean for that window), a current
        # adapter prefers the full triples — either way a batch never
        # vanishes into a KeyError-and-drop on the far side
        return await self._rpc.call(
            "report_pieces",
            {"peer_id": peer_id, "reports": triples,
             "piece_indices": [t[0] for t in triples],
             "cost_ms": (sum(t[1] for t in triples) / len(triples)) if triples else 0.0},
        )

    async def report_batch(self, peer_id, reports, result=None):
        return await self._rpc.call(
            "report_batch",
            {"peer_id": peer_id, "reports": [list(r) for r in reports],
             "result": result},
        )

    async def announce_task(self, peer_id, meta, host, *, content_length, piece_size, piece_indices, digest=""):
        await self._rpc.call(
            "announce_task",
            {"peer_id": peer_id, "meta": asdict(meta), "host": asdict(host),
             "content_length": content_length, "piece_size": piece_size,
             "piece_indices": list(piece_indices), "digest": digest},
        )

    async def report_peer_result(self, peer_id, *, success, bandwidth_bps=0.0):
        await self._rpc.call(
            "report_peer_result",
            {"peer_id": peer_id, "success": success, "bandwidth_bps": bandwidth_bps},
        )

    async def reschedule(self, peer_id):
        return _result_from_wire(await self._rpc.call("reschedule", {"peer_id": peer_id}))

    async def leave_peer(self, peer_id):
        await self._rpc.call("leave_peer", {"peer_id": peer_id})

    async def leave_host(self, host_id):
        await self._rpc.call("leave_host", {"host_id": host_id})

    async def announce_host(self, host: HostInfo, stats: dict | None = None):
        await self._rpc.call("announce_host", {"host": asdict(host), "stats": stats})

    async def stat_task(self, task_id: str):
        return await self._rpc.call("stat_task", {"task_id": task_id})

    async def sync_probes(self, host_id: str, results: list[dict]):
        return await self._rpc.call("sync_probes", {"host_id": host_id, "results": results})

    async def federation_sync(
        self, origin: str, *, topo_since=0, bw_since=0, topo_push=None,
        bw_push=None, epoch="",
    ):
        """Scheduler-to-scheduler push-pull gossip exchange (federation.py)."""
        return await self._rpc.call(
            "federation_sync",
            {"origin": origin, "topo_since": topo_since, "bw_since": bw_since,
             "topo_push": topo_push or [], "bw_push": bw_push or [],
             "epoch": epoch},
        )

    async def federation_state(self):
        return await self._rpc.call("federation_state")

    async def decision_records(
        self, *, task_id=None, child=None, limit: int = 64,
        with_features: bool = True,
    ):
        """Sampled scoring decision records (ISSUE 15; `dfml explain`)."""
        return await self._rpc.call(
            "decision_records",
            {"task_id": task_id, "child": child, "limit": limit,
             "with_features": with_features},
        )

    async def healthy(self) -> bool:
        return await self._rpc.healthy()

    async def close(self):
        await self._rpc.close()
