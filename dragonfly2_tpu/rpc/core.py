"""Framed msgpack RPC core.

Wire format: 4-byte big-endian length + msgpack map
  request:  {"i": id, "m": method, "p": payload, "t"?: traceparent}
  response: {"i": id, "r": result} | {"i": id, "e": {"code", "message"}}
Payloads are msgpack-native types (dicts/lists/str/bytes/numbers); service
adapters convert dataclasses at the boundary. "t" is the optional compact
trace context (W3C traceparent string, sampled flag included): the client
stamps it when a trace is active in its caller's context, the server opens
a continuation span around the handler — the otelgrpc-interceptor
equivalent (SURVEY §5) without widening any payload schema.

Server: asyncio.start_server (tcp or unix), method registry, per-server QPS
token bucket (reference default 10k QPS / 20k burst,
pkg/rpc/scheduler/server/server.go:43-44), error mapping.
Client: one connection with request multiplexing, auto-reconnect, retry with
exponential backoff + jitter (resilience.BackoffPolicy, ref interceptor
chain's retry), a per-target circuit breaker, and deadline-aware request
timeouts (min of the per-op timeout and the caller's propagated budget).
"""

from __future__ import annotations

import asyncio
import logging
import struct
from typing import Any, Awaitable, Callable

import msgpack

from dragonfly2_tpu.observability.tracing import SpanContext, Tracer, default_tracer
from dragonfly2_tpu.resilience import deadline as dl
from dragonfly2_tpu.resilience import faultline
from dragonfly2_tpu.resilience.backoff import BackoffPolicy
from dragonfly2_tpu.resilience.breaker import CircuitBreaker
from dragonfly2_tpu.utils.ratelimit import TokenBucket

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
MAX_FRAME = 256 << 20  # direct pieces / piece payloads stay well under this


class RpcError(Exception):
    def __init__(self, message: str, code: str = "internal", retry_after_s: float = 0.0):
        super().__init__(message)
        self.code = code
        # overload hint (ISSUE 17): a server answering "come back in N
        # seconds" rides it in the error frame; clients pre-charge their
        # process-wide RetryBudget with it so one overloaded answer mutes
        # EVERY caller's retries against that target class, not just this one
        self.retry_after_s = retry_after_s


class ConnectionClosed(RpcError):
    def __init__(self) -> None:
        super().__init__("connection closed", code="unavailable")


# Frames at/above this size take the zero-copy paths: bodies are read into a
# preallocated buffer (readinto-style — readexactly would assemble the chunk
# list with an extra full-frame join copy) and written without the
# header+body concatenation copy. Below it, syscall count beats copy cost.
_BIG_FRAME = 64 << 10


async def _read_frame(reader: asyncio.StreamReader) -> dict:
    if faultline.ACTIVE is not None:
        await faultline.ACTIVE.fire("rpc.read")
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}", code="resource_exhausted")
    if length >= _BIG_FRAME:
        # piece-payload-sized frame: land chunks directly in one
        # preallocated buffer and unpack from the memoryview — no chunk-list
        # join, no second full-frame allocation
        buf = bytearray(length)
        view = memoryview(buf)
        off = 0
        while off < length:
            chunk = await reader.read(length - off)
            if not chunk:
                raise asyncio.IncompleteReadError(bytes(view[:off]), length)
            view[off : off + len(chunk)] = chunk
            off += len(chunk)
        return msgpack.unpackb(view, raw=False)
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False)


class WriteCoalescer:
    """Per-connection outbound frame queue: control-plane frames coalesce
    into one writer.write + ONE drain per event-loop batch instead of a
    write+drain round trip per call.

    send() packs and enqueues synchronously — the faultline `rpc.write`
    injection point fires HERE, per frame, so chaos semantics are unchanged
    (an injected fault raises to the caller before the frame is queued, and
    the rpc client's retry path owns recovery exactly as before). A single
    flusher task drains the queue: consecutive small frames are joined into
    one write, frames at/above _BIG_FRAME keep their two-buffer zero-concat
    write, and ordering is queue order. Every frame enqueued while a drain()
    is parked rides the next batch — under concurrent request load (piece
    workers, batched report flushes, server responses) that turns N
    write+drain pairs per loop iteration into one.

    Nobody holds a lock across drain() anymore: enqueue is synchronous on
    the loop thread, and backpressure is the flusher awaiting drain before
    taking the next batch (the transport's high-water mark parks exactly the
    writes that need parking, not every caller)."""

    __slots__ = ("_writer", "_chunks", "_task")

    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer
        self._chunks: list[bytes] = []
        self._task: asyncio.Task | None = None

    def send(self, msg: dict) -> None:
        if faultline.ACTIVE is not None:
            faultline.ACTIVE.check("rpc.write")
        body = msgpack.packb(msg, use_bin_type=True)
        header = _LEN.pack(len(body))
        if len(body) >= _BIG_FRAME:
            # kept as separate chunks: the flusher writes them without the
            # header+body concatenation copy (a full-frame copy per
            # direct-piece/piece-body frame otherwise)
            self._chunks.append(header)
            self._chunks.append(body)
        else:
            self._chunks.append(header + body)
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._drain_loop())

    async def _drain_loop(self) -> None:
        try:
            while self._chunks:
                chunks, self._chunks = self._chunks, []
                if self._writer.is_closing():
                    return
                run: list[bytes] = []  # consecutive small frames to join
                for c in chunks:
                    if len(c) >= _BIG_FRAME:
                        if run:
                            self._writer.write(run[0] if len(run) == 1 else b"".join(run))
                            run.clear()
                        self._writer.write(c)
                    else:
                        run.append(c)
                if run:
                    self._writer.write(run[0] if len(run) == 1 else b"".join(run))
                await self._writer.drain()
        except (ConnectionError, OSError) as e:
            # peer gone mid-write (reset/broken pipe): close the transport so
            # the recv side fails pending calls NOW; retry paths own recovery
            logger.debug("coalesced write failed: %r", e)
            self._chunks.clear()
            self._writer.close()


Handler = Callable[[Any], Awaitable[Any]]

VSOCK_SCHEME = "vsock://"


def parse_vsock(address: str) -> tuple[int, int]:
    """``vsock://<cid>:<port>`` → (cid, port). Parity with the reference's
    vsock transport (pkg/rpc/vsock.go:1-59) for VM-isolated clients (e.g.
    Kata containers) talking to a host daemon over AF_VSOCK."""
    rest = address[len(VSOCK_SCHEME):]
    cid_s, sep, port_s = rest.partition(":")
    if not sep or not cid_s.isdigit() or not port_s.isdigit():
        raise ValueError(f"bad vsock address {address!r}: want vsock://<cid>:<port>")
    return int(cid_s), int(port_s)


def vsock_socket():
    """A fresh AF_VSOCK stream socket; raises OSError where the kernel (or
    platform) lacks vsock support."""
    import socket

    if not hasattr(socket, "AF_VSOCK"):
        raise OSError("AF_VSOCK unsupported on this platform")
    return socket.socket(socket.AF_VSOCK, socket.SOCK_STREAM)


class RpcServer:
    def __init__(
        self,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        unix_path: str | None = None,
        vsock_port: int | None = None,
        qps_limit: float = 10_000,
        qps_burst: float = 20_000,
        ssl: Any = None,
    ):
        self._handlers: dict[str, Handler] = {}
        self.host = host
        self.port = port
        self.unix_path = unix_path
        self.vsock_port = vsock_port  # listen on AF_VSOCK (any CID) when set
        self.ssl = ssl  # ssl.SSLContext for TLS/mTLS (security.ca helpers)
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[asyncio.StreamWriter] = set()
        self._bucket = TokenBucket(qps_limit, qps_burst)
        self.register("_ping", self._ping)

    async def _ping(self, payload: Any) -> str:
        return "pong"

    def register(self, method: str, handler: Handler) -> None:
        self._handlers[method] = handler

    def register_service(self, obj: Any, methods: list[str]) -> None:
        """Expose async methods of obj taking/returning msgpack-able payloads."""
        for name in methods:
            self.register(name, getattr(obj, name))

    async def start(self) -> None:
        if self.vsock_port is not None:
            import socket

            s = vsock_socket()
            s.bind((socket.VMADDR_CID_ANY, self.vsock_port))
            self._server = await asyncio.start_server(self._on_conn, sock=s)
        elif self.unix_path:
            self._server = await asyncio.start_unix_server(self._on_conn, path=self.unix_path)
        else:
            self._server = await asyncio.start_server(
                self._on_conn, self.host, self.port, ssl=self.ssl
            )
            self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Drop live connections too: wait_closed() (3.12+) waits for
            # connection handlers, which otherwise run until the peer hangs up.
            for w in list(self._conns):
                w.close()
            await self._server.wait_closed()
            self._server = None

    @property
    def address(self) -> str:
        if self.vsock_port is not None:
            import socket

            return f"{VSOCK_SCHEME}{socket.VMADDR_CID_HOST}:{self.vsock_port}"
        return self.unix_path or f"{self.host}:{self.port}"

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        tasks: set[asyncio.Task] = set()
        # One coalescer per connection (created inside the connection
        # coroutine, so its flusher binds to the serving loop). Concurrent
        # handler responses enqueue synchronously and ride one write+drain
        # per loop batch — the old per-connection write lock held across
        # drain() serialized every responder behind the slowest flush.
        wq = WriteCoalescer(writer)
        self._conns.add(writer)
        ssl_obj = writer.get_extra_info("ssl_object")
        if ssl_obj is not None:
            # one line per TLS connection: which suite actually negotiated
            # (cert-rollover/cipher-policy debugging without a pcap)
            logger.debug(
                "rpc conn from %s: %s %s", writer.get_extra_info("peername"),
                ssl_obj.version(), (ssl_obj.cipher() or ("?",))[0],
            )
        try:
            while True:
                try:
                    msg = await _read_frame(reader)
                except (asyncio.IncompleteReadError, OSError):
                    # peer hung up, or the transport (or an injected rpc.read
                    # fault) failed the read — either way this connection is
                    # done; the client's retry path owns recovery
                    break
                if not isinstance(msg, dict):
                    logger.warning("malformed frame (%s), closing connection", type(msg).__name__)
                    break
                t = asyncio.ensure_future(self._dispatch(msg, wq))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
        finally:
            self._conns.discard(writer)
            for t in tasks:
                t.cancel()
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    async def _dispatch(self, msg: dict, wq: WriteCoalescer) -> None:
        rid = msg.get("i")
        method = msg.get("m", "")
        handler = self._handlers.get(method)
        if handler is None:
            out = {"i": rid, "e": {"code": "unimplemented", "message": f"no method {method!r}"}}
        elif not self._bucket.try_acquire():
            out = {"i": rid, "e": {"code": "resource_exhausted", "message": "rate limited"}}
        else:
            # continuation span when the caller shipped trace context: the
            # handler (and everything it awaits — nested rpc calls, piece
            # fetches, in-process service methods) inherits it through the
            # contextvar. An unsampled context still flows so downstream
            # spans stay unrecorded (all-or-nothing); no "t" costs one get.
            # Non-string "t" (skewed/hostile peer) is ignored, NOT raised:
            # this parse runs before the error-response try below, and an
            # exception here would kill the dispatch task and leave the
            # caller hanging out its full timeout with no response frame.
            t = msg.get("t")
            remote = SpanContext.from_traceparent(t) if isinstance(t, str) else None
            try:
                if remote is not None:
                    with default_tracer().span(
                        "rpc.server", parent=remote, method=method
                    ):
                        result = await handler(msg.get("p"))
                else:
                    result = await handler(msg.get("p"))
                out = {"i": rid, "r": result}
            except RpcError as e:
                err = {"code": e.code, "message": str(e)}
                if e.retry_after_s > 0:
                    err["retry_after_s"] = e.retry_after_s
                out = {"i": rid, "e": err}
            except Exception as e:
                logger.exception("rpc handler %s failed", method)
                out = {"i": rid, "e": {"code": "internal", "message": f"{type(e).__name__}: {e}"}}
        try:
            wq.send(out)
        except OSError as e:
            # an injected rpc.write fault (or a dead transport caught at
            # enqueue): the client's retry path owns recovery
            logger.debug("response write for %s failed: %r", method, e)


class RpcClient:
    def __init__(
        self,
        address: str,
        *,
        timeout: float = 30.0,
        retries: int = 3,
        retry_backoff: float = 0.2,
        backoff: BackoffPolicy | None = None,
        breaker: CircuitBreaker | None = None,
        retry_budget=None,
        target_class: str | None = None,
        ssl: Any = None,
    ):
        self.address = address
        self.timeout = timeout
        self.retries = retries
        self.retry_backoff = retry_backoff  # kept: seeds the default policy base
        # Cluster retry budget (ISSUE 17): per-process token bucket shared by
        # every client retrying against the same TARGET CLASS ("scheduler",
        # "manager", ...). None (the default) keeps per-client behavior —
        # composition roots opt in where storm amplification is possible.
        if retry_budget is None and target_class:
            from dragonfly2_tpu.resilience.budget import budget_for

            retry_budget = budget_for(target_class)
        self.retry_budget = retry_budget
        # exponential + jitter, capped well under the per-op timeout so the
        # retry budget is spent on attempts, not waiting
        self.backoff = backoff or BackoffPolicy(
            base=retry_backoff, multiplier=2.0, max_delay=5.0, jitter=0.5
        )
        # per-target state: one client == one address, so this breaker IS the
        # per-target breaker (the balancer keeps one client per scheduler)
        self.breaker = breaker or CircuitBreaker()
        self.ssl = ssl  # ssl.SSLContext (security.ca.client_ssl_context)
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._wq: WriteCoalescer | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._recv_task: asyncio.Task | None = None
        # Safe outside a running loop: since 3.10 asyncio.Lock binds lazily on
        # first await, and each client is used from a single loop (DF021 audit).
        self._conn_lock = asyncio.Lock()

    async def _connect(self) -> None:
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            # vsock:// is explicit; tcp only when the address ends in
            # ":<digits>"; anything else (absolute, relative, or
            # colon-containing paths) is a unix socket
            host, _, port_s = self.address.rpartition(":")
            if self.address.startswith(VSOCK_SCHEME):
                cid, vport = parse_vsock(self.address)
                s = vsock_socket()
                s.setblocking(False)
                await asyncio.get_running_loop().sock_connect(s, (cid, vport))
                self._reader, self._writer = await asyncio.open_connection(sock=s)
            elif not port_s.isdigit():
                self._reader, self._writer = await asyncio.open_unix_connection(self.address)
            else:
                host, port = self.address.rsplit(":", 1)
                self._reader, self._writer = await asyncio.open_connection(
                    host, int(port), ssl=self.ssl
                )
            self._wq = WriteCoalescer(self._writer)
            self._recv_task = asyncio.ensure_future(self._recv_loop(self._reader))

    async def _recv_loop(self, reader: asyncio.StreamReader) -> None:
        try:
            while True:
                msg = await _read_frame(reader)
                fut = self._pending.pop(msg.get("i"), None)
                if fut is None or fut.done():
                    continue
                if "e" in msg:
                    err = msg["e"]
                    fut.set_exception(RpcError(
                        err.get("message", ""), err.get("code", "internal"),
                        retry_after_s=float(err.get("retry_after_s", 0.0)),
                    ))
                else:
                    fut.set_result(msg.get("r"))
        except (asyncio.IncompleteReadError, OSError, asyncio.CancelledError):
            # OSError covers transport failures AND injected rpc.read faults
            # (FaultError is an IOError); the finally below fails the pending
            # futures so call() reconnects and retries
            pass
        finally:
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionClosed())
            self._pending.clear()
            # Reset connection state so the next call() reconnects instead of
            # writing into the dead socket and waiting out its timeout.
            if self._reader is reader:
                if self._writer is not None:
                    self._writer.close()
                # _conn_lock guards only the connect handshake; these resets
                # are a single scheduling slice on the loop thread (no await),
                # so they cannot interleave with a _connect() holding the lock
                # — and the `is reader` guard above pins the incarnation.
                self._reader = self._writer = None  # dflint: disable=DF023 loop-thread reset, no await around it
                self._wq = None  # dflint: disable=DF023 loop-thread reset, no await around it
                self._recv_task = None  # dflint: disable=DF023 loop-thread reset, no await around it

    def _effective_timeout(self, timeout: float | None, method: str) -> float:
        """min(per-op timeout, propagated deadline remaining). An exhausted
        budget fails fast instead of issuing a request that cannot finish."""
        per_op = timeout or self.timeout
        rem = dl.remaining()
        if rem is None:
            return per_op
        if rem <= 0:
            raise RpcError(
                f"{method}: deadline exhausted before call", code="deadline_exceeded"
            )
        return min(per_op, rem)

    async def call(self, method: str, payload: Any = None, *, timeout: float | None = None) -> Any:
        last_err: Exception | None = None
        # trace context resolved ONCE per call: each attempt gets its own
        # client span (attempt index is an attribute, so retries are visible
        # in the trace), and the span's own context rides the frame's "t"
        # key. No active trace → no span objects, no wire bytes.
        traced = Tracer.current() is not None
        for attempt in range(self.retries + 1):
            if not self.breaker.allow():
                raise RpcError(
                    f"circuit open to {self.address}"
                    + (f" (last: {last_err})" if last_err else ""),
                    code="unavailable",
                )
            # outside the try: an exhausted caller budget is not the target's
            # fault and must not feed the breaker
            per_op = timeout or self.timeout
            effective = self._effective_timeout(timeout, method)
            try:
                if traced:
                    with default_tracer().span(
                        "rpc.client",
                        method=method,
                        address=self.address,
                        attempt=attempt,
                        deadline_remaining_s=round(effective, 3),
                    ) as sp:
                        result = await self._call_once(
                            method, payload, effective,
                            trace=sp.context.traceparent(),
                        )
                else:
                    result = await self._call_once(method, payload, effective)
                self.breaker.record_success()
                return result
            except (ConnectionClosed, ConnectionError, OSError) as e:
                self.breaker.record_failure()
                last_err = e
                self._drop_connection()
                if attempt < self.retries:  # no pointless sleep before raising
                    self._spend_retry(method, last_err)
                    await self.backoff.sleep(attempt)
            except RpcError as e:
                if e.code == "deadline_exceeded":
                    if effective >= per_op:
                        # silent for the FULL per-op window: counts against
                        # the target
                        self.breaker.record_failure()
                    # else: the caller's nearly-spent budget shrank the
                    # window — a healthy target may simply not have had time;
                    # record nothing either way
                else:
                    # any decoded response (even an error) proves the target alive
                    self.breaker.record_success()
                if e.retry_after_s > 0 and self.retry_budget is not None:
                    # server's overload hint: mute the WHOLE process's
                    # retries against this target class for the window
                    self.retry_budget.charge(e.retry_after_s)
                if e.code == "resource_exhausted" and attempt < self.retries:
                    last_err = e
                    self._spend_retry(method, last_err)
                    await self.backoff.sleep(attempt)
                    continue
                raise
        raise last_err or RpcError("rpc call failed")

    def _spend_retry(self, method: str, last_err: Exception | None) -> None:
        """Consult the cluster retry budget before ONE retry attempt (first
        attempts are free). Beyond budget — or inside a server-hinted
        retry_after window — fail fast so the caller moves to its next
        fallback instead of amplifying load on a sick target."""
        b = self.retry_budget
        if b is None:
            return
        if not b.spend():
            raise RpcError(
                f"{method}: retry budget exhausted for "
                f"{b.name or self.address}"
                + (f" (last: {last_err})" if last_err else ""),
                code="unavailable",
            )

    async def _call_once(
        self, method: str, payload: Any, timeout: float, trace: str | None = None
    ) -> Any:
        await self._connect()
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        msg = {"i": rid, "m": method, "p": payload}
        if trace is not None:
            msg["t"] = trace
        try:
            # enqueue is synchronous (injected rpc.write faults raise HERE and
            # feed the retry path); the coalescer's flusher owns the drain, so
            # concurrent calls in one loop batch share a single write+drain
            self._wq.send(msg)
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._pending.pop(rid, None)
            raise RpcError(f"{method} timed out after {timeout}s", code="deadline_exceeded")
        finally:
            self._pending.pop(rid, None)

    def _drop_connection(self) -> None:
        if self._recv_task is not None:
            self._recv_task.cancel()
            # sync method: runs to completion on the loop thread, atomic
            # w.r.t. any coroutine holding _conn_lock
            self._recv_task = None  # dflint: disable=DF023 sync method, atomic on the loop thread
        if self._writer is not None:
            self._writer.close()
        self._reader = self._writer = None  # dflint: disable=DF023 sync method, atomic on the loop thread
        self._wq = None  # dflint: disable=DF023 sync method, atomic on the loop thread

    async def close(self) -> None:
        writer = self._writer
        self._drop_connection()
        # In-flight futures must fail NOW, not hang until their timeout: the
        # recv task's finally does this too, but its cancellation completes on
        # a later loop cycle — close() callers (shutdown paths) need it done
        # before they proceed.
        for fut in list(self._pending.values()):
            if not fut.done():
                fut.set_exception(ConnectionClosed())
        self._pending.clear()
        if writer is not None:
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    def tls_info(self) -> "dict | None":
        """Negotiated TLS parameters of the live connection, or None when
        plain/disconnected: {"cipher", "version"}. The control plane rides
        asyncio's stock SSL (small frames — the data plane's fast-path
        transport lives in security/transport.py); this surfaces what
        actually negotiated so dfstress/debug tooling can report the wire
        posture next to its numbers."""
        if self._writer is None:
            return None
        ssl_obj = self._writer.get_extra_info("ssl_object")
        if ssl_obj is None:
            return None
        cipher = ssl_obj.cipher()
        return {
            "cipher": cipher[0] if cipher else None,
            "version": ssl_obj.version(),
        }

    async def healthy(self) -> bool:
        try:
            return await self.call("_ping", timeout=2.0) == "pong"
        except (RpcError, ConnectionError, OSError, asyncio.TimeoutError) as e:
            logger.debug("health probe of %s failed: %r", self.address, e)
            return False
