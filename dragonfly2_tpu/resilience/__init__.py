"""Unified resilience layer for the P2P data plane.

Dragonfly's value proposition is that a download survives the cluster
misbehaving; this package centralizes the machinery that makes that true
instead of ad-hoc per-module retry loops (the pre-PR-2 state: linear sleeps
in rpc/core.py, a fixed 50 ms interval in scheduler/scheduling.py, a 600 s
watchdog in daemon/conductor.py and nothing else):

  backoff   — BackoffPolicy: exponential backoff with deterministic seeded
              jitter, shared by every retry loop in the tree (dflint DF024
              flags hand-rolled asyncio.sleep retry ladders outside here)
  breaker   — CircuitBreaker: per-target open/half-open/closed state so a
              dead scheduler costs one failure burst, not a timeout per call
  budget    — RetryBudget: per-process token bucket over retries/second per
              target class, so a thousand callers backing off in lockstep
              cannot synchronize into a retry storm; servers' retry_after_s
              hints pre-charge it (ISSUE 17)
  deadline  — cooperative deadline propagation (contextvar): a budget carried
              engine → conductor → scheduler-client, so nested rpc calls and
              piece fetches get min(remaining, per-op) timeouts instead of
              independent 30 s / 600 s constants
  faultline — deterministic, seeded fault injection behind named points in
              the hot paths (rpc frame IO, parent piece fetch, metadata
              long-poll, origin reads, storage writes); a single module-
              global None check when disabled, so production pays nothing

See README.md "Resilience" for semantics and the DF_FAULTS spec grammar.
"""

from dragonfly2_tpu.resilience.backoff import BackoffPolicy
from dragonfly2_tpu.resilience.breaker import CircuitBreaker
from dragonfly2_tpu.resilience.budget import RetryBudget, budget_for, reset_budgets
from dragonfly2_tpu.resilience.deadline import Deadline

__all__ = [
    "BackoffPolicy",
    "CircuitBreaker",
    "Deadline",
    "RetryBudget",
    "budget_for",
    "reset_budgets",
]
