"""Cooperative deadline propagation (the Go context.WithTimeout analogue).

A download enters the engine with one overall budget; before this module the
budget stopped at the conductor's watchdog while every nested operation used
its own independent constant (30 s rpc timeout, 25 s long-poll, 600 s
watchdog) — so a task could burn its whole budget inside a single stuck rpc.
Now the budget rides a contextvar: `scope(seconds)` narrows it (never
extends — nesting takes the min), and leaf operations ask
`timeout(per_op)` for min(per_op, remaining).

contextvars propagate into tasks created inside the scope (asyncio.Task
copies the current Context at creation), which is exactly the engine →
conductor → scheduler-client chain: the conductor future is created under
the engine's scope, so every rpc call and piece fetch it makes sees the
budget without any signature threading.
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager
from typing import Iterator

__all__ = ["Deadline", "current", "remaining", "timeout", "scope"]


class Deadline:
    """An absolute expiry on the monotonic clock."""

    __slots__ = ("expires_at",)

    def __init__(self, seconds: float, *, _expires_at: float | None = None):
        self.expires_at = (
            _expires_at if _expires_at is not None else time.monotonic() + seconds
        )

    def remaining(self) -> float:
        """Seconds left; never negative (0.0 means expired)."""
        return max(0.0, self.expires_at - time.monotonic())

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self.expires_at

    def timeout(self, per_op: float | None) -> float:
        """min(per_op, remaining) — the per-operation slice of the budget."""
        rem = self.remaining()
        return rem if per_op is None else min(per_op, rem)

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


_current: contextvars.ContextVar[Deadline | None] = contextvars.ContextVar(
    "df_deadline", default=None
)


def current() -> Deadline | None:
    """The active deadline, or None when no budget is set."""
    return _current.get()


def remaining() -> float | None:
    """Seconds left in the active budget, or None when no budget is set."""
    dl = _current.get()
    return None if dl is None else dl.remaining()


def timeout(per_op: float | None) -> float | None:
    """min(per_op, remaining): the timeout a leaf operation should use.
    With no active deadline this is just per_op (possibly None)."""
    dl = _current.get()
    if dl is None:
        return per_op
    return dl.timeout(per_op)


@contextmanager
def scope(seconds: float | None) -> Iterator[Deadline | None]:
    """Run a block under a (possibly narrowed) deadline.

    `seconds=None` is a no-op that yields the inherited deadline — callers
    with an optional user-supplied budget don't need two code paths. A nested
    scope can only shrink the budget: the effective expiry is
    min(parent expiry, now + seconds)."""
    parent = _current.get()
    if seconds is None:
        yield parent
        return
    expires = time.monotonic() + seconds
    if parent is not None:
        expires = min(expires, parent.expires_at)
    token = _current.set(Deadline(0, _expires_at=expires))
    try:
        yield _current.get()
    finally:
        _current.reset(token)
