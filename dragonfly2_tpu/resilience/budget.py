"""Cluster retry budgets: per-process token buckets gating RETRY traffic.

Per-client backoff (backoff.py) paces one caller's retries; it cannot stop a
THOUSAND callers from pacing in lockstep. When a scheduler stalls for two
seconds under a flash crowd, every daemon's rpc client independently decides
to retry — the cluster-wide result is a synchronized storm that arrives just
as the target comes back, re-killing it (the classic retry-amplification
failure; the reference's answer is the interceptor chain's budgeted retry).

A RetryBudget is a token bucket over retries-per-second for one TARGET CLASS
("scheduler", "manager", "source", ...), shared by every call site in the
process:

  first attempts are FREE       the budget never blocks new work, only the
                                amplification on top of it
  each retry spends one token   refilled at `rate` per second up to `burst`
  spend() False => fail fast    the caller moves to its NEXT fallback
                                (another parent, back-to-source, the cached
                                snapshot) instead of hammering the sick target
  charge(seconds)               servers propagating a `retry_after_s` hint
                                pre-charge the budget: one overloaded answer
                                mutes the whole process's retries against that
                                class for the hinted window, not just the one
                                caller that heard it

Clock-injected (utils/clock.py) so the swarm simulator and chaos tests drive
refill in virtual time. Thread-safe: conductor piece workers consult the same
bucket the loop's rpc clients do.
"""

from __future__ import annotations

import threading
from typing import Callable

from dragonfly2_tpu.utils import clock as clockmod

__all__ = ["RetryBudget", "budget_for", "reset_budgets"]

# retries/s the process may spend per target class; generous next to steady
# state (a healthy cluster retries rarely) and tiny next to a storm (1000
# in-flight tasks retrying at 1/s would want 1000/s)
DEFAULT_RATE = 10.0
DEFAULT_BURST = 20.0


class RetryBudget:
    """Token bucket over retries/second for one target class."""

    __slots__ = (
        "name", "rate", "burst", "_tokens", "_charged_until", "_last",
        "_clock", "_lock", "spent", "denied", "charges",
    )

    def __init__(
        self,
        name: str = "",
        *,
        rate: float = DEFAULT_RATE,
        burst: float = DEFAULT_BURST,
        clock: clockmod.Clock | None = None,
    ):
        if rate <= 0 or burst <= 0:
            raise ValueError(f"bad retry budget: rate={rate} burst={burst}")
        self.name = name
        self.rate = rate
        self.burst = burst
        self._clock = clock or clockmod.SYSTEM
        self._tokens = burst
        self._charged_until = 0.0  # retry_after_s pre-charge horizon
        self._last = self._clock.monotonic()
        self._lock = threading.Lock()
        self.spent = 0  # retries allowed
        self.denied = 0  # retries refused (caller fell through to fallback)
        self.charges = 0  # retry_after_s hints absorbed

    def _refill(self, now: float) -> None:
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)  # dflint: disable=DF023 only reachable from spend()/stats(), both of which hold self._lock around the call
        self._last = now

    def spend(self, tokens: float = 1.0) -> bool:
        """Try to spend budget for ONE retry. False = beyond budget: fail
        fast to the next fallback instead of amplifying load."""
        now = self._clock.monotonic()
        with self._lock:
            if now < self._charged_until:
                self.denied += 1
                return False
            self._refill(now)
            if self._tokens < tokens:
                self.denied += 1
                return False
            self._tokens -= tokens
            self.spent += 1
            return True

    def charge(self, retry_after_s: float) -> None:
        """Absorb a server's retry_after hint: no retry against this class
        until the hint expires (the horizon only ever extends — two servers
        hinting different windows leave the longer one standing)."""
        if retry_after_s <= 0:
            return
        now = self._clock.monotonic()
        with self._lock:
            self._charged_until = max(self._charged_until, now + retry_after_s)
            self.charges += 1

    def retry_after_remaining(self) -> float:
        """Seconds until the current pre-charge horizon expires (0 = none)."""
        with self._lock:
            return max(0.0, self._charged_until - self._clock.monotonic())

    def stats(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "rate": self.rate,
                "burst": self.burst,
                "tokens": round(self._tokens, 3),
                "spent": self.spent,
                "denied": self.denied,
                "charges": self.charges,
                "charged_for_s": round(
                    max(0.0, self._charged_until - self._clock.monotonic()), 3
                ),
            }


# ---------------------------------------------------------------------------
# process-wide registry: every call site retrying against "scheduler" spends
# from the SAME bucket — that sharing is the whole point

_budgets: dict[str, RetryBudget] = {}
_registry_lock = threading.Lock()


def budget_for(
    target_class: str,
    *,
    rate: float = DEFAULT_RATE,
    burst: float = DEFAULT_BURST,
    clock: clockmod.Clock | None = None,
) -> RetryBudget:
    """The process-wide budget for a target class, created on first use
    (rate/burst/clock apply only at creation)."""
    b = _budgets.get(target_class)
    if b is None:
        with _registry_lock:
            b = _budgets.get(target_class)
            if b is None:
                b = _budgets[target_class] = RetryBudget(
                    target_class, rate=rate, burst=burst, clock=clock
                )
    return b


def reset_budgets() -> None:
    """Drop every registered budget (test isolation; in-process restarts)."""
    with _registry_lock:
        _budgets.clear()


def budget_stats() -> list[dict]:
    with _registry_lock:
        return [b.stats() for b in _budgets.values()]
