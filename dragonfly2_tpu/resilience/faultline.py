"""Faultline: deterministic, seeded fault injection for the P2P data plane.

The degradation paths (parent death, flaky origin, corrupt pieces, slow rpc)
must be *proven*, not assumed — but real networks don't fail on cue. This
registry injects faults behind named points threaded through the hot paths:

    rpc.read           client/server frame read        latency error drop
    rpc.write          client/server frame write              error drop
    parent.fetch       parent piece HTTP fetch         latency error drop
    parent.piece_body  fetched piece payload                 truncate corrupt
    parent.metadata    parent metadata long-poll       latency error drop
    source.read        origin source chunk reads       latency error drop
    source.body        origin source chunk payload           truncate corrupt
    storage.write      storage piece writes            latency error
    storage.meta       metadata (save_metadata) flush  latency error
    model.load         model artifact read + digest    latency error truncate corrupt
    model.swap         evaluator scorer hot-swap             error drop

Fault kinds:
    latency   sleep `param` seconds (default 0.05) before proceeding
    error     raise FaultError (an IOError — looks like a real failed IO)
    drop      raise ConnectionResetError (a dead-socket failure)
    truncate  cut the payload short (drop `param` trailing bytes, default half)
    corrupt   flip one bit at a seeded position

Each rule fires with probability `rate` per traversal, driven by ONE seeded
random.Random — the injection sequence is a pure function of the seed and the
traversal order, so a failing chaos run replays with its seed. (Under
concurrency the traversal order follows the event-loop schedule; tests assert
outcomes — "download still completes bit-exact" — not exact sequences.)

Zero overhead when disabled: hot paths guard with

    if faultline.ACTIVE is not None: ...

one module-attribute load + identity check (no call, no dict lookup) — the
piece fetch path pays nothing in production.

Spec grammar (env DF_FAULTS, or enable() directly):

    DF_FAULTS="<point>:<kind>:<rate>[:<param>][,<entry>...][,seed=<n>]"
    DF_FAULTS="parent.fetch:error:0.2,source.read:latency:0.5:0.01,seed=7"
"""

from __future__ import annotations

import logging
import os
import random
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)

__all__ = [
    "ACTIVE", "FaultError", "FaultRule", "Faultline",
    "enable", "disable", "parse_spec", "install_from_env",
]

KINDS = ("latency", "error", "drop", "truncate", "corrupt")
_FIRE_KINDS = ("latency", "error", "drop")
_MUTATE_KINDS = ("truncate", "corrupt")


class FaultError(IOError):
    """An injected IO failure; subclasses IOError so every call site treats
    it exactly like the real failure it simulates."""


@dataclass(frozen=True)
class FaultRule:
    point: str
    kind: str
    rate: float
    param: float = 0.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (want one of {KINDS})")
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"fault rate must be in [0,1], got {self.rate}")


@dataclass
class Faultline:
    """A set of fault rules plus the seeded rng that drives them."""

    rules: list[FaultRule]
    seed: int = 0
    _rng: random.Random = field(init=False, repr=False)
    _by_point: dict[str, list[FaultRule]] = field(init=False, repr=False)
    injected: dict[tuple[str, str], int] = field(init=False, default_factory=dict)

    def __post_init__(self):
        self._rng = random.Random(self.seed)
        self._by_point = {}
        for r in self.rules:
            self._by_point.setdefault(r.point, []).append(r)

    def _hit(self, rule: FaultRule) -> bool:
        if self._rng.random() >= rule.rate:
            return False
        key = (rule.point, rule.kind)
        self.injected[key] = self.injected.get(key, 0) + 1
        return True

    def injected_total(self, point: str | None = None) -> int:
        return sum(
            n for (p, _), n in self.injected.items() if point is None or p == point
        )

    async def fire(self, point: str) -> None:
        """latency/error/drop rules for `point`; may sleep or raise."""
        import asyncio

        for rule in self._by_point.get(point, ()):
            if rule.kind not in _FIRE_KINDS or not self._hit(rule):
                continue
            if rule.kind == "latency":
                await asyncio.sleep(rule.param or 0.05)
            elif rule.kind == "error":
                raise FaultError(f"faultline: injected error at {point}")
            else:  # drop
                raise ConnectionResetError(f"faultline: injected drop at {point}")

    def check(self, point: str, *, blocking_latency: bool = False) -> None:
        """Sync variant of fire() for non-async call sites (frame writes):
        error/drop only by default — latency needs the loop, so it is skipped
        unless `blocking_latency` is set. Blocking latency (time.sleep) is for
        sync call sites that already run off the event loop or whose blocking
        is the very behavior under test (storage.meta: a slow metadata flush
        widens the debounce loss window deterministically)."""
        for rule in self._by_point.get(point, ()):
            if rule.kind not in _FIRE_KINDS:
                continue
            if rule.kind == "latency":
                if blocking_latency and self._hit(rule):
                    import time

                    time.sleep(rule.param or 0.05)
                continue
            if not self._hit(rule):
                continue
            if rule.kind == "error":
                raise FaultError(f"faultline: injected error at {point}")
            raise ConnectionResetError(f"faultline: injected drop at {point}")

    def mutate(self, point: str, data: bytes) -> bytes:
        """truncate/corrupt rules for `point`; returns the (possibly damaged)
        payload. With no matching rule the input object passes through
        untouched — no copy."""
        for rule in self._by_point.get(point, ()):
            if rule.kind not in _MUTATE_KINDS or not data or not self._hit(rule):
                continue
            if rule.kind == "truncate":
                cut = int(rule.param) if rule.param else max(1, len(data) // 2)
                return data[: max(0, len(data) - cut)]
            # corrupt: flip one bit at a seeded position
            buf = bytearray(data)
            i = self._rng.randrange(len(buf))
            buf[i] ^= 1 << self._rng.randrange(8)
            return bytes(buf)
        return data


# The one live Faultline, or None (the production state). Hot paths guard on
# `faultline.ACTIVE is not None` — keep this a plain module global so the
# disabled check is a single attribute load.
ACTIVE: Faultline | None = None


def parse_spec(spec: str) -> Faultline:
    """Build a Faultline from the DF_FAULTS grammar (see module docstring)."""
    rules: list[FaultRule] = []
    seed = 0
    for entry in (e.strip() for e in spec.split(",")):
        if not entry:
            continue
        if entry.startswith("seed="):
            seed = int(entry[len("seed="):])
            continue
        parts = entry.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"bad fault entry {entry!r}: want point:kind:rate[:param]"
            )
        point, kind, rate = parts[0], parts[1], float(parts[2])
        param = float(parts[3]) if len(parts) == 4 else 0.0
        rules.append(FaultRule(point=point, kind=kind, rate=rate, param=param))
    return Faultline(rules, seed=seed)


def enable(spec: "str | Faultline") -> Faultline:
    """Install a Faultline as the process-wide ACTIVE one; returns it."""
    global ACTIVE
    fl = parse_spec(spec) if isinstance(spec, str) else spec
    ACTIVE = fl
    logger.warning(
        "faultline ENABLED: %d rule(s), seed=%d — this process now injects faults",
        len(fl.rules), fl.seed,
    )
    return fl


def disable() -> None:
    global ACTIVE
    ACTIVE = None


def install_from_env(env: str = "DF_FAULTS") -> Faultline | None:
    """Enable from the environment (daemon boot path); None when unset.
    A malformed spec fails loudly — a chaos run that silently tested nothing
    is worse than one that refuses to start."""
    raw = os.environ.get(env, "")
    if not raw:
        return None
    return enable(raw)
