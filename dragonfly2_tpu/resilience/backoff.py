"""Exponential backoff with deterministic, seeded jitter.

One policy object replaces the tree's hand-rolled retry pacing (rpc/core.py's
linear `retry_backoff * (attempt + 1)`, scheduling.py's fixed
`retry_interval`, conductor's bare 0.5 s sleeps). The shape follows the
reference interceptor chain's exponential retry (pkg/rpc retry interceptor;
also the classic "full jitter" recommendation): delay for attempt k is

    min(max_delay, base * multiplier**k) * (1 - jitter * U[0,1))

i.e. jitter only ever shortens the delay, so `delay(k)` is bounded above by
the deterministic ladder — callers can reason about worst-case wait, and
tests can assert hard bounds. Determinism: pass a seeded random.Random; the
default is an rng seeded at construction so one policy's sequence is
reproducible under a fixed seed (chaos runs pin this).
"""

from __future__ import annotations

import asyncio
import random

__all__ = ["BackoffPolicy"]


class BackoffPolicy:
    """Delay schedule for retry attempt numbers 0, 1, 2, ...

    Immutable configuration, mutable rng. `attempt` is how many tries have
    already failed (first retry waits ~base)."""

    __slots__ = ("base", "multiplier", "max_delay", "jitter", "_rng")

    def __init__(
        self,
        *,
        base: float = 0.2,
        multiplier: float = 2.0,
        max_delay: float = 30.0,
        jitter: float = 0.5,
        rng: random.Random | None = None,
        seed: int | None = None,
    ):
        if base < 0 or multiplier < 1.0 or max_delay < 0 or not 0 <= jitter <= 1:
            raise ValueError(
                f"bad backoff policy: base={base} multiplier={multiplier} "
                f"max_delay={max_delay} jitter={jitter}"
            )
        self.base = base
        self.multiplier = multiplier
        self.max_delay = max_delay
        self.jitter = jitter
        self._rng = rng if rng is not None else random.Random(seed)

    def delay(self, attempt: int) -> float:
        """Seconds to wait after `attempt` failures (attempt >= 0)."""
        d = min(self.max_delay, self.base * self.multiplier ** max(0, attempt))
        if self.jitter:
            d *= 1.0 - self.jitter * self._rng.random()
        return d

    async def sleep(self, attempt: int) -> float:
        """asyncio.sleep(delay(attempt)); returns the slept delay."""
        d = self.delay(attempt)
        if d > 0:
            await asyncio.sleep(d)
        return d

    def __repr__(self) -> str:  # readable in logs/test failures
        return (
            f"BackoffPolicy(base={self.base}, multiplier={self.multiplier}, "
            f"max_delay={self.max_delay}, jitter={self.jitter})"
        )
