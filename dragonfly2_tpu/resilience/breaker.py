"""Per-target circuit breaker.

The classic three-state machine (closed → open after N consecutive failures,
open → half-open after a cooldown, half-open → closed on a successful probe /
back to open on a failed one). Shape follows the reference's health-aware
client-side balancing (pkg/balancer + interceptor retry): a dead scheduler
should cost one burst of failures and then a cheap local refusal per call,
not a full timeout per call, until a single probe proves it back.

Single-loop asyncio use: no locks needed — every transition is a synchronous
method on the loop thread. The clock is injectable for tests.
"""

from __future__ import annotations

import time
from typing import Callable

__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure breaker with a single half-open probe slot."""

    __slots__ = (
        "failure_threshold", "reset_timeout", "_clock",
        "state", "failures", "_opened_at", "_probe_inflight", "_probe_started",
    )

    def __init__(
        self,
        *,
        failure_threshold: int = 5,
        reset_timeout: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1 or reset_timeout < 0:
            raise ValueError(
                f"bad breaker config: threshold={failure_threshold} reset={reset_timeout}"
            )
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self._clock = clock
        self.state = CLOSED
        self.failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0

    def _take_probe_slot(self) -> None:
        self._probe_inflight = True
        self._probe_started = self._clock()

    def allow(self) -> bool:
        """May a call proceed now? In half-open, exactly one probe passes;
        the rest are refused until the probe reports. The probe slot is
        time-bound: a probe whose caller vanished without reporting (the rpc
        was cancelled mid-flight by a task watchdog, say) releases the slot
        after reset_timeout, so an abandoned probe can never wedge the
        breaker in half-open forever."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self._clock() - self._opened_at >= self.reset_timeout:
                self.state = HALF_OPEN
                self._take_probe_slot()
                return True
            return False
        # HALF_OPEN: one probe at a time
        if (
            not self._probe_inflight
            or self._clock() - self._probe_started >= self.reset_timeout
        ):
            self._take_probe_slot()
            return True
        return False

    @property
    def is_open(self) -> bool:
        """Open AND still inside the cooldown — i.e. a call right now would be
        refused outright. Used by the balancer to route new keys elsewhere;
        returns False once the cooldown lapses so probe traffic still reaches
        the target and can close the breaker again."""
        return (
            self.state == OPEN
            and self._clock() - self._opened_at < self.reset_timeout
        )

    def record_success(self) -> None:
        self.state = CLOSED
        self.failures = 0
        self._probe_inflight = False

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == HALF_OPEN or self.failures >= self.failure_threshold:
            self.state = OPEN
            self._opened_at = self._clock()
            self._probe_inflight = False

    def __repr__(self) -> str:
        return f"CircuitBreaker(state={self.state}, failures={self.failures})"
