// Native batched parent scorer: the XLA-AOT-style serving artifact.
//
// Serving equivalent of the reference's intended TensorFlow-Serving Predict
// hop (pkg/rpc/tfserving/client/client_v1.go:82-102), replaced per SURVEY.md
// §2.1 by a compiled CPU artifact linked into the scheduler process — no RPC,
// no Python, no JAX runtime on the hot path. The trainer exports cached
// GraphSAGE node embeddings plus the pairwise MLP head (models/graphsage.py
// TopoScorer.head: Dense→gelu→Dense→gelu→Dense→sigmoid) into a flat binary;
// this library mmap-loads it and scores a batch of (child, parent, features)
// candidates per call.
//
// Build: g++ -O3 -shared -fPIC -o libdfscorer.so scorer.cc  (see scorer.py)
//
// Artifact layout (little-endian):
//   u32 magic 0x44465343 ("DFSC")  u32 version=1
//   u32 N (nodes)  u32 D (embed dim)  u32 FP (pair-feature dim)
//   u32 H1  u32 H2 (head hidden dims)
//   f32 z[N*D]                      cached node embeddings (row-major)
//   f32 W1[(3D+FP)*H1]  f32 b1[H1]  head layer 0 (kernel column-major-in =
//   f32 W2[H1*H2]       f32 b2[H2]    flax [in, out] row-major)
//   f32 W3[H2*1]        f32 b3[1]

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

constexpr uint32_t kMagic = 0x44465343u;
constexpr uint32_t kVersion = 1u;

struct Header {
  uint32_t magic, version, n, d, fp, h1, h2;
};

inline float gelu(float x) {
  // tanh approximation — matches jax.nn.gelu(approximate=True), the flax
  // default used by TopoScorer.head
  const float kC = 0.7978845608028654f;  // sqrt(2/pi)
  return 0.5f * x * (1.0f + std::tanh(kC * (x + 0.044715f * x * x * x)));
}

inline float sigmoidf(float x) { return 1.0f / (1.0f + std::exp(-x)); }

// Y[B, out] = X[B, in] · W[in, out] + bias  (W row-major [in][out], flax
// layout). Loop order (i, b, o): each W row streams through cache once per
// batch instead of once per sample — the weight matrices dominate memory
// traffic at the ~40-candidate batch sizes the scheduler sends.
void gemm(const float* __restrict__ X, const float* __restrict__ W,
          const float* __restrict__ bias, float* __restrict__ Y, int B, int in,
          int out) {
  for (int b = 0; b < B; ++b) {
    float* Yrow = Y + static_cast<size_t>(b) * out;
    for (int o = 0; o < out; ++o) Yrow[o] = bias[o];
  }
  // 8-way unroll over the contraction dim: one pass over the Y slab handles
  // 8 input features (8 W rows live in L1), cutting accumulator re-stream
  // traffic 8x versus the naive (i, b, o) order.
  int i = 0;
  for (; i + 8 <= in; i += 8) {
    const float* W0 = W + static_cast<size_t>(i) * out;
    for (int b = 0; b < B; ++b) {
      const float* xb = X + static_cast<size_t>(b) * in + i;
      const float x0 = xb[0], x1 = xb[1], x2 = xb[2], x3 = xb[3];
      const float x4 = xb[4], x5 = xb[5], x6 = xb[6], x7 = xb[7];
      float* Yrow = Y + static_cast<size_t>(b) * out;
      for (int o = 0; o < out; ++o) {
        Yrow[o] += x0 * W0[o] + x1 * W0[out + o] + x2 * W0[2 * out + o] +
                   x3 * W0[3 * out + o] + x4 * W0[4 * out + o] +
                   x5 * W0[5 * out + o] + x6 * W0[6 * out + o] +
                   x7 * W0[7 * out + o];
      }
    }
  }
  for (; i < in; ++i) {
    const float* Wrow = W + static_cast<size_t>(i) * out;
    for (int b = 0; b < B; ++b) {
      const float xi = X[static_cast<size_t>(b) * in + i];
      float* Yrow = Y + static_cast<size_t>(b) * out;
      for (int o = 0; o < out; ++o) Yrow[o] += xi * Wrow[o];
    }
  }
}

}  // namespace

extern "C" {

struct DfScorer {
  Header hdr;
  std::vector<float> z, w1, b1, w2, b2, w3, b3;
};

DfScorer* df_scorer_load(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  DfScorer* s = new DfScorer();
  bool ok = std::fread(&s->hdr, sizeof(Header), 1, f) == 1 &&
            s->hdr.magic == kMagic && s->hdr.version == kVersion;
  if (ok) {
    const Header& h = s->hdr;
    const uint32_t in = 3 * h.d + h.fp;
    auto rd = [&](std::vector<float>& v, size_t count) {
      v.resize(count);
      return std::fread(v.data(), sizeof(float), count, f) == count;
    };
    ok = rd(s->z, (size_t)h.n * h.d) && rd(s->w1, (size_t)in * h.h1) &&
         rd(s->b1, h.h1) && rd(s->w2, (size_t)h.h1 * h.h2) && rd(s->b2, h.h2) &&
         rd(s->w3, h.h2) && rd(s->b3, 1);
  }
  std::fclose(f);
  if (!ok) {
    delete s;
    return nullptr;
  }
  return s;
}

void df_scorer_free(DfScorer* s) { delete s; }

int32_t df_scorer_num_nodes(const DfScorer* s) { return (int32_t)s->hdr.n; }
int32_t df_scorer_embed_dim(const DfScorer* s) { return (int32_t)s->hdr.d; }
int32_t df_scorer_feature_dim(const DfScorer* s) { return (int32_t)s->hdr.fp; }

// Score `batch` (child, parent) pairs; feats is [batch, FP] row-major.
// Returns 0 on success, -1 on an out-of-range node index.
int32_t df_scorer_score(const DfScorer* s, const int32_t* child,
                        const int32_t* parent, const float* feats,
                        int32_t batch, float* out) {
  const Header& h = s->hdr;
  const int32_t in_dim = 3 * h.d + h.fp;
  // validate all indices up front, then run three batched GEMMs
  for (int32_t b = 0; b < batch; ++b) {
    const int32_t c = child[b], p = parent[b];
    if (c < 0 || p < 0 || (uint32_t)c >= h.n || (uint32_t)p >= h.n) return -1;
  }
  std::vector<float> x((size_t)batch * in_dim);
  std::vector<float> y1((size_t)batch * h.h1), y2((size_t)batch * h.h2);

  // Slice the batch across threads when OpenMP is available (TPU-VM serving
  // hosts have dozens of cores; the container CI has one and runs the serial
  // path). Each slice runs the full pipeline independently.
  int slices = 1;
#ifdef _OPENMP
  slices = std::min<int>(omp_get_max_threads(), std::max<int32_t>(1, batch / 8));
#endif
  const int32_t chunk = (batch + slices - 1) / slices;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) num_threads(slices)
#endif
  for (int si = 0; si < slices; ++si) {
    const int32_t b0 = si * chunk;
    const int32_t bn = std::min<int32_t>(batch - b0, chunk);
    if (bn <= 0) continue;
    for (int32_t b = b0; b < b0 + bn; ++b) {
      float* xb = x.data() + (size_t)b * in_dim;
      const float* zc = s->z.data() + (size_t)child[b] * h.d;
      const float* zp = s->z.data() + (size_t)parent[b] * h.d;
      for (uint32_t i = 0; i < h.d; ++i) {
        xb[i] = zc[i];
        xb[h.d + i] = zp[i];
        xb[2 * h.d + i] = zc[i] * zp[i];
      }
      std::memcpy(xb + 3 * h.d, feats + (size_t)b * h.fp, h.fp * sizeof(float));
    }
    float* x0 = x.data() + (size_t)b0 * in_dim;
    float* y1p = y1.data() + (size_t)b0 * h.h1;
    float* y2p = y2.data() + (size_t)b0 * h.h2;
    gemm(x0, s->w1.data(), s->b1.data(), y1p, bn, in_dim, h.h1);
    for (size_t i = 0; i < (size_t)bn * h.h1; ++i) y1p[i] = gelu(y1p[i]);
    gemm(y1p, s->w2.data(), s->b2.data(), y2p, bn, h.h1, h.h2);
    for (size_t i = 0; i < (size_t)bn * h.h2; ++i) y2p[i] = gelu(y2p[i]);
    for (int32_t b = b0; b < b0 + bn; ++b) {
      const float* yb = y2.data() + (size_t)b * h.h2;
      float o = s->b3[0];
      for (uint32_t i = 0; i < h.h2; ++i) o += yb[i] * s->w3[i];
      out[b] = sigmoidf(o);
    }
  }
  return 0;
}

}  // extern "C"
