// Native batched parent scorer: the XLA-AOT-style serving artifact.
//
// Serving equivalent of the reference's intended TensorFlow-Serving Predict
// hop (pkg/rpc/tfserving/client/client_v1.go:82-102), replaced per SURVEY.md
// §2.1 by a compiled CPU artifact linked into the scheduler process — no RPC,
// no Python, no JAX runtime on the hot path. The trainer exports cached
// GraphSAGE node embeddings plus the pairwise MLP head (models/graphsage.py
// TopoScorer.head: Dense→gelu→Dense→gelu→Dense→sigmoid) into a flat binary;
// this library mmap-loads it and scores batches of (child, parent, features)
// candidates.
//
// Serving math. The head's first layer sees x = [z_c, z_p, z_c∘z_p, feats].
// Because z is a FIXED node table at serving time, the z_c and z_p
// contributions to layer 1 are linear in a per-node vector and are
// precomputed at load time:
//     uc[n] = W1[0:D]ᵀ z[n]        up[n] = W1[D:2D]ᵀ z[n]       ([N, H1] each)
// so a scoring round only contracts the (z_c∘z_p, feats) tail — (D+FP) input
// dims instead of (3D+FP), a ~2.8× FLOP cut at the shipped shapes
// (D=128, FP=16, H1=256).
//
// Entry points:
//   df_scorer_score        — one scheduling round (B candidate pairs)
//   df_scorer_score_rounds — M queued rounds in ONE FFI call (the 10k-calls/s
//                            amortized path; rounds are independent, so this
//                            is a flat (M·B)-row batch through the same GEMMs)
//   df_round_drive         — M whole scheduling rounds in ONE call: re-validate
//                            the Python-snapshotted filter fields, fill the
//                            round-constant feature columns, score every
//                            survivor row through the same per-row pipeline as
//                            score_rounds (bit-identical math), then stable
//                            top-k per round. Python keeps only the snapshot
//                            (under the scheduler state lock) and the commit.
//
// Thread safety: scratch buffers live in the handle, so concurrent scoring
// calls on ONE handle are serialized by an internal mutex (ctypes releases
// the GIL during the call — without the lock two Python threads sharing a
// scorer would race on the scratch vectors). For parallel serving use one
// handle per thread — df_scorer_fork hands out extra handles that SHARE the
// immutable model data (weights/embeddings/precompute, refcounted), so N
// worker threads cost one model's cache footprint, not N. OpenMP (when
// compiled in) parallelizes INSIDE a call across row blocks;
// df_scorer_set_thread_parallelism caps that per calling thread.
//
// Build: g++ -O3 -shared -fPIC -o libdfscorer.so scorer.cc  (see scorer.py)
//
// Artifact layout (little-endian):
//   u32 magic 0x44465343 ("DFSC")  u32 version=1
//   u32 N (nodes)  u32 D (embed dim)  u32 FP (pair-feature dim)
//   u32 H1  u32 H2 (head hidden dims)
//   f32 z[N*D]                      cached node embeddings (row-major)
//   f32 W1[(3D+FP)*H1]  f32 b1[H1]  head layer 0 (kernel = flax [in, out]
//   f32 W2[H1*H2]       f32 b2[H2]    row-major)
//   f32 W3[H2*1]        f32 b3[1]

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

constexpr uint32_t kMagic = 0x44465343u;
constexpr uint32_t kVersion = 1u;

struct Header {
  uint32_t magic, version, n, d, fp, h1, h2;
};

// Rational tanh (Eigen's float coefficients): 7 FMAs + one divide, fully
// vectorizable — std::tanh would cost a libm call per element and the gelu
// pass touches H1+H2 = 384 activations per candidate. Max abs error vs
// libm tanhf is ~1e-6, far inside the bf16 tolerance the JAX-parity test
// allows.
inline float fast_tanh(float x) {
  x = std::min(std::max(x, -7.90531110763549805f), 7.90531110763549805f);
  const float x2 = x * x;
  float p = -2.76076847742355e-16f;
  p = p * x2 + 2.00018790482477e-13f;
  p = p * x2 + -8.60467152213735e-11f;
  p = p * x2 + 5.12229709037114e-08f;
  p = p * x2 + 1.48572235717979e-05f;
  p = p * x2 + 6.37261928875436e-04f;
  p = p * x2 + 4.89352455891786e-03f;
  p = p * x;
  float q = 1.19825839466702e-06f;
  q = q * x2 + 1.18534705686654e-04f;
  q = q * x2 + 2.26843463243900e-03f;
  q = q * x2 + 4.89352518554385e-03f;
  return p / q;
}

inline float gelu(float x) {
  // tanh approximation — matches jax.nn.gelu(approximate=True), the flax
  // default used by TopoScorer.head
  const float kC = 0.7978845608028654f;  // sqrt(2/pi)
  return 0.5f * x * (1.0f + fast_tanh(kC * (x + 0.044715f * x * x * x)));
}

inline float sigmoidf(float x) { return 1.0f / (1.0f + std::exp(-x)); }

// 16-lane float vector via GNU vector extensions (gcc/clang): one AVX-512
// zmm or a ymm pair. Local float[16] accumulator arrays looked equivalent but
// gcc 12 spilled them to the stack inside the FMA loop; typed vector
// variables stay in registers.
typedef float v16 __attribute__((vector_size(64)));
typedef float v16u __attribute__((vector_size(64), aligned(4), may_alias));

inline v16 loadu16(const float* p) { return *reinterpret_cast<const v16u*>(p); }
inline void storeu16(float* p, v16 v) { *reinterpret_cast<v16u*>(p) = v; }

// Y[R, out] += X[R, in] · W[in, out]   (W row-major [in][out], flax layout;
// Y PRE-INITIALIZED by the caller with bias / per-node partials).
//
// Register-blocked micro-kernel: 8 rows × 16 cols of Y live in 8 vector
// registers across the whole contraction — Y is read and written exactly
// once, and each streamed W vector feeds 8 FMAs.
//
// Loop order is column-panel OUTER, row-block inner: the W panel
// (in × 16 floats, ~9 KB at serving shapes) stays L1-resident across every
// row block, so W streams from cache once per call. The row-outer order
// re-streamed the whole W (147 KB at H1=256) per 8-row block — R/8 × 147 KB
// of LLC/DRAM traffic per call, which is what capped two dispatcher
// workers' concurrent GEMMs at ~1.3x on a host whose ALU-bound work scales
// 1.93x (X re-reads cost in/out = ~0.5 the W traffic saved, and X rows are
// hot in L2 anyway).
void gemm_acc(const float* __restrict__ X, const float* __restrict__ W,
              float* __restrict__ Y, int R, int in, int out) {
  constexpr int RB = 8, CB = 16;
  int o = 0;
  for (; o + CB <= out; o += CB) {
    const float* Wp = W + o;
    int r = 0;
    for (; r + RB <= R; r += RB) {
      const float* x[RB];
      float* y[RB];
      for (int k = 0; k < RB; ++k) {
        x[k] = X + static_cast<size_t>(r + k) * in;
        y[k] = Y + static_cast<size_t>(r + k) * out;
      }
      v16 a0 = loadu16(y[0] + o), a1 = loadu16(y[1] + o);
      v16 a2 = loadu16(y[2] + o), a3 = loadu16(y[3] + o);
      v16 a4 = loadu16(y[4] + o), a5 = loadu16(y[5] + o);
      v16 a6 = loadu16(y[6] + o), a7 = loadu16(y[7] + o);
      const float* w = Wp;
      for (int i = 0; i < in; ++i, w += out) {
        const v16 wv = loadu16(w);
        a0 += x[0][i] * wv;
        a1 += x[1][i] * wv;
        a2 += x[2][i] * wv;
        a3 += x[3][i] * wv;
        a4 += x[4][i] * wv;
        a5 += x[5][i] * wv;
        a6 += x[6][i] * wv;
        a7 += x[7][i] * wv;
      }
      storeu16(y[0] + o, a0);
      storeu16(y[1] + o, a1);
      storeu16(y[2] + o, a2);
      storeu16(y[3] + o, a3);
      storeu16(y[4] + o, a4);
      storeu16(y[5] + o, a5);
      storeu16(y[6] + o, a6);
      storeu16(y[7] + o, a7);
    }
    for (; r < R; ++r) {
      const float* xr = X + static_cast<size_t>(r) * in;
      float* yr = Y + static_cast<size_t>(r) * out;
      v16 a = loadu16(yr + o);
      const float* w = Wp;
      for (int i = 0; i < in; ++i, w += out) a += xr[i] * loadu16(w);
      storeu16(yr + o, a);
    }
  }
  for (; o < out; ++o) {
    const float* w0 = W + o;
    for (int r = 0; r < R; ++r) {
      const float* xr = X + static_cast<size_t>(r) * in;
      float a = Y[static_cast<size_t>(r) * out + o];
      const float* w = w0;
      for (int i = 0; i < in; ++i, w += out) a += xr[i] * *w;
      Y[static_cast<size_t>(r) * out + o] = a;
    }
  }
}

}  // namespace

extern "C" {

// Cap THIS THREAD's intra-call OpenMP parallelism (nthreads ICV is
// per-thread). The scheduler's round dispatcher pins its worker threads to
// 1: it shards rounds ACROSS workers, and letting every worker's GEMM also
// fan out OMP threads oversubscribes the host — libgomp's spin-waiting
// helpers burn the very cores the other workers' Python needs (measured
// NEGATIVE scaling, 0.74x at 2 workers on a 2-core host). Single-threaded
// callers (the micro-batch serving path, the bench headline) never call
// this and keep whole-host intra-call parallelism. No-op without OpenMP.
void df_scorer_set_thread_parallelism(int n) {
#ifdef _OPENMP
  if (n > 0) omp_set_num_threads(n);
#endif
  (void)n;
}

// Immutable model data, SHARED across handles (refcounted): the weights,
// embeddings, and uc/up precompute total ~1-2 MB at serving shapes, and the
// GEMM streams them every call — per-handle copies would double the cache
// working set per added worker thread and thrash the shared LLC (measured:
// duplicating the model capped 2-worker scaling at ~1.2x on a host whose
// compute scales 1.93x; sharing restores the headroom). Handles only own
// scratch + a mutex.
struct DfModel {
  Header hdr;
  std::vector<float> z, w1, b1, w2, b2, w3, b3;
  // load-time precompute: first-layer contributions of each node's embedding
  // in child position (uc) and parent position (up), [N, H1] each
  std::vector<float> uc, up;
  std::atomic<int32_t> refs{1};
};

struct DfScorer {
  DfModel* model;
  // per-handle scratch reused across calls (no per-call malloc on the hot
  // path); sliced disjointly by OpenMP row blocks inside one call, guarded
  // across calls by `mu` — which is why concurrent threads need one handle
  // each (df_scorer_fork)
  std::vector<float> sx, sy1, sy2;
  std::mutex mu;
};

DfScorer* df_scorer_load(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  DfModel* m = new DfModel();
  bool ok = std::fread(&m->hdr, sizeof(Header), 1, f) == 1 &&
            m->hdr.magic == kMagic && m->hdr.version == kVersion;
  if (ok) {
    const Header& h = m->hdr;
    const uint32_t in = 3 * h.d + h.fp;
    auto rd = [&](std::vector<float>& v, size_t count) {
      v.resize(count);
      return std::fread(v.data(), sizeof(float), count, f) == count;
    };
    ok = rd(m->z, (size_t)h.n * h.d) && rd(m->w1, (size_t)in * h.h1) &&
         rd(m->b1, h.h1) && rd(m->w2, (size_t)h.h1 * h.h2) && rd(m->b2, h.h2) &&
         rd(m->w3, h.h2) && rd(m->b3, 1);
  }
  std::fclose(f);
  if (!ok) {
    delete m;
    return nullptr;
  }
  // Precompute uc = z · W1[0:D], up = z · W1[D:2D]  (one-time ~2·N·D·H1 MACs)
  const Header& h = m->hdr;
  m->uc.assign((size_t)h.n * h.h1, 0.0f);
  m->up.assign((size_t)h.n * h.h1, 0.0f);
  gemm_acc(m->z.data(), m->w1.data(), m->uc.data(), (int)h.n, (int)h.d,
           (int)h.h1);
  gemm_acc(m->z.data(), m->w1.data() + (size_t)h.d * h.h1, m->up.data(),
           (int)h.n, (int)h.d, (int)h.h1);
  DfScorer* s = new DfScorer();
  s->model = m;
  return s;
}

// A new handle onto the SAME model (refs++): fresh scratch + mutex, zero
// copies — the per-worker-thread handle the scheduler's round dispatcher
// uses (scorer.cc rule: one handle per thread).
DfScorer* df_scorer_fork(DfScorer* s) {
  if (!s) return nullptr;
  s->model->refs.fetch_add(1, std::memory_order_relaxed);
  DfScorer* t = new DfScorer();
  t->model = s->model;
  return t;
}

void df_scorer_free(DfScorer* s) {
  if (!s) return;
  if (s->model->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    delete s->model;
  }
  delete s;
}

int32_t df_scorer_num_nodes(const DfScorer* s) { return (int32_t)s->model->hdr.n; }
int32_t df_scorer_embed_dim(const DfScorer* s) { return (int32_t)s->model->hdr.d; }
int32_t df_scorer_feature_dim(const DfScorer* s) { return (int32_t)s->model->hdr.fp; }

// The full three-stage per-row pipeline over R pre-validated rows: child/
// parent are PER-ROW node indices, feats row for row b is feats[row_map[b]]
// (row_map == nullptr ⇒ identity). Both public entries funnel here, so the
// multi-round batch path and the round driver are bit-identical by
// construction — per-row math never depends on the batch shape.
static void score_rows(DfScorer* s, const int32_t* child, const int32_t* parent,
                       const float* feats, const int32_t* row_map, int32_t R,
                       float* out) {
  const DfModel* m = s->model;
  const Header& h = m->hdr;
  const int D = (int)h.d, FP = (int)h.fp, H1 = (int)h.h1, H2 = (int)h.h2;
  const int in1 = D + FP;  // contraction after the uc/up precompute
  // Row-TILE the whole three-stage pipeline (128 rows ≈ 72 KB X + 128 KB Y1
  // scratch): running each stage over the full R first meant ~550 KB of
  // scratch churn per call — two dispatcher workers' concurrent calls then
  // fought over the shared cache (measured 1.33x scaling where ALU-bound
  // work scales 1.93x on this host). Tiled, each worker's hot set stays
  // private-cache-sized; the extra W1t re-streams per tile are L2 reads.
  constexpr int32_t kRowTile = 128;
  std::lock_guard<std::mutex> lock(s->mu);
  const int32_t tile = std::min<int32_t>(R, kRowTile);
  s->sx.resize((size_t)tile * in1);
  s->sy1.resize((size_t)tile * H1);
  s->sy2.resize((size_t)tile * H2);
  // W1 tail = rows [2D, 3D+FP) — the z_c∘z_p and pair-feature blocks, which
  // are contiguous in the artifact's row-major kernel
  const float* W1t = m->w1.data() + (size_t)2 * D * h.h1;

  int nblk = 1;
#ifdef _OPENMP
  nblk = std::min<int>(omp_get_max_threads(), std::max<int32_t>(1, R / kRowTile));
  if (nblk > 1) {
    // per-OMP-thread scratch tiles, disjoint slices of the handle's buffers
    s->sx.resize((size_t)nblk * tile * in1);
    s->sy1.resize((size_t)nblk * tile * H1);
    s->sy2.resize((size_t)nblk * tile * H2);
  }
#endif
  const int32_t chunk = (R + nblk - 1) / nblk;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) num_threads(nblk) if (nblk > 1)
#endif
  for (int blk = 0; blk < nblk; ++blk) {
    const int32_t c0 = blk * chunk;
    const int32_t cn = std::min<int32_t>(R - c0, chunk);
    if (cn <= 0) continue;
    float* X = s->sx.data() + (size_t)blk * tile * in1;
    float* Y1 = s->sy1.data() + (size_t)blk * tile * H1;
    float* Y2 = s->sy2.data() + (size_t)blk * tile * H2;
    for (int32_t t0 = c0; t0 < c0 + cn; t0 += tile) {
      const int32_t b0 = t0;
      const int32_t bn = std::min<int32_t>(c0 + cn - t0, tile);
      // stage 1: build the reduced input rows + preload Y1 with
      // b1 + uc[child] + up[parent] — scratch rows are tile-local
      for (int32_t b = b0; b < b0 + bn; ++b) {
        float* xb = X + (size_t)(b - b0) * in1;
        const float* zc = m->z.data() + (size_t)child[b] * D;
        const float* zp = m->z.data() + (size_t)parent[b] * D;
        for (int i = 0; i < D; ++i) xb[i] = zc[i] * zp[i];
        const size_t frow = row_map ? (size_t)row_map[b] : (size_t)b;
        std::memcpy(xb + D, feats + frow * FP, FP * sizeof(float));
        float* yb = Y1 + (size_t)(b - b0) * H1;
        const float* ucr = m->uc.data() + (size_t)child[b] * H1;
        const float* upr = m->up.data() + (size_t)parent[b] * H1;
        for (int i = 0; i < H1; ++i) yb[i] = m->b1[i] + ucr[i] + upr[i];
      }
      gemm_acc(X, W1t, Y1, bn, in1, H1);
      for (size_t i = 0; i < (size_t)bn * H1; ++i) Y1[i] = gelu(Y1[i]);
      for (int32_t b = 0; b < bn; ++b)
        std::memcpy(Y2 + (size_t)b * H2, m->b2.data(), H2 * sizeof(float));
      gemm_acc(Y1, m->w2.data(), Y2, bn, H1, H2);
      for (size_t i = 0; i < (size_t)bn * H2; ++i) Y2[i] = gelu(Y2[i]);
      for (int32_t b = 0; b < bn; ++b) {
        const float* yb = Y2 + (size_t)b * H2;
        float o = m->b3[0];
        for (int i = 0; i < H2; ++i) o += yb[i] * m->w3[i];
        out[b0 + b] = sigmoidf(o);
      }
    }
  }
}

// Score `rounds` independent scheduling rounds of `batch` (child, parent)
// pairs each in ONE call: child/parent are [rounds*batch] i32, feats is
// [rounds*batch, FP] row-major, out is [rounds*batch] f32. The multi-round
// entry amortizes FFI + dispatch overhead across rounds (north-star config 5's
// 10k-calls/s path). Returns 0 on success, -1 on an out-of-range node index.
int32_t df_scorer_score_rounds(DfScorer* s, const int32_t* child,
                               const int32_t* parent, const float* feats,
                               int32_t rounds, int32_t batch, float* out) {
  const Header& h = s->model->hdr;
  const int64_t total64 = (int64_t)rounds * batch;
  if (total64 <= 0 || total64 > (int64_t)1 << 24) return total64 == 0 ? 0 : -2;
  const int32_t R = (int32_t)total64;
  for (int32_t b = 0; b < R; ++b) {
    const int32_t c = child[b], p = parent[b];
    if (c < 0 || p < 0 || (uint32_t)c >= h.n || (uint32_t)p >= h.n) return -1;
  }
  score_rows(s, child, parent, feats, nullptr, R, out);
  return 0;
}

// Single-round entry (kept for API compatibility; one round of `batch` pairs).
int32_t df_scorer_score(DfScorer* s, const int32_t* child,
                        const int32_t* parent, const float* feats,
                        int32_t batch, float* out) {
  return df_scorer_score_rounds(s, child, parent, feats, 1, batch, out);
}

// ── The native round driver ────────────────────────────────────────────────
//
// One FFI call drives a BATCH of whole scheduling rounds: re-validate →
// fill round-constant feature columns → score → stable top-k, all with the
// GIL released. Arena contract (all buffers owned and reused by the caller):
//
//   offsets    [M+1] i32 — survivor rows of round r are [offsets[r], offsets[r+1])
//   child_idx  [M]   i32 — embedding-table index of the round's child (-1 unknown)
//   parent_idx [T]   i32 — per survivor row (-1 unknown), T = offsets[M]
//   feats      [T,FP]f32 — validated pair rows; round-constant columns
//                          (10 = finished-piece ratio, 11 = log-scaled
//                          content length, 13 = scaled schedule rounds) are
//                          broadcast HERE from round_cols
//   round_cols [M,3] f32 — the three round-constant values, computed in
//                          Python with the same float32 ops as
//                          _fill_round_columns
//   filt       [T,4] i32 — (flags, state_code, free_upload_slots, depth)
//                          snapshotted under the scheduler state lock
//
// Outputs: out_scores [T] f32 (NaN for rows the driver did not score),
// sel [M,k] i32 local survivor indices (-1 pad), n_sel [M] i32, and
// status [M] i32: 0 = natively scored, 1 = round must re-run on the Python
// serial leg (unknown node index, stale embedding table, or a filter field
// that disagrees with the snapshot predicate) — the caller routes those
// through the bit-identical evaluate_many path, preserving serial semantics
// for every fallback taxonomy case.
//
// Returns 0 on success; -2 row-cap overflow, -3 feature schema too narrow
// for the round-constant columns, -4 malformed offsets.
int32_t df_round_drive(DfScorer* s, const int32_t* offsets,
                       const int32_t* child_idx, const int32_t* parent_idx,
                       float* feats, const float* round_cols,
                       const int32_t* filt, int32_t rounds, int32_t k,
                       int32_t max_depth, float* out_scores, int32_t* sel,
                       int32_t* n_sel, int32_t* status) {
  const Header& h = s->model->hdr;
  if (rounds <= 0) return 0;
  const int FP = (int)h.fp;
  if (FP <= 13) return -3;
  const int64_t total64 = (int64_t)offsets[rounds];
  if (total64 < 0 || total64 > (int64_t)1 << 24) return -2;
  const int32_t T = (int32_t)total64;

  // Pass 1 (per round): native-or-fallback decision, round-column broadcast,
  // and compaction of the scorable rows (fallback rounds' rows are skipped).
  std::vector<int32_t> crow, prow, rmap;
  crow.reserve(T);
  prow.reserve(T);
  rmap.reserve(T);
  for (int32_t r = 0; r < rounds; ++r) {
    const int32_t t0 = offsets[r], t1 = offsets[r + 1];
    n_sel[r] = 0;
    for (int32_t j = 0; j < k; ++j) sel[(size_t)r * k + j] = -1;
    if (t1 < t0 || t0 < 0) return -4;
    if (t1 == t0) {  // no survivors: an empty round, natively resolved
      status[r] = 0;
      continue;
    }
    const int32_t c = child_idx[r];
    bool native = c >= 0 && (uint32_t)c < h.n;
    for (int32_t t = t0; native && t < t1; ++t) {
      const int32_t p = parent_idx[t];
      const int32_t* f = filt + (size_t)t * 4;
      if (p < 0 || (uint32_t)p >= h.n ||
          f[0] != 0 || f[1] < 0 || f[2] <= 0 || f[3] >= max_depth) {
        native = false;
      }
    }
    if (!native) {
      status[r] = 1;
      continue;
    }
    status[r] = 0;
    const float* rc = round_cols + (size_t)r * 3;
    for (int32_t t = t0; t < t1; ++t) {
      float* fr = feats + (size_t)t * FP;
      fr[10] = rc[0];
      fr[11] = rc[1];
      fr[13] = rc[2];
      crow.push_back(c);
      prow.push_back(parent_idx[t]);
      rmap.push_back(t);
    }
  }

  // Pass 2: one shared-pipeline scoring sweep over the compacted rows.
  const int32_t RC = (int32_t)rmap.size();
  std::vector<float> cs((size_t)RC);
  if (RC > 0) score_rows(s, crow.data(), prow.data(), feats, rmap.data(), RC, cs.data());
  for (int32_t t = 0; t < T; ++t) out_scores[t] = std::nanf("");
  for (int32_t i = 0; i < RC; ++i) out_scores[rmap[i]] = cs[i];

  // Pass 3: stable top-k per native round. Matches
  // np.argsort(-scores, kind="stable")[:k] exactly: descending score, ties
  // broken by survivor index, NaN ranked last (numpy sorts NaN to the end).
  std::vector<int32_t> order;
  for (int32_t r = 0; r < rounds; ++r) {
    if (status[r] != 0 || k <= 0) continue;
    const int32_t t0 = offsets[r];
    const int32_t nr = offsets[r + 1] - t0;
    if (nr <= 0) continue;
    order.resize(nr);
    for (int32_t j = 0; j < nr; ++j) order[j] = j;
    const float* sc = out_scores + t0;
    std::stable_sort(order.begin(), order.end(), [sc](int32_t a, int32_t b) {
      const float xa = sc[a], xb = sc[b];
      const bool na = std::isnan(xa), nb = std::isnan(xb);
      if (na || nb) return nb && !na;  // non-NaN sorts before NaN
      return xa > xb;
    });
    const int32_t kk = std::min<int32_t>(k, nr);
    for (int32_t j = 0; j < kk; ++j) sel[(size_t)r * k + j] = order[j];
    n_sel[r] = kk;
  }
  return 0;
}

}  // extern "C"
