// Native batched parent scorer: the XLA-AOT-style serving artifact.
//
// Serving equivalent of the reference's intended TensorFlow-Serving Predict
// hop (pkg/rpc/tfserving/client/client_v1.go:82-102), replaced per SURVEY.md
// §2.1 by a compiled CPU artifact linked into the scheduler process — no RPC,
// no Python, no JAX runtime on the hot path. The trainer exports cached
// GraphSAGE node embeddings plus the pairwise MLP head (models/graphsage.py
// TopoScorer.head: Dense→gelu→Dense→gelu→Dense→sigmoid) into a flat binary;
// this library mmap-loads it and scores batches of (child, parent, features)
// candidates.
//
// Serving math. The head's first layer sees x = [z_c, z_p, z_c∘z_p, feats].
// Because z is a FIXED node table at serving time, the z_c and z_p
// contributions to layer 1 are linear in a per-node vector and are
// precomputed at load time:
//     uc[n] = W1[0:D]ᵀ z[n]        up[n] = W1[D:2D]ᵀ z[n]       ([N, H1] each)
// so a scoring round only contracts the (z_c∘z_p, feats) tail — (D+FP) input
// dims instead of (3D+FP), a ~2.8× FLOP cut at the shipped shapes
// (D=128, FP=16, H1=256).
//
// Entry points:
//   df_scorer_score        — one scheduling round (B candidate pairs)
//   df_scorer_score_rounds — M queued rounds in ONE FFI call (the 10k-calls/s
//                            amortized path; rounds are independent, so this
//                            is a flat (M·B)-row batch through the same GEMMs)
//   df_round_drive         — M whole scheduling rounds in ONE call: re-validate
//                            the Python-snapshotted filter fields, fill the
//                            round-constant feature columns, score every
//                            survivor row through the same per-row pipeline as
//                            score_rounds (bit-identical math), then stable
//                            top-k per round. Python keeps only the snapshot
//                            (under the scheduler state lock) and the commit.
//
// Thread safety: scratch buffers live in the handle, so concurrent scoring
// calls on ONE handle are serialized by an internal mutex (ctypes releases
// the GIL during the call — without the lock two Python threads sharing a
// scorer would race on the scratch vectors). For parallel serving use one
// handle per thread — df_scorer_fork hands out extra handles that SHARE the
// immutable model data (weights/embeddings/precompute, refcounted), so N
// worker threads cost one model's cache footprint, not N. OpenMP (when
// compiled in) parallelizes INSIDE a call across row blocks;
// df_scorer_set_thread_parallelism caps that per calling thread.
//
// Build: g++ -O3 -shared -fPIC -o libdfscorer.so scorer.cc  (see scorer.py)
//
// Artifact layout (little-endian):
//   u32 magic 0x44465343 ("DFSC")  u32 version=1
//   u32 N (nodes)  u32 D (embed dim)  u32 FP (pair-feature dim)
//   u32 H1  u32 H2 (head hidden dims)
//   f32 z[N*D]                      cached node embeddings (row-major)
//   f32 W1[(3D+FP)*H1]  f32 b1[H1]  head layer 0 (kernel = flax [in, out]
//   f32 W2[H1*H2]       f32 b2[H2]    row-major)
//   f32 W3[H2*1]        f32 b3[1]

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <unordered_map>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

constexpr uint32_t kMagic = 0x44465343u;
constexpr uint32_t kVersion = 1u;

struct Header {
  uint32_t magic, version, n, d, fp, h1, h2;
};

// Rational tanh (Eigen's float coefficients): 7 FMAs + one divide, fully
// vectorizable — std::tanh would cost a libm call per element and the gelu
// pass touches H1+H2 = 384 activations per candidate. Max abs error vs
// libm tanhf is ~1e-6, far inside the bf16 tolerance the JAX-parity test
// allows.
inline float fast_tanh(float x) {
  x = std::min(std::max(x, -7.90531110763549805f), 7.90531110763549805f);
  const float x2 = x * x;
  float p = -2.76076847742355e-16f;
  p = p * x2 + 2.00018790482477e-13f;
  p = p * x2 + -8.60467152213735e-11f;
  p = p * x2 + 5.12229709037114e-08f;
  p = p * x2 + 1.48572235717979e-05f;
  p = p * x2 + 6.37261928875436e-04f;
  p = p * x2 + 4.89352455891786e-03f;
  p = p * x;
  float q = 1.19825839466702e-06f;
  q = q * x2 + 1.18534705686654e-04f;
  q = q * x2 + 2.26843463243900e-03f;
  q = q * x2 + 4.89352518554385e-03f;
  return p / q;
}

inline float gelu(float x) {
  // tanh approximation — matches jax.nn.gelu(approximate=True), the flax
  // default used by TopoScorer.head
  const float kC = 0.7978845608028654f;  // sqrt(2/pi)
  return 0.5f * x * (1.0f + fast_tanh(kC * (x + 0.044715f * x * x * x)));
}

inline float sigmoidf(float x) { return 1.0f / (1.0f + std::exp(-x)); }

// 16-lane float vector via GNU vector extensions (gcc/clang): one AVX-512
// zmm or a ymm pair. Local float[16] accumulator arrays looked equivalent but
// gcc 12 spilled them to the stack inside the FMA loop; typed vector
// variables stay in registers.
typedef float v16 __attribute__((vector_size(64)));
typedef float v16u __attribute__((vector_size(64), aligned(4), may_alias));

inline v16 loadu16(const float* p) { return *reinterpret_cast<const v16u*>(p); }
inline void storeu16(float* p, v16 v) { *reinterpret_cast<v16u*>(p) = v; }

// Y[R, out] += X[R, in] · W[in, out]   (W row-major [in][out], flax layout;
// Y PRE-INITIALIZED by the caller with bias / per-node partials).
//
// Register-blocked micro-kernel: 8 rows × 16 cols of Y live in 8 vector
// registers across the whole contraction — Y is read and written exactly
// once, and each streamed W vector feeds 8 FMAs.
//
// Loop order is column-panel OUTER, row-block inner: the W panel
// (in × 16 floats, ~9 KB at serving shapes) stays L1-resident across every
// row block, so W streams from cache once per call. The row-outer order
// re-streamed the whole W (147 KB at H1=256) per 8-row block — R/8 × 147 KB
// of LLC/DRAM traffic per call, which is what capped two dispatcher
// workers' concurrent GEMMs at ~1.3x on a host whose ALU-bound work scales
// 1.93x (X re-reads cost in/out = ~0.5 the W traffic saved, and X rows are
// hot in L2 anyway).
void gemm_acc(const float* __restrict__ X, const float* __restrict__ W,
              float* __restrict__ Y, int R, int in, int out) {
  constexpr int RB = 8, CB = 16;
  int o = 0;
  for (; o + CB <= out; o += CB) {
    const float* Wp = W + o;
    int r = 0;
    for (; r + RB <= R; r += RB) {
      const float* x[RB];
      float* y[RB];
      for (int k = 0; k < RB; ++k) {
        x[k] = X + static_cast<size_t>(r + k) * in;
        y[k] = Y + static_cast<size_t>(r + k) * out;
      }
      v16 a0 = loadu16(y[0] + o), a1 = loadu16(y[1] + o);
      v16 a2 = loadu16(y[2] + o), a3 = loadu16(y[3] + o);
      v16 a4 = loadu16(y[4] + o), a5 = loadu16(y[5] + o);
      v16 a6 = loadu16(y[6] + o), a7 = loadu16(y[7] + o);
      const float* w = Wp;
      for (int i = 0; i < in; ++i, w += out) {
        const v16 wv = loadu16(w);
        a0 += x[0][i] * wv;
        a1 += x[1][i] * wv;
        a2 += x[2][i] * wv;
        a3 += x[3][i] * wv;
        a4 += x[4][i] * wv;
        a5 += x[5][i] * wv;
        a6 += x[6][i] * wv;
        a7 += x[7][i] * wv;
      }
      storeu16(y[0] + o, a0);
      storeu16(y[1] + o, a1);
      storeu16(y[2] + o, a2);
      storeu16(y[3] + o, a3);
      storeu16(y[4] + o, a4);
      storeu16(y[5] + o, a5);
      storeu16(y[6] + o, a6);
      storeu16(y[7] + o, a7);
    }
    for (; r < R; ++r) {
      const float* xr = X + static_cast<size_t>(r) * in;
      float* yr = Y + static_cast<size_t>(r) * out;
      v16 a = loadu16(yr + o);
      const float* w = Wp;
      for (int i = 0; i < in; ++i, w += out) a += xr[i] * loadu16(w);
      storeu16(yr + o, a);
    }
  }
  for (; o < out; ++o) {
    const float* w0 = W + o;
    for (int r = 0; r < R; ++r) {
      const float* xr = X + static_cast<size_t>(r) * in;
      float a = Y[static_cast<size_t>(r) * out + o];
      const float* w = w0;
      for (int i = 0; i < in; ++i, w += out) a += xr[i] * *w;
      Y[static_cast<size_t>(r) * out + o] = a;
    }
  }
}

}  // namespace

extern "C" {

// Cap THIS THREAD's intra-call OpenMP parallelism (nthreads ICV is
// per-thread). The scheduler's round dispatcher pins its worker threads to
// 1: it shards rounds ACROSS workers, and letting every worker's GEMM also
// fan out OMP threads oversubscribes the host — libgomp's spin-waiting
// helpers burn the very cores the other workers' Python needs (measured
// NEGATIVE scaling, 0.74x at 2 workers on a 2-core host). Single-threaded
// callers (the micro-batch serving path, the bench headline) never call
// this and keep whole-host intra-call parallelism. No-op without OpenMP.
void df_scorer_set_thread_parallelism(int n) {
#ifdef _OPENMP
  if (n > 0) omp_set_num_threads(n);
#endif
  (void)n;
}

// Immutable model data, SHARED across handles (refcounted): the weights,
// embeddings, and uc/up precompute total ~1-2 MB at serving shapes, and the
// GEMM streams them every call — per-handle copies would double the cache
// working set per added worker thread and thrash the shared LLC (measured:
// duplicating the model capped 2-worker scaling at ~1.2x on a host whose
// compute scales 1.93x; sharing restores the headroom). Handles only own
// scratch + a mutex.
struct DfModel {
  Header hdr;
  std::vector<float> z, w1, b1, w2, b2, w3, b3;
  // load-time precompute: first-layer contributions of each node's embedding
  // in child position (uc) and parent position (up), [N, H1] each
  std::vector<float> uc, up;
  std::atomic<int32_t> refs{1};
};

struct DfScorer {
  DfModel* model;
  // per-handle scratch reused across calls (no per-call malloc on the hot
  // path); sliced disjointly by OpenMP row blocks inside one call, guarded
  // across calls by `mu` — which is why concurrent threads need one handle
  // each (df_scorer_fork)
  std::vector<float> sx, sy1, sy2;
  std::mutex mu;
};

DfScorer* df_scorer_load(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  DfModel* m = new DfModel();
  bool ok = std::fread(&m->hdr, sizeof(Header), 1, f) == 1 &&
            m->hdr.magic == kMagic && m->hdr.version == kVersion;
  if (ok) {
    const Header& h = m->hdr;
    const uint32_t in = 3 * h.d + h.fp;
    auto rd = [&](std::vector<float>& v, size_t count) {
      v.resize(count);
      return std::fread(v.data(), sizeof(float), count, f) == count;
    };
    ok = rd(m->z, (size_t)h.n * h.d) && rd(m->w1, (size_t)in * h.h1) &&
         rd(m->b1, h.h1) && rd(m->w2, (size_t)h.h1 * h.h2) && rd(m->b2, h.h2) &&
         rd(m->w3, h.h2) && rd(m->b3, 1);
  }
  std::fclose(f);
  if (!ok) {
    delete m;
    return nullptr;
  }
  // Precompute uc = z · W1[0:D], up = z · W1[D:2D]  (one-time ~2·N·D·H1 MACs)
  const Header& h = m->hdr;
  m->uc.assign((size_t)h.n * h.h1, 0.0f);
  m->up.assign((size_t)h.n * h.h1, 0.0f);
  gemm_acc(m->z.data(), m->w1.data(), m->uc.data(), (int)h.n, (int)h.d,
           (int)h.h1);
  gemm_acc(m->z.data(), m->w1.data() + (size_t)h.d * h.h1, m->up.data(),
           (int)h.n, (int)h.d, (int)h.h1);
  DfScorer* s = new DfScorer();
  s->model = m;
  return s;
}

// A new handle onto the SAME model (refs++): fresh scratch + mutex, zero
// copies — the per-worker-thread handle the scheduler's round dispatcher
// uses (scorer.cc rule: one handle per thread).
DfScorer* df_scorer_fork(DfScorer* s) {
  if (!s) return nullptr;
  s->model->refs.fetch_add(1, std::memory_order_relaxed);
  DfScorer* t = new DfScorer();
  t->model = s->model;
  return t;
}

void df_scorer_free(DfScorer* s) {
  if (!s) return;
  if (s->model->refs.fetch_sub(1, std::memory_order_acq_rel) == 1) {
    delete s->model;
  }
  delete s;
}

int32_t df_scorer_num_nodes(const DfScorer* s) { return (int32_t)s->model->hdr.n; }
int32_t df_scorer_embed_dim(const DfScorer* s) { return (int32_t)s->model->hdr.d; }
int32_t df_scorer_feature_dim(const DfScorer* s) { return (int32_t)s->model->hdr.fp; }

// The full three-stage per-row pipeline over R pre-validated rows: child/
// parent are PER-ROW node indices, feats row for row b is feats[row_map[b]]
// (row_map == nullptr ⇒ identity). Both public entries funnel here, so the
// multi-round batch path and the round driver are bit-identical by
// construction — per-row math never depends on the batch shape.
static void score_rows(DfScorer* s, const int32_t* child, const int32_t* parent,
                       const float* feats, const int32_t* row_map, int32_t R,
                       float* out) {
  const DfModel* m = s->model;
  const Header& h = m->hdr;
  const int D = (int)h.d, FP = (int)h.fp, H1 = (int)h.h1, H2 = (int)h.h2;
  const int in1 = D + FP;  // contraction after the uc/up precompute
  // Row-TILE the whole three-stage pipeline (128 rows ≈ 72 KB X + 128 KB Y1
  // scratch): running each stage over the full R first meant ~550 KB of
  // scratch churn per call — two dispatcher workers' concurrent calls then
  // fought over the shared cache (measured 1.33x scaling where ALU-bound
  // work scales 1.93x on this host). Tiled, each worker's hot set stays
  // private-cache-sized; the extra W1t re-streams per tile are L2 reads.
  constexpr int32_t kRowTile = 128;
  std::lock_guard<std::mutex> lock(s->mu);
  const int32_t tile = std::min<int32_t>(R, kRowTile);
  s->sx.resize((size_t)tile * in1);
  s->sy1.resize((size_t)tile * H1);
  s->sy2.resize((size_t)tile * H2);
  // W1 tail = rows [2D, 3D+FP) — the z_c∘z_p and pair-feature blocks, which
  // are contiguous in the artifact's row-major kernel
  const float* W1t = m->w1.data() + (size_t)2 * D * h.h1;

  int nblk = 1;
#ifdef _OPENMP
  nblk = std::min<int>(omp_get_max_threads(), std::max<int32_t>(1, R / kRowTile));
  if (nblk > 1) {
    // per-OMP-thread scratch tiles, disjoint slices of the handle's buffers
    s->sx.resize((size_t)nblk * tile * in1);
    s->sy1.resize((size_t)nblk * tile * H1);
    s->sy2.resize((size_t)nblk * tile * H2);
  }
#endif
  const int32_t chunk = (R + nblk - 1) / nblk;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) num_threads(nblk) if (nblk > 1)
#endif
  for (int blk = 0; blk < nblk; ++blk) {
    const int32_t c0 = blk * chunk;
    const int32_t cn = std::min<int32_t>(R - c0, chunk);
    if (cn <= 0) continue;
    float* X = s->sx.data() + (size_t)blk * tile * in1;
    float* Y1 = s->sy1.data() + (size_t)blk * tile * H1;
    float* Y2 = s->sy2.data() + (size_t)blk * tile * H2;
    for (int32_t t0 = c0; t0 < c0 + cn; t0 += tile) {
      const int32_t b0 = t0;
      const int32_t bn = std::min<int32_t>(c0 + cn - t0, tile);
      // stage 1: build the reduced input rows + preload Y1 with
      // b1 + uc[child] + up[parent] — scratch rows are tile-local
      for (int32_t b = b0; b < b0 + bn; ++b) {
        float* xb = X + (size_t)(b - b0) * in1;
        const float* zc = m->z.data() + (size_t)child[b] * D;
        const float* zp = m->z.data() + (size_t)parent[b] * D;
        for (int i = 0; i < D; ++i) xb[i] = zc[i] * zp[i];
        const size_t frow = row_map ? (size_t)row_map[b] : (size_t)b;
        std::memcpy(xb + D, feats + frow * FP, FP * sizeof(float));
        float* yb = Y1 + (size_t)(b - b0) * H1;
        const float* ucr = m->uc.data() + (size_t)child[b] * H1;
        const float* upr = m->up.data() + (size_t)parent[b] * H1;
        for (int i = 0; i < H1; ++i) yb[i] = m->b1[i] + ucr[i] + upr[i];
      }
      gemm_acc(X, W1t, Y1, bn, in1, H1);
      for (size_t i = 0; i < (size_t)bn * H1; ++i) Y1[i] = gelu(Y1[i]);
      for (int32_t b = 0; b < bn; ++b)
        std::memcpy(Y2 + (size_t)b * H2, m->b2.data(), H2 * sizeof(float));
      gemm_acc(Y1, m->w2.data(), Y2, bn, H1, H2);
      for (size_t i = 0; i < (size_t)bn * H2; ++i) Y2[i] = gelu(Y2[i]);
      for (int32_t b = 0; b < bn; ++b) {
        const float* yb = Y2 + (size_t)b * H2;
        float o = m->b3[0];
        for (int i = 0; i < H2; ++i) o += yb[i] * m->w3[i];
        out[b0 + b] = sigmoidf(o);
      }
    }
  }
}

// Score `rounds` independent scheduling rounds of `batch` (child, parent)
// pairs each in ONE call: child/parent are [rounds*batch] i32, feats is
// [rounds*batch, FP] row-major, out is [rounds*batch] f32. The multi-round
// entry amortizes FFI + dispatch overhead across rounds (north-star config 5's
// 10k-calls/s path). Returns 0 on success, -1 on an out-of-range node index.
int32_t df_scorer_score_rounds(DfScorer* s, const int32_t* child,
                               const int32_t* parent, const float* feats,
                               int32_t rounds, int32_t batch, float* out) {
  const Header& h = s->model->hdr;
  const int64_t total64 = (int64_t)rounds * batch;
  if (total64 <= 0 || total64 > (int64_t)1 << 24) return total64 == 0 ? 0 : -2;
  const int32_t R = (int32_t)total64;
  for (int32_t b = 0; b < R; ++b) {
    const int32_t c = child[b], p = parent[b];
    if (c < 0 || p < 0 || (uint32_t)c >= h.n || (uint32_t)p >= h.n) return -1;
  }
  score_rows(s, child, parent, feats, nullptr, R, out);
  return 0;
}

// Single-round entry (kept for API compatibility; one round of `batch` pairs).
int32_t df_scorer_score(DfScorer* s, const int32_t* child,
                        const int32_t* parent, const float* feats,
                        int32_t batch, float* out) {
  return df_scorer_score_rounds(s, child, parent, feats, 1, batch, out);
}

// ── The native round driver ────────────────────────────────────────────────
//
// One FFI call drives a BATCH of whole scheduling rounds: re-validate →
// fill round-constant feature columns → score → stable top-k, all with the
// GIL released. Arena contract (all buffers owned and reused by the caller):
//
//   offsets    [M+1] i32 — survivor rows of round r are [offsets[r], offsets[r+1])
//   child_idx  [M]   i32 — embedding-table index of the round's child (-1 unknown)
//   parent_idx [T]   i32 — per survivor row (-1 unknown), T = offsets[M]
//   feats      [T,FP]f32 — validated pair rows; round-constant columns
//                          (10 = finished-piece ratio, 11 = log-scaled
//                          content length, 13 = scaled schedule rounds) are
//                          broadcast HERE from round_cols
//   round_cols [M,3] f32 — the three round-constant values, computed in
//                          Python with the same float32 ops as
//                          _fill_round_columns
//   filt       [T,4] i32 — (flags, state_code, free_upload_slots, depth)
//                          snapshotted under the scheduler state lock
//
// Outputs: out_scores [T] f32 (NaN for rows the driver did not score),
// sel [M,k] i32 local survivor indices (-1 pad), n_sel [M] i32, and
// status [M] i32: 0 = natively scored, 1 = round must re-run on the Python
// serial leg (unknown node index, stale embedding table, or a filter field
// that disagrees with the snapshot predicate) — the caller routes those
// through the bit-identical evaluate_many path, preserving serial semantics
// for every fallback taxonomy case.
//
// Returns 0 on success; -2 row-cap overflow, -3 feature schema too narrow
// for the round-constant columns, -4 malformed offsets.
int32_t df_round_drive(DfScorer* s, const int32_t* offsets,
                       const int32_t* child_idx, const int32_t* parent_idx,
                       float* feats, const float* round_cols,
                       const int32_t* filt, int32_t rounds, int32_t k,
                       int32_t max_depth, float* out_scores, int32_t* sel,
                       int32_t* n_sel, int32_t* status) {
  const Header& h = s->model->hdr;
  if (rounds <= 0) return 0;
  const int FP = (int)h.fp;
  if (FP <= 13) return -3;
  const int64_t total64 = (int64_t)offsets[rounds];
  if (total64 < 0 || total64 > (int64_t)1 << 24) return -2;
  const int32_t T = (int32_t)total64;

  // Pass 1 (per round): native-or-fallback decision, round-column broadcast,
  // and compaction of the scorable rows (fallback rounds' rows are skipped).
  std::vector<int32_t> crow, prow, rmap;
  crow.reserve(T);
  prow.reserve(T);
  rmap.reserve(T);
  for (int32_t r = 0; r < rounds; ++r) {
    const int32_t t0 = offsets[r], t1 = offsets[r + 1];
    n_sel[r] = 0;
    for (int32_t j = 0; j < k; ++j) sel[(size_t)r * k + j] = -1;
    if (t1 < t0 || t0 < 0) return -4;
    if (t1 == t0) {  // no survivors: an empty round, natively resolved
      status[r] = 0;
      continue;
    }
    const int32_t c = child_idx[r];
    bool native = c >= 0 && (uint32_t)c < h.n;
    for (int32_t t = t0; native && t < t1; ++t) {
      const int32_t p = parent_idx[t];
      const int32_t* f = filt + (size_t)t * 4;
      if (p < 0 || (uint32_t)p >= h.n ||
          f[0] != 0 || f[1] < 0 || f[2] <= 0 || f[3] >= max_depth) {
        native = false;
      }
    }
    if (!native) {
      status[r] = 1;
      continue;
    }
    status[r] = 0;
    const float* rc = round_cols + (size_t)r * 3;
    for (int32_t t = t0; t < t1; ++t) {
      float* fr = feats + (size_t)t * FP;
      fr[10] = rc[0];
      fr[11] = rc[1];
      fr[13] = rc[2];
      crow.push_back(c);
      prow.push_back(parent_idx[t]);
      rmap.push_back(t);
    }
  }

  // Pass 2: one shared-pipeline scoring sweep over the compacted rows.
  const int32_t RC = (int32_t)rmap.size();
  std::vector<float> cs((size_t)RC);
  if (RC > 0) score_rows(s, crow.data(), prow.data(), feats, rmap.data(), RC, cs.data());
  for (int32_t t = 0; t < T; ++t) out_scores[t] = std::nanf("");
  for (int32_t i = 0; i < RC; ++i) out_scores[rmap[i]] = cs[i];

  // Pass 3: stable top-k per native round. Matches
  // np.argsort(-scores, kind="stable")[:k] exactly: descending score, ties
  // broken by survivor index, NaN ranked last (numpy sorts NaN to the end).
  std::vector<int32_t> order;
  for (int32_t r = 0; r < rounds; ++r) {
    if (status[r] != 0 || k <= 0) continue;
    const int32_t t0 = offsets[r];
    const int32_t nr = offsets[r + 1] - t0;
    if (nr <= 0) continue;
    order.resize(nr);
    for (int32_t j = 0; j < nr; ++j) order[j] = j;
    const float* sc = out_scores + t0;
    std::stable_sort(order.begin(), order.end(), [sc](int32_t a, int32_t b) {
      const float xa = sc[a], xb = sc[b];
      const bool na = std::isnan(xa), nb = std::isnan(xb);
      if (na || nb) return nb && !na;  // non-NaN sorts before NaN
      return xa > xb;
    });
    const int32_t kk = std::min<int32_t>(k, nr);
    for (int32_t j = 0; j < kk; ++j) sel[(size_t)r * k + j] = order[j];
    n_sel[r] = kk;
  }
  return 0;
}

// ── Native mirrored peer table (ISSUE 19) ──────────────────────────────────
//
// A C-side mirror of the scheduler's per-task candidate state, so
// df_mirror_drive can sample, filter, and score rounds without Python ever
// walking the peer pool. Python pushes incremental deltas at exactly the
// mutation sites that already bump a version counter (peer/host feat bumps,
// FSM transitions, DAG edge commits, topology/bandwidth bumps, peer
// lifecycle); the drive consumes the mirror under one mutex acquisition per
// batch and the deltas are tiny synchronous calls, so mutators overlap
// driving except for the sample/filter/gather window itself.
//
// Entities are SLOT-indexed (Python's MirrorClient owns slot allocation and
// keeps the slot→object maps); versions are the same counters Python's
// feature caches key on, so a mirrored pair row is fresh exactly when
// Python's own `_pair_rows` hit would be. A stale or missing row does NOT
// force a full re-export: the round reports status 2 (stale) with its
// survivors, Python scores it on the bit-identical serial leg and pushes the
// freshly cached rows back — steady state is pure native rounds with zero
// full re-exports (counter-asserted by tools/check.sh's mirror-smoke).
//
// RNG: the candidate draw is a bit-exact reproduction of CPython's
// random.sample over the mirrored (insertion-ordered) peer list — MT19937
// genrand_uint32 + getrandbits(k)/_randbelow rejection + the dual
// pool-shuffle/selection-set strategy with the same setsize switch — with
// the Mersenne state marshalled in/out per drive, so Python's
// Scheduling._rng remains THE owner and serial/native draws interleave on
// one stream (decision records and `dfml explain` replay stay bit-identical
// to the serial evaluator).

struct MirrorRow {
  int64_t key[5];  // (peer_feat, host_feat, child_host_feat, topo_pair, bw_parent)
  std::vector<float> row;  // [fp], round-constant columns left zero
};

struct MirrorPeer {
  int32_t alive = 0;
  int32_t task_slot = -1;
  int32_t host_slot = -1;
  int32_t state_code = -1;
  int32_t bad = 0;
  int64_t feat_version = -1;
  std::vector<int32_t> parents;   // DAG parent slots, Python set-iteration order
  std::vector<int32_t> children;  // DAG child slots (membership only)
  std::unordered_map<int32_t, MirrorRow> rows;  // child_host_slot → cached pair row
};

struct MirrorHost {
  int32_t alive = 0;
  int32_t free_slots = 0;
  int32_t node_idx = -1;  // embedding-table row for the CURRENT bundle
  int64_t feat_version = -1;
  // bandwidth parent version; INT64_MIN = never pushed → adopted from the
  // first row push (lazily consistent: any later bump overwrites it)
  int64_t bw_version = INT64_MIN;
};

struct MirrorTask {
  int32_t alive = 0;
  std::vector<int32_t> vlist;  // peer slots, DAG insertion order (= dag._vlist)
};

// Mirrors resource._PAIR_ROW_CACHE_MAX: past this many distinct child hosts
// a peer's row map is cleared whole, exactly like Python's `_pair_rows`.
constexpr size_t kMirrorRowCacheMax = 4096;

struct DfMirror {
  int32_t fp;
  std::mutex mu;
  std::vector<MirrorPeer> peers;
  std::vector<MirrorHost> hosts;
  std::vector<MirrorTask> tasks;
  // topology pair version keyed by canonical (min,max) host-slot pair;
  // absent = never pushed → adopted from the first row push (see bw_version)
  std::unordered_map<uint64_t, int64_t> topo;
  // epoch-stamped scratch (no per-round set allocations): excl = blocked ∪
  // lineage ∪ child for the active round, tmp = sample rejection set, then
  // per-candidate depth-walk seen sets (epoch bumped per use)
  std::vector<uint32_t> excl_mark, tmp_mark;
  uint32_t excl_epoch = 0, tmp_epoch = 0;
  std::vector<int32_t> pool_scratch;  // random.sample's pool-copy strategy
  // counters (df_mirror_stats layout)
  int64_t deltas = 0, rows_pushed = 0, native_rounds = 0, stale_rounds = 0,
          fallback_rounds = 0, empty_rounds = 0, full_syncs = 0, drives = 0,
          rows_cached = 0;
};

static inline uint64_t topo_key(int32_t a, int32_t b) {
  const uint32_t lo = (uint32_t)std::min(a, b), hi = (uint32_t)std::max(a, b);
  return ((uint64_t)lo << 32) | hi;
}

static inline MirrorPeer& peer_slot_at(std::vector<MirrorPeer>& v, int32_t slot) {
  if ((size_t)slot >= v.size()) v.resize((size_t)slot + 1);
  return v[(size_t)slot];
}
static inline MirrorHost& host_slot_at(std::vector<MirrorHost>& v, int32_t slot) {
  if ((size_t)slot >= v.size()) v.resize((size_t)slot + 1);
  return v[(size_t)slot];
}
static inline MirrorTask& task_slot_at(std::vector<MirrorTask>& v, int32_t slot) {
  if ((size_t)slot >= v.size()) v.resize((size_t)slot + 1);
  return v[(size_t)slot];
}

static inline bool valid_slot(size_t n, int32_t slot) {
  return slot >= 0 && (size_t)slot < n;
}

static void mirror_marks_ensure(DfMirror* m) {
  const size_t n = m->peers.size();
  if (m->excl_mark.size() < n) {
    m->excl_mark.resize(n, 0);
    m->tmp_mark.resize(n, 0);
  }
}

// ---- CPython MT19937 (_randommodule.c genrand_uint32), state-injected ----

struct MtState {
  uint32_t mt[624];
  int32_t mti;
};

static inline uint32_t mt_genrand(MtState* s) {
  if (s->mti >= 624) {
    uint32_t* mt = s->mt;
    for (int kk = 0; kk < 624; ++kk) {
      const uint32_t y = (mt[kk] & 0x80000000u) | (mt[(kk + 1) % 624] & 0x7fffffffu);
      mt[kk] = mt[(kk + 397) % 624] ^ (y >> 1) ^ ((y & 1u) ? 0x9908b0dfu : 0u);
    }
    s->mti = 0;
  }
  uint32_t y = s->mt[s->mti++];
  y ^= y >> 11;
  y ^= (y << 7) & 0x9d2c5680u;
  y ^= (y << 15) & 0xefc60000u;
  y ^= y >> 18;
  return y;
}

// random.getrandbits(k) for 0 < k <= 32: one word, top k bits
static inline uint32_t mt_getrandbits(MtState* s, int k) {
  return mt_genrand(s) >> (32 - k);
}

// random._randbelow_with_getrandbits(n), n > 0
static inline uint32_t mt_randbelow(MtState* s, uint32_t n) {
  int k = 32 - __builtin_clz(n);  // n.bit_length()
  uint32_t r = mt_getrandbits(s, k);
  while (r >= n) r = mt_getrandbits(s, k);
  return r;
}

// random.Random.sample(population, k) over `pop[0:n]`, k < n (the k >= n
// case never reaches here: DAG.random_vertices returns the whole list
// WITHOUT consuming the rng). Result preserves CPython's draw order — it
// determines stable-argsort tie-breaks downstream.
static void mt_sample(MtState* s, DfMirror* m, const int32_t* pop, int32_t n,
                      int32_t k, std::vector<int32_t>& out) {
  out.clear();
  int setsize = 21;
  if (k > 5)
    setsize += (int)std::pow(4.0, std::ceil(std::log((double)k * 3) / std::log(4.0)));
  if (n <= setsize) {
    // pool-copy partial shuffle
    std::vector<int32_t>& pool = m->pool_scratch;
    pool.assign(pop, pop + n);
    for (int32_t i = 0; i < k; ++i) {
      const uint32_t j = mt_randbelow(s, (uint32_t)(n - i));
      out.push_back(pool[j]);
      pool[j] = pool[n - i - 1];
    }
  } else {
    // selection-set rejection (epoch-stamped marks instead of a Python set;
    // stamps are keyed by POSITION in the population, not peer slot, so the
    // scratch only needs n entries)
    std::vector<uint32_t>& mark = m->tmp_mark;
    if (mark.size() < (size_t)n) mark.resize((size_t)n, 0);
    const uint32_t epoch = ++m->tmp_epoch;
    for (int32_t i = 0; i < k; ++i) {
      uint32_t j = mt_randbelow(s, (uint32_t)n);
      while (mark[j] == epoch) j = mt_randbelow(s, (uint32_t)n);
      mark[j] = epoch;
      out.push_back(pop[j]);
    }
  }
}

// resource.Peer.depth() without the TTL memo: first-parent chain walk with a
// seen set, capped at 10 hops. The mirror computes depth FRESH each drive;
// the serial leg's ≤1 s-stale memo is the one documented tolerance
// (equivalence tests pin the memo TTL to 0).
static int32_t mirror_depth(DfMirror* m, int32_t slot) {
  std::vector<uint32_t>& mark = m->tmp_mark;
  const uint32_t epoch = ++m->tmp_epoch;
  int32_t depth = 1, cur = slot;
  mark[cur] = epoch;
  for (;;) {
    const std::vector<int32_t>& ps = m->peers[(size_t)cur].parents;
    if (ps.empty()) break;
    const int32_t nxt = ps[0];
    if (mark[nxt] == epoch || depth > 10) break;
    depth += 1;
    cur = nxt;
    mark[cur] = epoch;
  }
  return depth;
}

// dag.lineage(child): ancestors ∪ descendants, stamped into excl_mark under
// the CURRENT excl epoch (on top of the round's blocked slots + child).
static void mirror_stamp_lineage(DfMirror* m, int32_t child_slot) {
  std::vector<uint32_t>& mark = m->excl_mark;
  const uint32_t epoch = m->excl_epoch;
  std::vector<int32_t> stack;
  stack.push_back(child_slot);
  while (!stack.empty()) {  // ancestors
    const int32_t cur = stack.back();
    stack.pop_back();
    for (int32_t p : m->peers[(size_t)cur].parents) {
      if (mark[p] != epoch) {
        mark[p] = epoch;
        stack.push_back(p);
      }
    }
  }
  stack.push_back(child_slot);
  while (!stack.empty()) {  // descendants
    const int32_t cur = stack.back();
    stack.pop_back();
    for (int32_t c : m->peers[(size_t)cur].children) {
      if (mark[c] != epoch) {
        mark[c] = epoch;
        stack.push_back(c);
      }
    }
  }
}

static void vec_remove(std::vector<int32_t>& v, int32_t x) {
  for (size_t i = 0; i < v.size(); ++i) {
    if (v[i] == x) {
      v.erase(v.begin() + i);
      return;
    }
  }
}

DfMirror* df_mirror_new(int32_t fp) {
  if (fp <= 13) return nullptr;  // round-constant columns must exist
  DfMirror* m = new DfMirror();
  m->fp = fp;
  return m;
}

void df_mirror_free(DfMirror* m) { delete m; }

int32_t df_mirror_host_upsert(DfMirror* m, int32_t slot, int64_t feat_version,
                              int32_t free_slots, int32_t node_idx) {
  if (slot < 0) return -1;
  std::lock_guard<std::mutex> lock(m->mu);
  MirrorHost& h = host_slot_at(m->hosts, slot);
  h.alive = 1;
  h.feat_version = feat_version;
  h.free_slots = free_slots;
  h.node_idx = node_idx;
  m->deltas++;
  return 0;
}

int32_t df_mirror_host_remove(DfMirror* m, int32_t slot) {
  std::lock_guard<std::mutex> lock(m->mu);
  if (!valid_slot(m->hosts.size(), slot)) return -1;
  m->hosts[(size_t)slot] = MirrorHost{};
  m->deltas++;
  return 0;
}

int32_t df_mirror_task_upsert(DfMirror* m, int32_t slot) {
  if (slot < 0) return -1;
  std::lock_guard<std::mutex> lock(m->mu);
  task_slot_at(m->tasks, slot).alive = 1;
  m->deltas++;
  return 0;
}

int32_t df_mirror_task_remove(DfMirror* m, int32_t slot) {
  std::lock_guard<std::mutex> lock(m->mu);
  if (!valid_slot(m->tasks.size(), slot)) return -1;
  m->tasks[(size_t)slot] = MirrorTask{};
  m->deltas++;
  return 0;
}

int32_t df_mirror_peer_add(DfMirror* m, int32_t slot, int32_t task_slot,
                           int32_t host_slot, int32_t state_code, int32_t bad,
                           int64_t feat_version) {
  if (slot < 0 || task_slot < 0 || host_slot < 0) return -1;
  std::lock_guard<std::mutex> lock(m->mu);
  if (!valid_slot(m->tasks.size(), task_slot) || !m->tasks[(size_t)task_slot].alive)
    return -2;
  MirrorPeer& p = peer_slot_at(m->peers, slot);
  if (p.alive) return -3;  // client never reuses a live slot
  p.alive = 1;
  p.task_slot = task_slot;
  p.host_slot = host_slot;
  p.state_code = state_code;
  p.bad = bad;
  p.feat_version = feat_version;
  p.parents.clear();
  p.children.clear();
  m->tasks[(size_t)task_slot].vlist.push_back(slot);
  mirror_marks_ensure(m);
  m->deltas++;
  return 0;
}

int32_t df_mirror_peer_remove(DfMirror* m, int32_t slot) {
  std::lock_guard<std::mutex> lock(m->mu);
  if (!valid_slot(m->peers.size(), slot) || !m->peers[(size_t)slot].alive) return -1;
  MirrorPeer& p = m->peers[(size_t)slot];
  // detach from adjacency: children lose a parent IN PLACE (matches Python's
  // set.discard preserving remaining relative order), parents lose a child
  for (int32_t c : p.children) vec_remove(m->peers[(size_t)c].parents, slot);
  for (int32_t pa : p.parents) vec_remove(m->peers[(size_t)pa].children, slot);
  if (valid_slot(m->tasks.size(), p.task_slot))
    vec_remove(m->tasks[(size_t)p.task_slot].vlist, slot);
  m->rows_cached -= (int64_t)p.rows.size();
  p = MirrorPeer{};
  m->deltas++;
  return 0;
}

int32_t df_mirror_peer_feat(DfMirror* m, int32_t slot, int64_t feat_version,
                            int32_t bad) {
  std::lock_guard<std::mutex> lock(m->mu);
  if (!valid_slot(m->peers.size(), slot) || !m->peers[(size_t)slot].alive) return -1;
  m->peers[(size_t)slot].feat_version = feat_version;
  m->peers[(size_t)slot].bad = bad;
  m->deltas++;
  return 0;
}

int32_t df_mirror_peer_state(DfMirror* m, int32_t slot, int32_t state_code) {
  std::lock_guard<std::mutex> lock(m->mu);
  if (!valid_slot(m->peers.size(), slot) || !m->peers[(size_t)slot].alive) return -1;
  m->peers[(size_t)slot].state_code = state_code;
  m->deltas++;
  return 0;
}

// Replace `child`'s FULL parent list (Python pushes list(vertex.parents) in
// current set-iteration order after every edge mutation — the order the
// depth walk's parents[0] depends on cannot be derived from deltas alone).
int32_t df_mirror_set_parents(DfMirror* m, int32_t child_slot,
                              const int32_t* parents, int32_t n) {
  std::lock_guard<std::mutex> lock(m->mu);
  if (!valid_slot(m->peers.size(), child_slot) || !m->peers[(size_t)child_slot].alive)
    return -1;
  MirrorPeer& c = m->peers[(size_t)child_slot];
  for (int32_t old : c.parents) vec_remove(m->peers[(size_t)old].children, child_slot);
  c.parents.clear();
  for (int32_t i = 0; i < n; ++i) {
    const int32_t pa = parents[i];
    if (!valid_slot(m->peers.size(), pa) || !m->peers[(size_t)pa].alive) continue;
    c.parents.push_back(pa);
    m->peers[(size_t)pa].children.push_back(child_slot);
  }
  m->deltas++;
  return 0;
}

int32_t df_mirror_topo_bump(DfMirror* m, int32_t a_slot, int32_t b_slot,
                            int64_t version) {
  if (a_slot < 0 || b_slot < 0) return -1;
  std::lock_guard<std::mutex> lock(m->mu);
  m->topo[topo_key(a_slot, b_slot)] = version;
  m->deltas++;
  return 0;
}

int32_t df_mirror_bw_bump(DfMirror* m, int32_t host_slot, int64_t version) {
  std::lock_guard<std::mutex> lock(m->mu);
  if (!valid_slot(m->hosts.size(), host_slot)) return -1;
  m->hosts[(size_t)host_slot].bw_version = version;
  m->deltas++;
  return 0;
}

// Bulk node-index refresh for a model hot-swap: the client re-pushes every
// mirrored host's embedding row for the NEW bundle before the next drive, so
// a drive can never mix node indices across bundles (zero torn rounds).
int32_t df_mirror_set_node_indices(DfMirror* m, const int32_t* slots,
                                   const int32_t* idx, int32_t n) {
  std::lock_guard<std::mutex> lock(m->mu);
  for (int32_t i = 0; i < n; ++i) {
    if (!valid_slot(m->hosts.size(), slots[i])) return -1;
    m->hosts[(size_t)slots[i]].node_idx = idx[i];
  }
  m->deltas++;
  return 0;
}

// Push freshly revalidated pair rows after a stale round's serial re-score:
// keys are the SAME 5-tuple Python's `_export_pair_rows` caches under, rows
// have the round-constant columns zero. Rows enter the mirror ONLY through
// this leg — the mirror never recomputes features itself.
int32_t df_mirror_push_rows(DfMirror* m, int32_t child_host_slot, int32_t n,
                            const int32_t* peer_slots, const int64_t* keys,
                            const float* rows) {
  std::lock_guard<std::mutex> lock(m->mu);
  if (!valid_slot(m->hosts.size(), child_host_slot)) return -1;
  for (int32_t i = 0; i < n; ++i) {
    const int32_t ps = peer_slots[i];
    if (!valid_slot(m->peers.size(), ps) || !m->peers[(size_t)ps].alive) continue;
    MirrorPeer& p = m->peers[(size_t)ps];
    if (p.rows.size() >= kMirrorRowCacheMax && !p.rows.count(child_host_slot)) {
      m->rows_cached -= (int64_t)p.rows.size();
      p.rows.clear();  // clear-whole, mirroring _PAIR_ROW_CACHE_MAX
    }
    auto ins = p.rows.try_emplace(child_host_slot);
    MirrorRow& row = ins.first->second;
    if (ins.second) m->rows_cached++;
    std::memcpy(row.key, keys + (size_t)i * 5, 5 * sizeof(int64_t));
    row.row.assign(rows + (size_t)i * m->fp, rows + (size_t)(i + 1) * m->fp);
    // adopt topology/bandwidth versions the mirror has never seen a bump
    // for (pre-attach probe history, federation merges before host
    // registration): any later bump overwrites, so this is lazily exact
    m->topo.try_emplace(topo_key(ps >= 0 ? p.host_slot : 0, child_host_slot),
                        row.key[3]);
    MirrorHost& h = m->hosts[(size_t)p.host_slot];
    if (h.bw_version == INT64_MIN) h.bw_version = row.key[4];
    m->rows_pushed++;
  }
  return 0;
}

void df_mirror_note_sync(DfMirror* m) {
  std::lock_guard<std::mutex> lock(m->mu);
  m->full_syncs++;
}

void df_mirror_stats(DfMirror* m, int64_t* out) {
  std::lock_guard<std::mutex> lock(m->mu);
  int64_t peers = 0, hosts = 0, tasks = 0;
  for (const MirrorPeer& p : m->peers) peers += p.alive;
  for (const MirrorHost& h : m->hosts) hosts += h.alive;
  for (const MirrorTask& t : m->tasks) tasks += t.alive;
  out[0] = m->deltas;
  out[1] = m->rows_pushed;
  out[2] = m->native_rounds;
  out[3] = m->stale_rounds;
  out[4] = m->fallback_rounds;
  out[5] = m->empty_rounds;
  out[6] = m->full_syncs;
  out[7] = m->drives;
  out[8] = peers;
  out[9] = hosts;
  out[10] = tasks;
  out[11] = m->rows_cached;
}

// Drive a batch of whole scheduling rounds off the mirror: per round, draw
// the candidate sample (bit-exact rng.sample over the mirrored peer list),
// run the 8-condition filter natively, gather version-checked cached pair
// rows into the caller's arena, then score + stable top-k through the exact
// df_round_drive pipeline. Python's jobs shrink to the round descriptors
// (O(1) per round: slots, blocked list, round-constant scalars) and the
// commit.
//
// Inputs per round r: task_slot/child_slot/child_host[r], blocked slots
// [blocked_off[r], blocked_off[r+1]) — blocklist ∪ child.block_parents
// mapped to slots — and round_cols[r*3] (the _round_col_values scalars).
// rng_state: [625] u32 in/out — CPython getstate()[1] verbatim (624 words +
// index). Outputs: offsets [M+1], cand_slots [row_cap] survivor peer slots
// in draw order, feats [row_cap, fp] gathered rows (round-constant columns
// broadcast), out_scores [row_cap] (NaN unscored), sel [M,k] (-1 pad),
// n_sel [M], status [M]: 0 = natively resolved, 1 = fallback (node index
// unknown/out of range — Python re-scores the survivors on the serial leg),
// 2 = stale (a cached row missed or failed its version check — serial
// re-score + df_mirror_push_rows revalidation), 3 = mirror miss (task or
// child not mirrored; the round consumed NO rng draws).
//
// Returns 0, or a negative arg error BEFORE any rng consumption:
// -2 row-cap overflow possible (row_cap < rounds * sample_n), -3 feature
// dim mismatch with the scorer, -5 bad args.
int32_t df_mirror_drive(DfScorer* s, DfMirror* m, int32_t rounds,
                        const int32_t* task_slot, const int32_t* child_slot,
                        const int32_t* child_host, const int32_t* blocked_off,
                        const int32_t* blocked, const float* round_cols,
                        int32_t sample_n, int32_t k, int32_t max_depth,
                        uint32_t* rng_state, int32_t* offsets,
                        int32_t* cand_slots, float* feats, float* out_scores,
                        int32_t* sel, int32_t* n_sel, int32_t* status,
                        int32_t row_cap) {
  if (!s || !m || rounds < 0 || sample_n <= 0 || k < 0) return -5;
  const Header& h = s->model->hdr;
  const int FP = (int)h.fp;
  if (FP != m->fp) return -3;
  if ((int64_t)rounds * sample_n > (int64_t)row_cap ||
      (int64_t)row_cap > (int64_t)1 << 24)
    return -2;
  if (rounds == 0) return 0;

  MtState rng;
  std::memcpy(rng.mt, rng_state, 624 * sizeof(uint32_t));
  rng.mti = (int32_t)rng_state[624];

  std::vector<int32_t> sample, crow, prow, rmap;
  sample.reserve(sample_n);

  {
    std::lock_guard<std::mutex> lock(m->mu);
    m->drives++;
    mirror_marks_ensure(m);
    int32_t t = 0;
    offsets[0] = 0;
    for (int32_t r = 0; r < rounds; ++r) {
      n_sel[r] = 0;
      for (int32_t j = 0; j < k; ++j) sel[(size_t)r * k + j] = -1;
      const int32_t ts = task_slot[r], cs = child_slot[r], ch = child_host[r];
      if (!valid_slot(m->tasks.size(), ts) || !m->tasks[(size_t)ts].alive ||
          !valid_slot(m->peers.size(), cs) || !m->peers[(size_t)cs].alive ||
          !valid_slot(m->hosts.size(), ch) || !m->hosts[(size_t)ch].alive) {
        status[r] = 3;  // mirror miss: no rng consumed, Python runs serial
        m->fallback_rounds++;
        offsets[r + 1] = t;
        continue;
      }
      const std::vector<int32_t>& vlist = m->tasks[(size_t)ts].vlist;
      const int32_t n = (int32_t)vlist.size();
      // DAG.random_vertices: whole-list copy consumes NO rng when the
      // sample covers the population
      if (sample_n >= n) {
        sample.assign(vlist.begin(), vlist.end());
      } else {
        mt_sample(&rng, m, vlist.data(), n, sample_n, sample);
      }
      // exclusion stamps: child ∪ blocked ∪ lineage under one epoch
      const uint32_t epoch = ++m->excl_epoch;
      std::vector<uint32_t>& excl = m->excl_mark;
      excl[cs] = epoch;
      for (int32_t b = blocked_off[r]; b < blocked_off[r + 1]; ++b) {
        const int32_t bs = blocked[b];
        if (valid_slot(m->peers.size(), bs)) excl[bs] = epoch;
      }
      mirror_stamp_lineage(m, cs);
      // the 8 filter conditions over the sample, survivors in draw order
      const int32_t t0 = t;
      int32_t round_status = 0;
      const int64_t child_feat = m->hosts[(size_t)ch].feat_version;
      const int32_t cidx = m->hosts[(size_t)ch].node_idx;
      if (cidx < 0 || (uint32_t)cidx >= h.n) round_status = 1;
      for (int32_t i = 0; i < (int32_t)sample.size(); ++i) {
        const int32_t ps = sample[i];
        if (excl[ps] == epoch) continue;
        const MirrorPeer& p = m->peers[(size_t)ps];
        if (p.host_slot == ch) continue;
        if (p.state_code < 0) continue;
        const MirrorHost& ph = m->hosts[(size_t)p.host_slot];
        if (ph.free_slots <= 0) continue;
        if (mirror_depth(m, ps) >= max_depth) continue;
        if (p.bad) continue;
        // survivor: gather its cached pair row if this round still scores
        cand_slots[t] = ps;
        if (round_status == 0) {
          const int32_t pidx = ph.node_idx;
          if (pidx < 0 || (uint32_t)pidx >= h.n) {
            round_status = 1;
          } else {
            auto it = p.rows.find(ch);
            if (it == p.rows.end()) {
              round_status = 2;
            } else {
              const MirrorRow& row = it->second;
              int64_t topo_cur = row.key[3];  // adopt when never bumped
              auto tit = m->topo.find(topo_key(p.host_slot, ch));
              if (tit != m->topo.end()) topo_cur = tit->second;
              const int64_t bw_cur =
                  ph.bw_version == INT64_MIN ? row.key[4] : ph.bw_version;
              if (row.key[0] != p.feat_version || row.key[1] != ph.feat_version ||
                  row.key[2] != child_feat || row.key[3] != topo_cur ||
                  row.key[4] != bw_cur) {
                round_status = 2;
              } else {
                float* fr = feats + (size_t)t * FP;
                std::memcpy(fr, row.row.data(), (size_t)FP * sizeof(float));
                const float* rc = round_cols + (size_t)r * 3;
                fr[10] = rc[0];
                fr[11] = rc[1];
                fr[13] = rc[2];
                crow.push_back(cidx);
                prow.push_back(pidx);
                rmap.push_back(t);
              }
            }
          }
        }
        ++t;
      }
      if (round_status != 0) {
        // drop any rows gathered before the round went stale/fallback
        while (!rmap.empty() && rmap.back() >= t0) {
          rmap.pop_back();
          crow.pop_back();
          prow.pop_back();
        }
        if (round_status == 1) m->fallback_rounds++;
        else m->stale_rounds++;
      } else if (t == t0) {
        m->empty_rounds++;
      } else {
        m->native_rounds++;
      }
      status[r] = round_status;
      offsets[r + 1] = t;
    }
  }  // mirror mutex released: scoring runs on the gathered copies

  const int32_t T = offsets[rounds];
  const int32_t RC = (int32_t)rmap.size();
  std::vector<float> cs_out((size_t)RC);
  if (RC > 0)
    score_rows(s, crow.data(), prow.data(), feats, rmap.data(), RC, cs_out.data());
  for (int32_t t = 0; t < T; ++t) out_scores[t] = std::nanf("");
  for (int32_t i = 0; i < RC; ++i) out_scores[rmap[i]] = cs_out[i];

  // stable top-k per natively-scored round — identical to df_round_drive's
  std::vector<int32_t> order;
  for (int32_t r = 0; r < rounds; ++r) {
    if (status[r] != 0 || k <= 0) continue;
    const int32_t t0 = offsets[r];
    const int32_t nr = offsets[r + 1] - t0;
    if (nr <= 0) continue;
    order.resize(nr);
    for (int32_t j = 0; j < nr; ++j) order[j] = j;
    const float* sc = out_scores + t0;
    std::stable_sort(order.begin(), order.end(), [sc](int32_t a, int32_t b) {
      const float xa = sc[a], xb = sc[b];
      const bool na = std::isnan(xa), nb = std::isnan(xb);
      if (na || nb) return nb && !na;
      return xa > xb;
    });
    const int32_t kk = std::min<int32_t>(k, nr);
    for (int32_t j = 0; j < kk; ++j) sel[(size_t)r * k + j] = order[j];
    n_sel[r] = kk;
  }

  std::memcpy(rng_state, rng.mt, 624 * sizeof(uint32_t));
  rng_state[624] = (uint32_t)rng.mti;
  return 0;
}

}  // extern "C"
