"""Micro-batching facade over the native scorer's multi-round FFI entry.

The scheduler serves many concurrent AnnouncePeer streams on one asyncio
loop; each scheduling round needs one ~40-candidate scoring call. Crossing
the FFI per round caps throughput at the single-call rate, so under load this
facade queues concurrent rounds and flushes them as ONE
``df_scorer_score_rounds`` call (scorer.cc) — the amortized path behind the
10k-calls/s north star (BASELINE.md config 5; the reference's intent was a
TF-Serving Predict RPC per round, pkg/rpc/tfserving/client/client_v1.go:82-102,
which it never implemented).

Design: an explicit flush loop, not per-call timers. A caller appends its
round to the pending list and awaits its future; the single flusher task
drains everything pending in one native call, then yields to the loop. Under
no load a round still completes in one loop tick (no artificial latency
floor); under load the queue depth self-adjusts to the arrival rate.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)


class MicroBatchScorer:
    """Coalesces concurrent score() calls into multi-round native calls.

    All rounds in one flush must share the candidate batch width B (rounds
    are padded up to the widest round in the flush; padding rows reuse index
    0 with zero features and are sliced off on return).
    """

    def __init__(self, scorer, *, max_rounds_per_flush: int = 64, offload: bool | None = None):
        import os

        self._scorer = scorer  # NativeScorer (or anything with score_rounds)
        self._max_rounds = max_rounds_per_flush
        self._pending: list[tuple[np.ndarray, np.ndarray, np.ndarray, asyncio.Future]] = []
        self._flusher: Optional[asyncio.Task] = None
        # Off-loop flushes only pay off with a second core to run them on:
        # the native call releases the GIL, so on a multi-core host the loop
        # builds the next flush's features while this one's GEMMs run; on a
        # single core the thread hop is pure overhead (measured ~-15%).
        self._offload = offload if offload is not None else (os.cpu_count() or 1) > 1
        self.flushes = 0
        self.rounds = 0

    @property
    def ready(self) -> bool:
        return getattr(self._scorer, "ready", False)

    async def score(
        self, pair_feats: np.ndarray, *, child: np.ndarray, parent: np.ndarray
    ) -> np.ndarray:
        """Queue one scoring round; resolves after the next flush."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending.append((np.asarray(pair_feats), np.asarray(child), np.asarray(parent), fut))
        if self._flusher is None or self._flusher.done():
            self._flusher = asyncio.create_task(self._flush_loop())
        return await fut

    async def _flush_loop(self) -> None:
        # Yield once so callers scheduled in the same tick can enqueue before
        # the first drain — this is what turns N concurrent rounds into one
        # native call instead of N.
        await asyncio.sleep(0)
        while self._pending:
            batch, self._pending = self._pending[: self._max_rounds], self._pending[self._max_rounds :]
            # The NATIVE scorer rejects a flat batch containing any bad index
            # (ValueError), so its rounds dispatch OPTIMISTICALLY — the
            # per-round bounds checks (4 numpy reductions each) stay off the
            # hot path and run only after a rejection, isolating the culprit
            # round(s) and re-scoring the rest. Every other scorer must be
            # validated UP FRONT: the JAX fallback's gather CLAMPS
            # out-of-bounds indices under jit — a stale node id would return
            # a wrong score instead of raising anything.
            optimistic = getattr(self._scorer, "engine", None) == "native"
            good = batch
            try:
                if not optimistic:
                    good = self._validate(batch)
                    if not good:
                        continue
                out, widths = await self._score(good)
            except Exception as e:
                if not optimistic:
                    self._fail_all(good, e)
                    continue
                try:
                    good = self._validate(good)
                except Exception as ve:  # broken scorer: fail the flush
                    self._fail_all(good, ve)
                    continue
                if not good:
                    continue  # culprits already resolved by _validate
                try:
                    out, widths = await self._score(good)
                except Exception as e2:  # pragma: no cover - defensive
                    self._fail_all(good, e2)
                    continue
            self.flushes += 1
            self.rounds += len(good)
            for m, (*_r, fut) in enumerate(good):
                if not fut.done():
                    fut.set_result(out[m, : widths[m]])
            await asyncio.sleep(0)

    async def _score(self, good) -> tuple[np.ndarray, list[int]]:
        if len(good) == 1 or not self._offload:
            # single-round (or single-core) latency path: a thread hop costs
            # more than it buys
            return self._score_assembled(good)
        # Multi-round flush runs OFF the loop thread: the native call
        # releases the GIL (ctypes + OpenMP inside), so the event loop keeps
        # building the NEXT flush's features while this one's GEMMs run —
        # scoring and feature assembly pipeline instead of serializing.
        return await asyncio.to_thread(self._score_assembled, good)

    @staticmethod
    def _fail_all(rounds, err: BaseException) -> None:
        for *_r, fut in rounds:
            if not fut.done():
                fut.set_exception(err)

    def _validate(self, batch) -> list:
        """Per-round bounds checks, run ONLY after the native call rejected a
        flat batch (loop thread — it resolves futures): the native call
        rejects the whole batch on any bad index, so one round carrying a
        stale node id (e.g. from a pre-refresh graph) must fail alone, not
        take down 63 healthy concurrent rounds. Resolves culprit futures with
        the error and returns the surviving rounds for re-scoring."""
        n = self._scorer.num_nodes
        good = []
        for f, c, p, fut in batch:
            if c.min(initial=0) < 0 or p.min(initial=0) < 0 or (
                len(c) and (c.max() >= n or p.max() >= n)
            ):
                if not fut.done():
                    fut.set_exception(
                        ValueError(f"node index out of range for {n}-node artifact")
                    )
            else:
                good.append((f, c, p, fut))
        return good

    def _score_assembled(self, good) -> tuple[np.ndarray, list[int]]:
        """Assembly + the native call; pure compute, safe off the loop."""
        fp = self._scorer.feature_dim
        widths = [len(c) for _f, c, _p, _fut in good]
        B = max(widths)
        M = len(good)
        feats = np.zeros((M, B, fp), np.float32)
        child = np.zeros((M, B), np.int32)
        parent = np.zeros((M, B), np.int32)
        for m, (f, c, p, _fut) in enumerate(good):
            feats[m, : widths[m]] = f
            child[m, : widths[m]] = c
            parent[m, : widths[m]] = p
        out = self._scorer.score_rounds(feats, child=child, parent=parent)
        return out, widths
