"""Artifact export + ctypes binding for the C++ batched scorer (scorer.cc).

Flow (north-star config 5):
  1. trainer finishes → `export_scorer_artifact(params, z, path)` flattens the
     TopoScorer head weights + cached embeddings into scorer.cc's binary format
  2. `build_native_lib()` compiles scorer.cc once (g++ -O3, cached by mtime)
  3. `NativeScorer(artifact)` loads both and serves `score()` with the same
     batch signature as models.scorer.GNNScorer — drop-in for the scheduler's
     `ml` evaluator slot, no JAX runtime on the hot path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import subprocess
from pathlib import Path
from typing import Any

import numpy as np

logger = logging.getLogger(__name__)

_MAGIC = 0x44465343
_VERSION = 1
_SRC = Path(__file__).with_name("scorer.cc")


def _default_lib_path() -> Path:
    # per-user cache dir: the .so is CDLL-loaded, so a predictable path in a
    # world-writable tmp dir would be a cross-user code-injection vector
    override = os.environ.get("DRAGONFLY_NATIVE_CACHE")
    if override:
        cache = Path(override)
    else:
        xdg = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
        cache = Path(xdg) / "dragonfly2_tpu_native"
    cache.mkdir(parents=True, exist_ok=True)
    os.chmod(cache, 0o700)
    return cache / "libdfscorer.so"


def build_native_lib(*, force: bool = False, lib_path: Path | None = None) -> Path:
    """Compile scorer.cc → shared library (cached; rebuilt when stale)."""
    lib = lib_path or _default_lib_path()
    if not force and lib.exists() and lib.stat().st_mtime >= _SRC.stat().st_mtime:
        return lib
    lib.parent.mkdir(parents=True, exist_ok=True)
    tmp = lib.with_name(lib.name + f".{os.getpid()}.tmp")
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-ffast-math",
            "-funroll-loops", "-o", str(tmp), str(_SRC)]
    # best → portable: native SIMD + OpenMP, then native SIMD, then plain
    for extra in (["-march=native", "-fopenmp"], ["-march=native"], []):
        try:
            subprocess.run(base + extra, check=True, capture_output=True, text=True)
            break
        except subprocess.CalledProcessError as e:
            err = e.stderr
    else:
        raise RuntimeError(f"native scorer build failed:\n{err}")
    tmp.replace(lib)
    logger.info("built native scorer lib at %s", lib)
    return lib


def export_scorer_artifact(params: Any, z: np.ndarray, path: str | Path) -> Path:
    """Write the binary scoring artifact: cached embeddings + head weights.

    params: the TopoScorer flax variables ({'params': {'head': {'layers_0':
    ...}}}); z: [N, D] float32 node embeddings from TopoScorer.embed.
    """
    head = params["params"]["head"]
    w1 = np.asarray(head["layers_0"]["kernel"], np.float32)
    b1 = np.asarray(head["layers_0"]["bias"], np.float32)
    w2 = np.asarray(head["layers_2"]["kernel"], np.float32)
    b2 = np.asarray(head["layers_2"]["bias"], np.float32)
    w3 = np.asarray(head["layers_4"]["kernel"], np.float32)
    b3 = np.asarray(head["layers_4"]["bias"], np.float32)
    z = np.ascontiguousarray(np.asarray(z, np.float32))

    n, d = z.shape
    in_dim, h1 = w1.shape
    fp = in_dim - 3 * d
    if fp < 0:
        raise ValueError(f"head input {in_dim} < 3*embed_dim {3*d}: wrong params/z pairing")
    if w2.shape != (h1, w2.shape[1]) or w3.shape[0] != w2.shape[1] or w3.shape[1] != 1:
        raise ValueError(f"unexpected head shapes: {w1.shape}, {w2.shape}, {w3.shape}")
    h2 = w2.shape[1]

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as f:
        f.write(struct.pack("<7I", _MAGIC, _VERSION, n, d, fp, h1, h2))
        for arr in (z, w1, b1, w2, b2, w3, b3):
            f.write(np.ascontiguousarray(arr, np.float32).tobytes())
    tmp.replace(path)
    return path


class NativeScorer:
    """ctypes binding with GNNScorer's batch-score interface.

    `score(pair_feats, child=, parent=)` → [B] float32 in (0, 1). `ready` is
    always True once constructed (embeddings ship inside the artifact).
    """

    engine = "native"  # serving-mode metric label

    def __init__(self, artifact_path: str | Path, *, lib_path: Path | None = None):
        lib = build_native_lib(lib_path=lib_path)
        self._dll = ctypes.CDLL(str(lib))
        self._dll.df_scorer_load.restype = ctypes.c_void_p
        self._dll.df_scorer_load.argtypes = [ctypes.c_char_p]
        self._dll.df_scorer_free.argtypes = [ctypes.c_void_p]
        for fn in ("df_scorer_num_nodes", "df_scorer_embed_dim", "df_scorer_feature_dim"):
            getattr(self._dll, fn).restype = ctypes.c_int32
            getattr(self._dll, fn).argtypes = [ctypes.c_void_p]
        self._dll.df_scorer_score.restype = ctypes.c_int32
        self._dll.df_scorer_score.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
        ]
        self._dll.df_scorer_score_rounds.restype = ctypes.c_int32
        self._dll.df_scorer_score_rounds.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float),
        ]
        self._dll.df_scorer_set_thread_parallelism.argtypes = [ctypes.c_int32]
        self._dll.df_scorer_set_thread_parallelism.restype = None
        self._dll.df_scorer_fork.restype = ctypes.c_void_p
        self._dll.df_scorer_fork.argtypes = [ctypes.c_void_p]
        _pi32 = ctypes.POINTER(ctypes.c_int32)
        _pf32 = ctypes.POINTER(ctypes.c_float)
        self._dll.df_round_drive.restype = ctypes.c_int32
        self._dll.df_round_drive.argtypes = [
            ctypes.c_void_p,  # handle
            _pi32,  # offsets [M+1]
            _pi32,  # child_idx [M]
            _pi32,  # parent_idx [T]
            _pf32,  # feats [T, FP]
            _pf32,  # round_cols [M, 3]
            _pi32,  # filt [T, 4]
            ctypes.c_int32,  # rounds
            ctypes.c_int32,  # k
            ctypes.c_int32,  # max_depth
            _pf32,  # out_scores [T]
            _pi32,  # sel [M, k]
            _pi32,  # n_sel [M]
            _pi32,  # status [M]
        ]
        # bound-method + pointer-type lookups cached off the hot path: at the
        # 10k-calls/s target every getattr/py-object allocation per call counts
        self._score_fn = self._dll.df_scorer_score
        self._score_rounds_fn = self._dll.df_scorer_score_rounds
        self._drive_fn = self._dll.df_round_drive
        self._pi32 = _pi32
        self._pf32 = _pf32
        self.drive_calls = 0  # FFI-call observability for bench/dfstress
        self._handle = self._dll.df_scorer_load(str(artifact_path).encode())
        if not self._handle:
            raise IOError(f"failed to load scorer artifact {artifact_path}")
        self.num_nodes = self._dll.df_scorer_num_nodes(self._handle)
        self.embed_dim = self._dll.df_scorer_embed_dim(self._handle)
        self.feature_dim = self._dll.df_scorer_feature_dim(self._handle)

    @property
    def ready(self) -> bool:
        return True

    def score(
        self, pair_feats: np.ndarray, *, child: np.ndarray, parent: np.ndarray
    ) -> np.ndarray:
        feats = np.ascontiguousarray(pair_feats, np.float32)
        c = np.ascontiguousarray(child, np.int32)
        p = np.ascontiguousarray(parent, np.int32)
        batch = len(c)
        if len(p) != batch:
            raise ValueError(f"child/parent length mismatch: {batch} != {len(p)}")
        if feats.shape != (batch, self.feature_dim):
            raise ValueError(
                f"pair_feats shape {feats.shape} != ({batch}, {self.feature_dim})"
            )
        out = np.empty(batch, np.float32)
        rc = self._score_fn(
            self._handle,
            c.ctypes.data_as(self._pi32),
            p.ctypes.data_as(self._pi32),
            feats.ctypes.data_as(self._pf32),
            batch,
            out.ctypes.data_as(self._pf32),
        )
        if rc != 0:
            raise ValueError(f"native scorer rejected batch (rc={rc}): bad node index")
        return out

    def score_rounds(
        self, pair_feats: np.ndarray, *, child: np.ndarray, parent: np.ndarray
    ) -> np.ndarray:
        """Score M queued scheduling rounds in ONE FFI call (amortized path).

        pair_feats: [M, B, FP]; child/parent: [M, B] int32. Returns [M, B]
        float32. Rounds are independent, so the native side runs one flat
        (M·B)-row batch through the GEMMs — FFI, validation, and dispatch
        overhead is paid once per M rounds instead of per round.
        """
        feats = np.ascontiguousarray(pair_feats, np.float32)
        c = np.ascontiguousarray(child, np.int32)
        p = np.ascontiguousarray(parent, np.int32)
        if feats.ndim != 3 or c.shape != feats.shape[:2] or p.shape != c.shape:
            raise ValueError(
                f"shape mismatch: feats {feats.shape}, child {c.shape}, parent {p.shape}"
            )
        rounds, batch, fp = feats.shape
        if fp != self.feature_dim:
            raise ValueError(f"pair_feats last dim {fp} != {self.feature_dim}")
        out = np.empty((rounds, batch), np.float32)
        rc = self._score_rounds_fn(
            self._handle,
            c.ctypes.data_as(self._pi32),
            p.ctypes.data_as(self._pi32),
            feats.ctypes.data_as(self._pf32),
            rounds,
            batch,
            out.ctypes.data_as(self._pf32),
        )
        if rc == -2:
            raise ValueError(
                f"native scorer rejected batch: {rounds}x{batch} rows exceeds the "
                "2^24-row per-call cap"
            )
        if rc != 0:
            raise ValueError(f"native scorer rejected batch (rc={rc}): bad node index")
        return out

    def drive_rounds(
        self,
        offsets: np.ndarray,
        child_idx: np.ndarray,
        parent_idx: np.ndarray,
        feats: np.ndarray,
        round_cols: np.ndarray,
        filt: np.ndarray,
        *,
        rounds: int,
        k: int,
        max_depth: int,
        out_scores: np.ndarray,
        sel: np.ndarray,
        n_sel: np.ndarray,
        status: np.ndarray,
    ) -> None:
        """Drive `rounds` whole scheduling rounds in ONE FFI call (GIL released).

        The caller owns every buffer (a reusable per-thread arena — see
        scheduling._RoundArena) and guarantees dtype/contiguity: offsets,
        child_idx, parent_idx, n_sel, status and the [T,4] filt / [M,k] sel
        blocks are int32; feats ([T,FP]), round_cols ([M,3]) and out_scores
        ([T]) are float32. No per-call allocation or dtype coercion happens
        here — this wrapper is on the 10k-rounds/s hot path. The driver
        fills feats' round-constant columns, scores the survivor rows with
        the exact score_rounds pipeline, and writes stable top-k selections;
        per-round `status` distinguishes natively-scored rounds (0) from
        rounds the caller must re-run on the Python serial leg (1).
        """
        self.drive_rounds_bound(
            self.bind_drive(
                offsets, child_idx, parent_idx, feats, round_cols, filt,
                out_scores, sel, n_sel, status,
            ),
            rounds=rounds, k=k, max_depth=max_depth,
        )

    def bind_drive(
        self,
        offsets: np.ndarray,
        child_idx: np.ndarray,
        parent_idx: np.ndarray,
        feats: np.ndarray,
        round_cols: np.ndarray,
        filt: np.ndarray,
        out_scores: np.ndarray,
        sel: np.ndarray,
        n_sel: np.ndarray,
        status: np.ndarray,
    ) -> tuple:
        """Precompute drive_rounds' ctypes pointer arguments for a reusable
        buffer set. The 13 per-call `.ctypes.data_as` conversions cost ~40 µs
        per drive — a real tax on one-round batches — and the arena's buffers
        only move when it grows, so the binding is cached on the arena and
        invalidated by `_RoundArena.ensure` on reallocation. Pointer-only:
        a binding made through one forked handle is valid on any fork of the
        same model (ctypes pointer types are process-global)."""
        return (
            offsets.ctypes.data_as(self._pi32),
            child_idx.ctypes.data_as(self._pi32),
            parent_idx.ctypes.data_as(self._pi32),
            feats.ctypes.data_as(self._pf32),
            round_cols.ctypes.data_as(self._pf32),
            filt.ctypes.data_as(self._pi32),
            out_scores.ctypes.data_as(self._pf32),
            sel.ctypes.data_as(self._pi32),
            n_sel.ctypes.data_as(self._pi32),
            status.ctypes.data_as(self._pi32),
        )

    def drive_rounds_bound(
        self, binding: tuple, *, rounds: int, k: int, max_depth: int
    ) -> None:
        """drive_rounds over a prebuilt `bind_drive` binding (hot path)."""
        rc = self._drive_fn(
            self._handle,
            binding[0], binding[1], binding[2], binding[3], binding[4],
            binding[5], rounds, k, max_depth,
            binding[6], binding[7], binding[8], binding[9],
        )
        self.drive_calls += 1
        if rc != 0:
            raise ValueError(f"native round driver rejected batch (rc={rc})")

    def fork(self) -> "NativeScorer":
        """A second handle onto the SAME loaded model (df_scorer_fork).

        scorer.cc serializes concurrent calls on ONE handle behind an
        internal mutex (the scratch buffers live in the handle), so a scorer
        shared across the round dispatcher's worker threads would serialize
        exactly the leg the dispatcher exists to overlap. Each worker thread
        scores through its own forked handle instead (ScorerHandlePool).
        Forked handles share the immutable model data natively (refcounted)
        — no artifact re-read, and crucially no duplicated weight/embedding
        cache footprint: per-handle model copies capped 2-worker scaling at
        ~1.2x on a host whose compute scales 1.93x (LLC thrash)."""
        clone = object.__new__(NativeScorer)
        clone.__dict__.update(self.__dict__)
        handle = self._dll.df_scorer_fork(self._handle)
        if not handle:
            raise IOError("df_scorer_fork failed (closed handle?)")
        clone._handle = handle
        clone.drive_calls = 0  # each handle counts its own FFI calls
        return clone

    def limit_thread_parallelism(self, n: int = 1) -> None:
        """Cap intra-call OpenMP fan-out for the CALLING thread (per-thread
        ICV). Dispatcher worker threads call this once: sharding rounds
        across workers AND letting each call's GEMM spawn its own OMP team
        oversubscribes the host (libgomp spin-waiters starve the other
        workers' Python — measured negative scaling on the 2-core box)."""
        self._dll.df_scorer_set_thread_parallelism(n)

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._dll.df_scorer_free(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:  # dflint: disable=DF031 interpreter teardown can raise anything; __del__ must not
            pass


class NativeMirror:
    """ctypes binding for the `df_mirror_*` surface (ISSUE 19): the C-side
    mirror of the scheduler's per-task candidate state.

    This class is the thin FFI layer only — slot allocation, the mutation
    hooks, and the full-sync protocol live in scheduler.mirror.MirrorClient.
    Delta methods are cached bound functions because they sit on mutation
    hot paths (every feat bump crosses here once); `drive` marshals the
    caller's arena pointers the same way NativeScorer.bind_drive does.
    """

    _pi32 = ctypes.POINTER(ctypes.c_int32)
    _pi64 = ctypes.POINTER(ctypes.c_int64)
    _pf32 = ctypes.POINTER(ctypes.c_float)
    _pu32 = ctypes.POINTER(ctypes.c_uint32)

    def __init__(self, scorer: "NativeScorer", *, feature_dim: int | None = None):
        dll = scorer._dll
        self._dll = dll
        if not getattr(dll, "_df_mirror_bound", False):
            i32, i64 = ctypes.c_int32, ctypes.c_int64
            vp = ctypes.c_void_p
            dll.df_mirror_new.restype = vp
            dll.df_mirror_new.argtypes = [i32]
            dll.df_mirror_free.restype = None
            dll.df_mirror_free.argtypes = [vp]
            dll.df_mirror_host_upsert.restype = i32
            dll.df_mirror_host_upsert.argtypes = [vp, i32, i64, i32, i32]
            dll.df_mirror_host_remove.restype = i32
            dll.df_mirror_host_remove.argtypes = [vp, i32]
            dll.df_mirror_task_upsert.restype = i32
            dll.df_mirror_task_upsert.argtypes = [vp, i32]
            dll.df_mirror_task_remove.restype = i32
            dll.df_mirror_task_remove.argtypes = [vp, i32]
            dll.df_mirror_peer_add.restype = i32
            dll.df_mirror_peer_add.argtypes = [vp, i32, i32, i32, i32, i32, i64]
            dll.df_mirror_peer_remove.restype = i32
            dll.df_mirror_peer_remove.argtypes = [vp, i32]
            dll.df_mirror_peer_feat.restype = i32
            dll.df_mirror_peer_feat.argtypes = [vp, i32, i64, i32]
            dll.df_mirror_peer_state.restype = i32
            dll.df_mirror_peer_state.argtypes = [vp, i32, i32]
            dll.df_mirror_set_parents.restype = i32
            dll.df_mirror_set_parents.argtypes = [vp, i32, self._pi32, i32]
            dll.df_mirror_topo_bump.restype = i32
            dll.df_mirror_topo_bump.argtypes = [vp, i32, i32, i64]
            dll.df_mirror_bw_bump.restype = i32
            dll.df_mirror_bw_bump.argtypes = [vp, i32, i64]
            dll.df_mirror_set_node_indices.restype = i32
            dll.df_mirror_set_node_indices.argtypes = [vp, self._pi32, self._pi32, i32]
            dll.df_mirror_push_rows.restype = i32
            dll.df_mirror_push_rows.argtypes = [
                vp, i32, i32, self._pi32, self._pi64, self._pf32,
            ]
            dll.df_mirror_note_sync.restype = None
            dll.df_mirror_note_sync.argtypes = [vp]
            dll.df_mirror_stats.restype = None
            dll.df_mirror_stats.argtypes = [vp, self._pi64]
            dll.df_mirror_drive.restype = i32
            dll.df_mirror_drive.argtypes = [
                vp, vp, i32,                       # scorer, mirror, rounds
                self._pi32, self._pi32, self._pi32,  # task/child/child_host
                self._pi32, self._pi32,            # blocked_off, blocked
                self._pf32,                        # round_cols [M,3]
                i32, i32, i32,                     # sample_n, k, max_depth
                self._pu32,                        # rng_state [625] in/out
                self._pi32, self._pi32,            # offsets, cand_slots
                self._pf32, self._pf32,            # feats, out_scores
                self._pi32, self._pi32, self._pi32,  # sel, n_sel, status
                i32,                               # row_cap
            ]
            dll._df_mirror_bound = True
        self.feature_dim = int(feature_dim or scorer.feature_dim)
        self._handle = dll.df_mirror_new(self.feature_dim)
        if not self._handle:
            raise ValueError(f"df_mirror_new rejected feature_dim={self.feature_dim}")
        # cached bound fns: the delta methods ride mutation hot paths
        self.host_upsert_fn = dll.df_mirror_host_upsert
        self.host_remove_fn = dll.df_mirror_host_remove
        self.task_upsert_fn = dll.df_mirror_task_upsert
        self.task_remove_fn = dll.df_mirror_task_remove
        self.peer_add_fn = dll.df_mirror_peer_add
        self.peer_remove_fn = dll.df_mirror_peer_remove
        self.peer_feat_fn = dll.df_mirror_peer_feat
        self.peer_state_fn = dll.df_mirror_peer_state
        self._set_parents_fn = dll.df_mirror_set_parents
        self.topo_bump_fn = dll.df_mirror_topo_bump
        self.bw_bump_fn = dll.df_mirror_bw_bump
        self._drive_fn = dll.df_mirror_drive
        self.drive_calls = 0

    @property
    def handle(self):
        return self._handle

    def set_parents(self, child_slot: int, parent_slots) -> int:
        n = len(parent_slots)
        arr = (ctypes.c_int32 * n)(*parent_slots)
        return self._set_parents_fn(self._handle, child_slot, arr, n)

    def set_node_indices(self, slots: np.ndarray, idx: np.ndarray) -> int:
        s = np.ascontiguousarray(slots, np.int32)
        i = np.ascontiguousarray(idx, np.int32)
        return self._dll.df_mirror_set_node_indices(
            self._handle, s.ctypes.data_as(self._pi32),
            i.ctypes.data_as(self._pi32), len(s),
        )

    def push_rows(
        self, child_host_slot: int, peer_slots: np.ndarray, keys: np.ndarray,
        rows: np.ndarray,
    ) -> int:
        ps = np.ascontiguousarray(peer_slots, np.int32)
        ky = np.ascontiguousarray(keys, np.int64)
        rw = np.ascontiguousarray(rows, np.float32)
        return self._dll.df_mirror_push_rows(
            self._handle, child_host_slot, len(ps),
            ps.ctypes.data_as(self._pi32), ky.ctypes.data_as(self._pi64),
            rw.ctypes.data_as(self._pf32),
        )

    def note_sync(self) -> None:
        self._dll.df_mirror_note_sync(self._handle)

    _STAT_KEYS = (
        "deltas", "rows_pushed", "native_rounds", "stale_rounds",
        "fallback_rounds", "empty_rounds", "full_syncs", "drives",
        "peers", "hosts", "tasks", "rows_cached",
    )

    def stats(self) -> dict:
        out = (ctypes.c_int64 * 16)()
        self._dll.df_mirror_stats(self._handle, out)
        return dict(zip(self._STAT_KEYS, out[: len(self._STAT_KEYS)]))

    def bind_drive(
        self, task_slot, child_slot, child_host, blocked_off, blocked,
        round_cols, rng_state, offsets, cand_slots, feats, out_scores,
        sel, n_sel, status,
    ) -> tuple:
        """Precompute the drive's ctypes pointer arguments for a reusable
        arena (same caching contract as NativeScorer.bind_drive: the binding
        is invalidated by the arena whenever a buffer moves)."""
        return (
            task_slot.ctypes.data_as(self._pi32),
            child_slot.ctypes.data_as(self._pi32),
            child_host.ctypes.data_as(self._pi32),
            blocked_off.ctypes.data_as(self._pi32),
            blocked.ctypes.data_as(self._pi32),
            round_cols.ctypes.data_as(self._pf32),
            ctypes.cast(rng_state, self._pu32),
            offsets.ctypes.data_as(self._pi32),
            cand_slots.ctypes.data_as(self._pi32),
            feats.ctypes.data_as(self._pf32),
            out_scores.ctypes.data_as(self._pf32),
            sel.ctypes.data_as(self._pi32),
            n_sel.ctypes.data_as(self._pi32),
            status.ctypes.data_as(self._pi32),
        )

    def drive_bound(
        self, scorer: "NativeScorer", binding: tuple, *, rounds: int,
        sample_n: int, k: int, max_depth: int, row_cap: int,
    ) -> None:
        """One mirror-backed drive over a prebuilt binding (hot path). The
        GIL is released for the whole call; arg errors raise BEFORE any rng
        consumption (the C side validates first), so the caller can re-run
        the batch serially on the untouched rng stream."""
        rc = self._drive_fn(
            scorer._handle, self._handle, rounds,
            binding[0], binding[1], binding[2], binding[3], binding[4],
            binding[5], sample_n, k, max_depth, binding[6],
            binding[7], binding[8], binding[9], binding[10], binding[11],
            binding[12], binding[13], row_cap,
        )
        self.drive_calls += 1
        if rc != 0:
            raise ValueError(f"native mirror drive rejected batch (rc={rc})")

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._dll.df_mirror_free(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:  # dflint: disable=DF031 interpreter teardown can raise anything; __del__ must not
            pass


class ScorerHandlePool:
    """Per-thread native scorer handles behind one artifact.

    The pattern scorer.cc documents: concurrent scoring calls on one handle
    serialize on an internal mutex, so every thread that scores needs its own
    handle. `get()` returns the calling thread's handle, forking one from the
    primary on a thread's first call; the constructing thread (the scheduler
    event loop) is pre-bound to the PRIMARY scorer so single-threaded callers
    see zero behavior change. Forked handles are tracked and freed by
    `close()`; the pool never closes the primary (its owner does).

    Worker threads are long-lived (the dispatcher's ThreadPoolExecutor), so
    the handle count is bounded by the worker count, not the call count.
    """

    def __init__(self, scorer: "NativeScorer"):
        import threading

        self._primary = scorer
        self._local = threading.local()
        self._local.scorer = scorer  # creator thread scores on the primary
        self._forks: list[NativeScorer] = []
        self._lock = threading.Lock()
        self._closed = False

    @property
    def ready(self) -> bool:
        return getattr(self._primary, "ready", False)

    def get(self) -> "NativeScorer":
        if self._closed:
            # the cached thread-local fork may already be freed — a closed
            # pool degrades every thread to the (caller-owned) primary
            # rather than handing back a handle whose native side is gone
            return self._primary
        s = getattr(self._local, "scorer", None)
        if s is None:
            s = self._primary.fork()
            # this NEW worker thread's GEMMs stay single-threaded: the
            # dispatcher parallelizes across workers, and nested OMP teams
            # oversubscribe the host (see limit_thread_parallelism)
            s.limit_thread_parallelism(1)
            with self._lock:
                if self._closed:  # raced a close(): don't leak the handle
                    s.close()
                    return self._primary
                self._forks.append(s)
            self._local.scorer = s
        return s

    def handles(self) -> int:
        """Live handle count (primary + forks) — observability/tests."""
        with self._lock:
            return 1 + len(self._forks)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            forks, self._forks = self._forks, []
        for s in forks:
            s.close()
