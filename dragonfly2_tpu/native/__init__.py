"""Native serving runtime: C++ batched scorer behind ctypes.

The reference's online-inference plan was a TF-Serving RPC per scheduling
round (pkg/rpc/tfserving/client/client_v1.go:82-102, never wired in). The
TPU-native replacement (SURVEY.md §2.1, north-star config 5) is an exported
CPU artifact scored in-process: JAX computes and caches the GraphSAGE node
embeddings at refresh time, the C++ library scores (child, parent) batches
through the MLP head with no Python/JAX on the hot path.
"""

from dragonfly2_tpu.native.microbatch import MicroBatchScorer
from dragonfly2_tpu.native.scorer import (
    NativeScorer,
    ScorerHandlePool,
    build_native_lib,
    export_scorer_artifact,
)

__all__ = [
    "MicroBatchScorer",
    "NativeScorer",
    "ScorerHandlePool",
    "build_native_lib",
    "export_scorer_artifact",
]
