"""Device mesh + sharding rules for the trainer.

Scaling model ("How to Scale Your Model" recipe): pick a mesh, annotate
shardings on inputs/params, let XLA insert the collectives, profile. Axes:

  data  — batch/data parallelism: training pairs and graph node rows are
          row-sharded here; XLA inserts the gradient psum and the per-layer
          all-gather that the cross-shard neighbor gather needs (this is the
          sequence-parallel-shaped axis of the GNN: nodes play the role of
          sequence positions).
  model — tensor parallelism: Dense kernels column-sharded on the output dim.

The reference has no ICI story at all (its parallelism is goroutines + gRPC,
SURVEY.md §2.4); this module is where the TPU build replaces it.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(devices: list | None = None, *, model_parallel: int | None = None) -> Mesh:
    """Build a ("data", "model") mesh over the given (or all) devices.

    model_parallel defaults to the largest power of two ≤ min(4, n_devices)
    that divides the device count — tp stays small (it rides ICI), dp takes
    the rest.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if model_parallel is None:
        model_parallel = 1
        for cand in (2, 4):
            if n % cand == 0 and cand <= n:
                model_parallel = cand
    if n % model_parallel:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    grid = np.asarray(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def _shardable(dim: int, mesh: Mesh, axis: str) -> bool:
    return dim % mesh.shape[axis] == 0


def param_leaf_sharding(leaf: Any, mesh: Mesh) -> NamedSharding:
    """Tensor-parallel rule for one leaf: 2-D kernels column-shard the output
    dim over "model" when divisible; 1-D biases follow; else replicate.

    Also applied to optimizer-state leaves (adam m/v mirror param shapes) so
    opt state and params never diverge in sharding.
    """
    shape = getattr(leaf, "shape", ())
    if len(shape) == 2 and _shardable(shape[1], mesh, MODEL_AXIS):
        return NamedSharding(mesh, P(None, MODEL_AXIS))
    if len(shape) == 1 and shape[0] > 1 and _shardable(shape[0], mesh, MODEL_AXIS):
        return NamedSharding(mesh, P(MODEL_AXIS))
    return NamedSharding(mesh, P())


def infer_param_sharding(params: Any, mesh: Mesh) -> Any:
    """Apply param_leaf_sharding across a whole pytree."""
    return jax.tree.map(lambda leaf: param_leaf_sharding(leaf, mesh), params)


def graph_shardings(mesh: Mesh) -> tuple[NamedSharding, ...]:
    """Shardings for TopoGraph fields: node rows over "data"."""
    row = NamedSharding(mesh, P(DATA_AXIS))
    return (
        row,  # node_feats [N, F]
        row,  # neighbors  [N, K]
        row,  # mask       [N, K]
        row,  # edge_feats [N, K, E]
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(n: int, multiple: int) -> int:
    return int(math.ceil(n / multiple) * multiple)
