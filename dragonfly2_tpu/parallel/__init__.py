"""Mesh + sharding helpers (dp/tp over ICI, scaling-book style)."""

from dragonfly2_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    graph_shardings,
    infer_param_sharding,
    make_mesh,
)
