"""Multi-process (multi-host) initialization and data feeding.

North-star configs 3/4 run one JAX process per TPU-VM host (v5e-16 /
v5p-64); collectives ride ICI between chips and DCN between hosts. This
module is the process-bootstrap layer for that topology:

  initialize()              — jax.distributed wrapper (coordinator + N
                              processes), env- or argument-driven, with the
                              CPU-simulation knobs needed to exercise the
                              SAME code path on a laptop/CI: each process
                              hosts `local_device_count` virtual CPU devices
                              and cross-process collectives run over Gloo.
  process_local_batch()     — per-process data feeding: each host samples /
                              loads only its own rows and the global array is
                              assembled from process-local shards
                              (jax.make_array_from_process_local_data), the
                              multihost analogue of the piece-granular range
                              splits the reference uses for downloads
                              (SURVEY.md §5 long-context note).
  launch_localhost()        — spawn an n-process cluster on 127.0.0.1 for
                              tests and dry runs (the "cluster-in-a-box"
                              strategy, SURVEY.md §4).

The reference has no multi-process compute story (its distribution plane is
gRPC + goroutines, SURVEY.md §2.4); this is where the TPU build adds one.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from dataclasses import dataclass
from typing import Any, Sequence

_ENV_COORD = "DF_DIST_COORDINATOR"
_ENV_NPROCS = "DF_DIST_NUM_PROCESSES"
_ENV_PROC_ID = "DF_DIST_PROCESS_ID"
_ENV_LOCAL_DEVICES = "DF_DIST_LOCAL_DEVICES"


@dataclass
class DistributedConfig:
    """One process's view of the cluster. num_processes == 1 → no-op init."""

    coordinator_address: str = ""
    num_processes: int = 1
    process_id: int = 0
    # >0 → simulate this many virtual CPU devices in this process (CI mode);
    # 0 → use the real local platform (TPU chips on a pod host).
    local_device_count: int = 0

    @classmethod
    def from_env(cls) -> "DistributedConfig":
        return cls(
            coordinator_address=os.environ.get(_ENV_COORD, ""),
            num_processes=int(os.environ.get(_ENV_NPROCS, "1")),
            process_id=int(os.environ.get(_ENV_PROC_ID, "0")),
            local_device_count=int(os.environ.get(_ENV_LOCAL_DEVICES, "0")),
        )

    def env(self) -> dict[str, str]:
        return {
            _ENV_COORD: self.coordinator_address,
            _ENV_NPROCS: str(self.num_processes),
            _ENV_PROC_ID: str(self.process_id),
            _ENV_LOCAL_DEVICES: str(self.local_device_count),
        }


def initialize(cfg: DistributedConfig | None = None) -> None:
    """Initialize jax.distributed for this process (idempotent-ish: call once,
    before any other JAX use; backend selection freezes at first device touch).

    CPU-simulation mode (local_device_count > 0) must set the XLA flag and
    platform BEFORE the first backend initialization — same constraint as
    __graft_entry__._force_virtual_cpu.
    """
    cfg = cfg or DistributedConfig.from_env()
    if cfg.local_device_count > 0:
        _force_cpu_devices(cfg.local_device_count)
    if cfg.num_processes <= 1:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )


def _force_cpu_devices(count: int) -> None:
    """Steer this process onto >= `count` virtual CPU devices.

    Must run before the first backend initialization (the flag is read once);
    an existing smaller count in XLA_FLAGS is raised in place so a process
    that inherited the test conftest's 8 can still request 16+. Canonical
    implementation — __graft_entry__._force_virtual_cpu delegates here.
    """
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={count}"
        ).strip()
    elif int(m.group(1)) < count:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"--xla_force_host_platform_device_count={count}"
        )
    import jax

    platforms = (jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS") or "").split(",")
    if platforms and platforms[0] not in ("", "cpu"):
        jax.config.update("jax_platforms", "cpu")


def process_local_batch(sharding, local_rows: Any, global_shape: tuple[int, ...]):
    """Assemble a global array from this process's row slice.

    `local_rows` is the contiguous slice of the global batch this process is
    responsible for (row-ownership follows device order: process p owns rows
    [p·L, (p+1)·L) of a batch-sharded axis). On a single process this is just
    device_put — the same call sites work unchanged in both modes.
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(sharding, local_rows, global_shape)


def local_row_slice(global_rows: int) -> tuple[int, int]:
    """[start, stop) of the batch rows this process owns (equal split)."""
    import jax

    n, p = jax.process_count(), jax.process_index()
    if global_rows % n:
        raise ValueError(f"global batch {global_rows} not divisible by {n} processes")
    per = global_rows // n
    return p * per, (p + 1) * per


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_localhost(
    num_processes: int,
    module: str,
    *,
    local_devices: int = 4,
    extra_env: dict[str, str] | None = None,
    args: Sequence[str] = (),
    timeout: float = 600.0,
) -> list[subprocess.CompletedProcess]:
    """Run `python -m <module> <args>` as an n-process localhost cluster.

    Each process gets the DF_DIST_* env (coordinator on a free port) plus
    `local_devices` virtual CPU devices. Returns the completed processes in
    process-id order; raises if any exits nonzero.

    `timeout` is ONE wall-clock budget for the whole cluster, not a fresh
    allowance per process: a deadlocked collective stalls every process, and
    N sequential full timeouts would multiply the wait by N (a tier-1 run
    lost most of its budget to exactly that before this was a deadline).
    """
    import time as _time

    deadline = _time.monotonic() + timeout
    coord = f"127.0.0.1:{free_port()}"
    procs: list[subprocess.Popen] = []
    for pid in range(num_processes):
        cfg = DistributedConfig(
            coordinator_address=coord,
            num_processes=num_processes,
            process_id=pid,
            local_device_count=local_devices,
        )
        env = dict(os.environ)
        # scrub ambient single-process JAX config; the worker sets its own
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env.update(cfg.env())
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", module, *args],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    done: list[subprocess.CompletedProcess] = []
    failed: list[str] = []
    for pid, p in enumerate(procs):
        try:
            remaining = max(1.0, deadline - _time.monotonic())
            out, err = p.communicate(timeout=remaining)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            failed.append(f"process {pid} timed out ({timeout}s cluster budget)")
        done.append(subprocess.CompletedProcess(p.args, p.returncode, out, err))
        if p.returncode != 0:
            failed.append(
                f"process {pid} rc={p.returncode}: {(err or '').strip()[-500:]}"
            )
    if failed:
        raise RuntimeError("localhost cluster failed:\n" + "\n".join(failed))
    return done
