"""One process of a multi-process sharded GNN training job (config 3 shape).

Launched per-host by `distributed.launch_localhost` (tests / dry runs) or by
the real pod launcher: initializes jax.distributed from DF_DIST_* env, builds
the global ("data", "model") mesh over ALL processes' devices, and runs
DF_MP_STEPS training steps where each process feeds only its own batch rows
(`distributed.process_local_batch`). Process 0 prints the loss trajectory as
`MP_LOSSES <json>`.

This is the code path the reference never had (its trainer dropped dataset
chunks on the floor, pkg/rpc/trainer/server/server.go:59): data parallelism
across hosts over DCN/Gloo, tensor parallelism inside a host — the same jit
and shardings as single-process training; only initialization and batch
feeding differ.
"""

from __future__ import annotations

import json
import os


def main() -> None:
    from dragonfly2_tpu.parallel import distributed as dist

    cfg = dist.DistributedConfig.from_env()
    dist.initialize(cfg)

    import jax
    import numpy as np

    from dragonfly2_tpu.parallel import mesh as meshlib
    from dragonfly2_tpu.trainer import synthetic, train_gnn
    from dragonfly2_tpu.trainer.synthetic import PairBatch

    steps = int(os.environ.get("DF_MP_STEPS", "12"))
    num_nodes = int(os.environ.get("DF_MP_NODES", "128"))
    mesh = meshlib.make_mesh()  # all processes' devices → global mesh
    cluster = synthetic.make_cluster(
        num_nodes=num_nodes, num_neighbors=8, num_pairs=4096, seed=3
    )
    tcfg = train_gnn.GNNTrainConfig(
        hidden=32,
        embed_dim=16,
        num_layers=2,
        batch_size=meshlib.pad_to_multiple(256, mesh.shape[meshlib.DATA_AXIS]),
        warmup_steps=2,
    )
    state = train_gnn.init_state(tcfg, cluster.graph, rng_seed=0)
    state, g, step_fn = train_gnn.shard_for_training(state, cluster.graph, mesh)

    batch_sh = meshlib.batch_sharding(mesh)
    lo, hi = dist.local_row_slice(tcfg.batch_size)
    rng = np.random.default_rng(0)  # same seed everywhere → same global batch
    losses: list[float] = []
    for _ in range(steps):
        b = synthetic.sample_batch(cluster.pairs, tcfg.batch_size, rng)
        gb = PairBatch(
            *(
                dist.process_local_batch(batch_sh, a[lo:hi], (tcfg.batch_size,) + a.shape[1:])
                for a in b
            )
        )
        state, loss = step_fn(state, g, gb)
        losses.append(float(loss))
    jax.block_until_ready(state.params)
    if jax.process_index() == 0:
        print(
            f"mp_train ok: procs={jax.process_count()} devices={len(jax.devices())} "
            f"mesh={dict(mesh.shape)} steps={steps}",
            flush=True,
        )
        print("MP_LOSSES " + json.dumps([round(v, 6) for v in losses]), flush=True)


if __name__ == "__main__":
    main()
