"""Discrete-event engine driving the REAL control plane at 10^5+ peers.

One binary min-heap of (virtual time, seq, kind, payload) events; one
VirtualClock shared by the engine, the asyncio loop (sim.clockloop), and
every SchedulerService under simulation. Events are processed strictly in
heap order and each handler is awaited to completion before the next pops —
a handler that awaits a scheduler-side backoff advances virtual time through
the loop's timer heap, so retry pacing inside `schedule_candidate_parents`
costs simulated (not wall) time.

What is real and what is modeled:

  REAL     SchedulerService, Scheduling (filters, retry/backoff, DAG
           commits), MLEvaluator feature assembly + scoring, ResourcePool
           TTL GC, NetworkTopology probe ingest, FederationSync push-pull
           gossip, telemetry record emission — the exact objects production
           serves with, reached through the existing InProcessSchedulerClient
           over a consistent-hash ring (the balancer's placement semantics).
  MODELED  the data plane: a piece transfer is a completion-time computed
           from the synthetic topology (per-flow link caps, the parent's
           LIVE upload-slot occupancy read off the scheduler's own Host row,
           and the parent's own completion time for streaming children);
           origin fetches ride a per-region origin-rate model.

Known approximation (documented, deliberate): handlers are serialized, so N
peers backing off "concurrently" serialize their virtual backoffs instead of
overlapping them — control-plane latency under deep overload is pessimistic.
Events the clock has passed (a handler advanced time beyond a scheduled
event) execute tardily at the current now; the heap keeps order, time never
runs backward.
"""

from __future__ import annotations

import heapq
import time as _walltime
from dataclasses import dataclass, field
from typing import Any, Callable

from dragonfly2_tpu.scheduler.evaluator import new_evaluator
from dragonfly2_tpu.scheduler.resource import GCPolicy
from dragonfly2_tpu.scheduler.service import HostInfo, SchedulerService, TaskMeta
from dragonfly2_tpu.sim import metrics as sim_metrics
from dragonfly2_tpu.sim.clockloop import run_virtual
from dragonfly2_tpu.sim.topology import Placement, SyntheticTopology, TopologyConfig
from dragonfly2_tpu.sim.workload import TaskSpec, Workload, WorkloadConfig
from dragonfly2_tpu.utils.clock import VirtualClock


@dataclass
class SimConfig:
    schedulers: int = 1
    seed: int = 0
    topology: TopologyConfig = field(default_factory=TopologyConfig)
    workload: WorkloadConfig = field(default_factory=WorkloadConfig)
    evaluator: str = "ml"  # the real MLEvaluator (base-fallback until a model attaches)
    # ---- scoring plane (ISSUE 18: the native round driver at sim scale) ----
    # "base": no model attached — the evaluator serves its numpy fallback
    #         (HEAD behavior; the placement-quality scenario checks assume it).
    # "ml-serial": a synthetic native scorer attaches and every round scores
    #         through the pre-ISSUE-18 per-round Python loop.
    # "ml-native": same model, but rounds ride df_round_drive — the A/B twin
    #         proving the driver at 10^5-peer scale: placement is bit-exact vs
    #         ml-serial for the same seed, only sched_rounds_per_s moves.
    # Both ml modes degrade to "base" (with a warning) when the native
    # toolchain is unavailable — the sim never hard-requires g++.
    scoring: str = "base"
    telemetry_dir: str | None = None  # None: no record capture (pure control-plane run)
    telemetry_rotate_rows: int = 16384
    federation_interval_s: float = 2.0
    gc_interval_s: float = 0.0  # 0: no TTL sweeps scheduled
    gc_policy: GCPolicy | None = None
    sample_interval_s: float = 0.0  # timeseries sampling cadence (0: off)
    max_virtual_s: float = 24 * 3600.0
    drain_grace_s: float = 1800.0  # after the last arrival, let transfers finish
    register_retry_limit: int = 3  # sim-peer re-register attempts after empty rounds
    reschedule_limit: int = 2  # mid-transfer parent-loss recoveries per peer
    bucket_s: float = 10.0  # per-interval stats resolution
    stream_lag_s: float = 0.1  # child completes this long after a still-running parent
    # ---- graceful degradation under overload (ISSUE 17) ----
    # modeled scheduler service time per registration (0 = instant): requests
    # queue behind a per-scheduler busy horizon, and that backlog is exactly
    # what the REAL DegradationController's queue-depth probe reads
    register_cost_ms: float = 0.0
    # modeled client deadline: a register whose queue wait exceeds it "times
    # out" (the server still burns the service time on the dead request — the
    # storm amplifier admission control exists to cut) and retries later
    register_timeout_s: float = 0.0
    degradation: bool = False  # attach the real brownout ladder per scheduler
    degradation_queue_budget: float = 64.0
    degradation_sustain_s: float = 3.0
    degradation_cool_s: float = 10.0
    degradation_interval_s: float = 1.0
    overload_retry_limit: int = 20  # overloaded/timeout re-registers before giving up
    gray_uplink_frac: float = 0.03  # a gray parent serves at this uplink fraction
    # modeled manager plane: keepalive agents probing a manager that scenario
    # control events blackout()/restore(); 0 agents = plane off
    keepalive_agents: int = 0
    keepalive_interval_s: float = 20.0
    keepalive_horizon_s: float = 600.0
    # True: every agent's first keepalive fires at the SAME instant (a fleet
    # restarted by one deploy — the worst case for rejoin thundering herds);
    # False: initial phases staggered across one interval
    keepalive_sync_start: bool = False


@dataclass
class SimReport:
    scenario: str = ""
    peers: int = 0
    events: int = 0
    wall_s: float = 0.0
    virtual_s: float = 0.0
    events_per_sec: float = 0.0
    time_compression: float = 0.0
    registered: int = 0
    completed: int = 0
    failed: int = 0
    refused: int = 0
    back_to_source: int = 0
    reschedules: int = 0
    departed: int = 0
    crashed: int = 0
    # placement quality (scheduling-time, against the synthetic ground truth)
    rounds_with_parents: int = 0
    parents_assigned: int = 0
    same_region_frac: float = 0.0
    same_rack_frac: float = 0.0
    mean_parent_rtt_ms: float = 0.0
    # byte accounting
    origin_egress_bytes: dict[str, int] = field(default_factory=dict)
    p2p_bytes: int = 0
    cross_region_bytes: int = 0
    fairness_jain: float = 0.0
    departed_parent_rounds: int = 0
    federation: dict[str, Any] = field(default_factory=dict)
    per_scheduler: list[dict] = field(default_factory=list)
    gc_removed: dict[str, int] = field(default_factory=dict)
    buckets: list[dict] = field(default_factory=list)
    dataset: dict[str, Any] | None = None
    # overload / degradation plane (ISSUE 17)
    overload_refused: int = 0  # typed `overloaded` answers received
    overload_retries: int = 0  # re-registers scheduled after refusal/timeout
    register_timeouts: int = 0  # modeled client-deadline expiries in queue
    admitted_p50_ms: float = 0.0  # arrival -> successful admission latency
    admitted_p99_ms: float = 0.0
    shed_by_class: dict[str, int] = field(default_factory=dict)
    gray_peers: int = 0
    degradation: dict[str, Any] = field(default_factory=dict)
    manager: dict[str, Any] = field(default_factory=dict)
    # scoring plane (ISSUE 18): which scoring mode actually served (may read
    # "base" after an ml-* request degraded for lack of a toolchain), rounds
    # through schedule_candidate_parents across all schedulers, seconds spent
    # inside them (local schedule_duration histograms), and the quotient —
    # the sim-scale scheduler rounds/s the native driver is accountable to
    scoring: str = "base"
    sched_rounds: int = 0
    sched_s: float = 0.0
    sched_rounds_per_s: float = 0.0
    native_rounds: int = 0
    # ISSUE 19: rounds the mirrored peer table drove (cached-row fast path /
    # stale-revalidated — both run sample+filter+score in C and count toward
    # native_rounds coverage) and the full-export counter, which must stay at
    # 1 per scheduler (the attach): steady state is deltas or it's a bug
    mirror_rounds: int = 0
    mirror_stale_rounds: int = 0
    mirror_full_syncs: int = 0


class _SimPeer:
    __slots__ = (
        "index", "peer_id", "host_id", "placement", "task", "host_info",
        "state", "parents", "rate_bps", "attempts", "reschedules",
        "alive", "crashed_flag", "probe_targets", "probes_left", "finish_at",
        "priority", "gray", "arrived_at", "overload_attempts",
    )

    def __init__(self, index: int, task: TaskSpec, placement: Placement):
        self.index = index
        self.peer_id = f"sim-p{index:07d}"
        self.host_id = f"sim-h{index:07d}"
        self.placement = placement
        self.task = task
        self.host_info = HostInfo(
            id=self.host_id,
            ip=f"10.{(index >> 16) & 255}.{(index >> 8) & 255}.{index & 255}",
            hostname=f"sim-{index}",
            download_port=18000 + (index % 40000),
            idc=placement.idc,
            location=placement.location,
        )
        self.state = "arriving"
        self.parents: list = []
        self.rate_bps = 0.0
        self.attempts = 0
        self.reschedules = 0
        self.alive = True
        self.crashed_flag = False
        self.probe_targets: list = []
        self.probes_left = 0
        self.finish_at = 0.0
        self.priority = 1.0  # traffic-shaper class (admission sheds lowest first)
        self.gray = False  # gray parent: uplink capped at gray_uplink_frac
        self.arrived_at = 0.0  # set on arrival; -1 once admission latency counted
        self.overload_attempts = 0


class _KeepaliveAgent:
    """One modeled manager-link client (a daemon or scheduler keepalive loop).

    Carries exactly the attributes ManagerLink._rejoin_delay reads (hostname,
    keepalive_interval), so the rejoin spread the blackout scenarios measure
    is the PRODUCTION jitter function, not a sim reimplementation."""

    __slots__ = ("hostname", "keepalive_interval", "failures", "unreachable")

    def __init__(self, index: int, interval: float):
        self.hostname = f"sim-agent-{index:05d}"
        self.keepalive_interval = interval
        self.failures = 0
        self.unreachable = False


class _LoopbackFederationClient:
    """federation_sync straight into a peer SchedulerService — no sockets.
    Partition state lives on the simulation: a severed pair raises the same
    ConnectionError a blackholed wire peer would."""

    def __init__(self, sim: "Simulation", src: str, dst: str):
        self._sim = sim
        self._src = src
        self._dst = dst

    async def federation_sync(self, origin: str, **kw):
        if self._sim.is_partitioned(self._src, self._dst):
            raise ConnectionError(f"simulated partition {self._src} <-> {self._dst}")
        return self._sim.services[self._dst].federation_sync(origin, **kw)

    async def close(self):
        return None


class Simulation:
    """One configured simulation run: cluster + workload + event heap."""

    def __init__(self, config: SimConfig | None = None, *, scenario: str = ""):
        from dragonfly2_tpu.daemon.engine import InProcessSchedulerClient
        from dragonfly2_tpu.rpc.balancer import ConsistentHashRing
        from dragonfly2_tpu.scheduler.federation import FederationSync

        self.config = config or SimConfig()
        self.scenario = scenario
        self.clock = VirtualClock()
        self.topology = SyntheticTopology(self.config.topology, seed=self.config.seed)
        self.workload = Workload(self.config.workload, seed=self.config.seed + 1)

        # ---- the real cluster, in-process ----
        self.names = [f"sim-sch-{i}" for i in range(max(1, self.config.schedulers))]
        self.services: dict[str, SchedulerService] = {}
        self._telemetry = {}
        self._scoring = self.config.scoring
        self._scorers: list[Any] = []  # native handles to close after run()
        self._scorer_artifact: str | None = None
        if self._scoring not in ("base", "ml-serial", "ml-native"):
            raise ValueError(f"unknown scoring mode {self._scoring!r}")
        if self._scoring != "base":
            import tempfile

            self._scorer_artifact = _synthetic_scorer_artifact(
                tempfile.mktemp(prefix="dfsim-scorer-", suffix=".dfsc"),
                seed=self.config.seed,
            )
            # one model load for the whole cluster: each service attaches a
            # fork (shared weights, private handle). A missing toolchain
            # degrades the RUN to base scoring here — before any service is
            # built — so every member sees the same round_driver config.
            try:
                from dragonfly2_tpu.native import NativeScorer

                self._scorers.append(NativeScorer(self._scorer_artifact))
            except Exception as e:  # noqa: BLE001 — no g++: degrade, honestly
                import logging

                logging.getLogger(__name__).warning(
                    "sim scoring %s degraded to base (%r)", self._scoring, e
                )
                self._scoring = "base"
        for i, name in enumerate(self.names):
            telemetry = None
            if self.config.telemetry_dir is not None:
                from dragonfly2_tpu.telemetry import TelemetryStorage

                telemetry = TelemetryStorage(
                    f"{self.config.telemetry_dir}/{name}",
                    rotate_rows=self.config.telemetry_rotate_rows,
                )
                self._telemetry[name] = telemetry
            import random as _random

            svc = SchedulerService(
                evaluator=new_evaluator(self.config.evaluator),
                telemetry=telemetry,
                gc_policy=self.config.gc_policy,
                clock=self.clock,
                scheduling_config=self._scheduling_config(),
                # seeded per member: probe-target draws (and so the probe
                # telemetry and the bridged dataset) replay bit-identically
                # for a given SimConfig.seed
                topology_rng=_random.Random(self.config.seed * 1009 + i),
            )
            # One peer per simulated host and every (parent, child-host) pair
            # is scheduled at most once, so the evaluator's pair-row cache can
            # only cost memory here (O(rounds × candidates) rows at 10^5
            # peers, measured ~1 GB) — disable it; the static-row cache stays.
            svc.evaluator.feature_builder = _uncached_pair_features
            self._attach_sim_scorer(svc)
            self.services[name] = svc
        self.ring = ConsistentHashRing(self.names)
        self.clients = {
            name: InProcessSchedulerClient(svc) for name, svc in self.services.items()
        }
        self.federation: dict[str, Any] = {}
        if len(self.names) > 1:
            for name in self.names:
                self.federation[name] = FederationSync(
                    self.services[name],
                    self_addr=name,
                    name=name,
                    peers=[n for n in self.names if n != name],
                    client_factory=lambda addr, src=name: _LoopbackFederationClient(
                        self, src, addr
                    ),
                )
        self._severed: set[frozenset] = set()

        # ---- overload / degradation plane (ISSUE 17) ----
        import random as _random

        self._rng = _random.Random(self.config.seed + 2)  # retry jitter draws
        self._busy_until: dict[str, float] = {n: 0.0 for n in self.names}
        self._admit_waits: list[float] = []
        self._deg_max = 0
        self.degradation_controllers: dict[str, Any] = {}
        if self.config.degradation:
            from dragonfly2_tpu.scheduler.degradation import DegradationController

            # the REAL ladder, fed by the MODELED register backlog: depth =
            # queued registrations behind this scheduler's busy horizon
            cost_s = max(self.config.register_cost_ms, 0.001) / 1000.0
            for name in self.names:
                ctrl = DegradationController(
                    queue_depth=lambda n=name: max(
                        0.0, (self._busy_until[n] - self.clock.monotonic()) / cost_s
                    ),
                    queue_budget=self.config.degradation_queue_budget,
                    sustain_s=self.config.degradation_sustain_s,
                    cool_s=self.config.degradation_cool_s,
                )
                self.services[name].attach_degradation(ctrl)
                self.degradation_controllers[name] = ctrl
        # modeled manager plane (blackout scenarios)
        self.manager_down = False
        self._agents: list[_KeepaliveAgent] = []
        self._mgr_stats = {"unreachable_declared": 0, "recovered": 0, "rejoined": 0}

        # ---- event heap + run state ----
        self._heap: list[tuple[float, int, str, Any]] = []
        self._seq = 0
        self._pending_work = 0  # non-periodic events in the heap (O(1) drain check)
        self._last_arrival_s = 0.0
        self.report = SimReport(scenario=scenario)
        self._peers: list[_SimPeer] = []
        self._peers_by_pid: dict[str, _SimPeer] = {}
        self._placements: dict[str, Placement] = {}
        self._departed_pids: set[str] = set()
        self._live = 0
        self._rtt_sum = 0.0
        self._same_region = 0
        self._same_rack = 0
        self._buckets: dict[int, dict] = {}
        self._fed_history: list[dict] = []
        self._recorder = None
        if self.config.sample_interval_s > 0:
            from dragonfly2_tpu.observability.timeseries import MetricsRecorder

            # fresh recorder (not the process default): only this run's
            # samples land in it, stamped with VIRTUAL wall time so scenario
            # assertions are windowed-rate queries in simulated time
            self._recorder = MetricsRecorder(interval=self.config.sample_interval_s)

    # ---- public control surface (scenarios schedule through these) ----

    @property
    def recorder(self):
        return self._recorder

    def at(self, t_s: float, fn: Callable[[], Any]) -> None:
        """Run `fn` (sync or async) at virtual time t — scenario control
        events (partition, heal, parameter flips)."""
        self._push(t_s, "control", fn)

    def partition(self, a: str, b: str) -> None:
        self._severed.add(frozenset((a, b)))

    def heal(self, a: str, b: str) -> None:
        self._severed.discard(frozenset((a, b)))

    def blackout(self) -> None:
        """Take the modeled manager down (keepalive agents start failing)."""
        self.manager_down = True

    def restore(self) -> None:
        self.manager_down = False

    def is_partitioned(self, a: str, b: str) -> bool:
        return frozenset((a, b)) in self._severed

    def preseed(self, task: TaskSpec, region: str, count: int = 1) -> None:
        """Announce `count` completed seed holders of `task` in `region`
        (the dfcache-import / crash-rejoin announce path, no transfer)."""
        for _ in range(count):
            sp = self._new_peer(task, region=region)
            sp.state = "seeded"
            client = self._for_task(task.task_id)
            self._run_sync(
                client.announce_task(
                    sp.peer_id,
                    TaskMeta(task.task_id, task.url),
                    sp.host_info,
                    content_length=task.content_length,
                    piece_size=task.piece_size,
                    piece_indices=list(range(task.total_pieces)),
                )
            )

    @staticmethod
    def _run_sync(coro) -> Any:
        """Drive an InProcess-client coroutine that never truly suspends
        (announce/report verbs) without an event loop — preseeding happens
        before run()."""
        try:
            coro.send(None)
        except StopIteration as stop:
            return stop.value
        raise RuntimeError("coroutine suspended outside the simulation loop")

    # ---- internals ----

    def _push(self, t_s: float, kind: str, payload: Any) -> None:
        self._seq += 1
        if kind not in self._PERIODIC:
            self._pending_work += 1
        heapq.heappush(self._heap, (t_s, self._seq, kind, payload))

    def _for_task(self, task_id: str):
        return self.clients[self.ring.pick(task_id)]

    def _scheduling_config(self):
        """round_driver pins the A/B leg: ml-native routes the sim's async
        rounds through df_round_drive one-round batches, ml-serial pins the
        per-round Python loop on the SAME attached model. Everything else
        (filters, rng, retries) is the shared default config."""
        from dragonfly2_tpu.scheduler.scheduling import SchedulingConfig

        if self._scoring == "ml-native":
            return SchedulingConfig(round_driver="native")
        if self._scoring == "ml-serial":
            return SchedulingConfig(round_driver="serial")
        return None

    def _attach_sim_scorer(self, svc: SchedulerService) -> None:
        """Attach a fork of the synthetic native model (ml-* scoring modes):
        shared weights, one handle per service."""
        if self._scoring == "base" or not self._scorers:
            return
        scorer = self._scorers[0].fork()
        self._scorers.append(scorer)
        svc.evaluator.attach_scorer(
            scorer, _ModNodeIndex(scorer.num_nodes), version="sim-synthetic"
        )
        if self._scoring == "ml-native":
            # ISSUE 19: the native leg rides the mirrored peer table — the
            # sim's registration/departure churn streams deltas through the
            # resource-pool hooks, and rounds sample+filter natively. Row
            # caching stays cold here (each (parent, child-host) pair is
            # scheduled at most once AND the uncached builder is active, so
            # the stale leg's serial scoring is the steady state) — the win
            # is the snapshot/sample/filter leg leaving Python.
            svc.enable_native_mirror()

    def _for_host(self, host_id: str):
        return self.clients[self.ring.pick(host_id)]

    def _new_peer(self, task: TaskSpec, region: str | None = None) -> _SimPeer:
        placement = self.topology.place(region)
        sp = _SimPeer(len(self._peers), task, placement)
        sp.priority = self.workload.draw_priority()
        sp.gray = self.workload.is_gray()
        if sp.gray:
            self.report.gray_peers += 1
        self._peers.append(sp)
        self._peers_by_pid[sp.peer_id] = sp
        self._placements[sp.host_id] = placement
        return sp

    def _bucket(self) -> dict:
        b = int(self.clock.monotonic() // self.config.bucket_s)
        d = self._buckets.get(b)
        if d is None:
            d = self._buckets[b] = {
                "t_s": b * self.config.bucket_s,
                "arrivals": 0, "rounds": 0, "parents": 0, "same_region": 0,
                "completions": 0, "back_to_source": 0,
                "origin_bytes": 0, "p2p_bytes": 0,
                "refused_overload": 0, "keepalives": 0, "rejoins": 0,
            }
        return d

    # ---- event handlers ----

    async def _on_arrival(self, sp: _SimPeer) -> None:
        self._live += 1
        sim_metrics.SIM_PEERS.set(float(self._live))
        self._bucket()["arrivals"] += 1
        sp.arrived_at = self.clock.monotonic()
        # the daemon keepalive's host announce, to the host's ring owner:
        # probe rounds route there (federation shards probe ingest by source
        # host), so that member must know the host to hand out targets
        await self._for_host(sp.host_id).announce_host(sp.host_info)
        await self._register(sp)

    async def _register(self, sp: _SimPeer) -> None:
        rep = self.report
        cfg = self.config
        task = sp.task
        now = self.clock.monotonic()
        name = self.ring.pick(task.task_id)
        client = self.clients[name]
        # modeled service time (ISSUE 17): registrations queue behind this
        # scheduler's busy horizon; the degradation controller's queue-depth
        # probe reads exactly this backlog
        cost_s = cfg.register_cost_ms / 1000.0
        wait = 0.0
        if cost_s > 0:
            wait = max(0.0, self._busy_until[name] - now)
            if cfg.register_timeout_s > 0 and wait > cfg.register_timeout_s:
                # the client's deadline expired in queue. The server still
                # burns service time on the dead request — UNLESS the ladder's
                # admission rung is up, in which case the request gets the
                # cheap typed shed answer instead of full processing. This is
                # the retry-storm amplifier the brownout ladder exists to cut.
                deg = self.services[name].degradation
                cheap = deg is not None and deg.admission_control
                self._busy_until[name] = now + wait + cost_s * (0.1 if cheap else 1.0)
                rep.register_timeouts += 1
                self._requeue_register(
                    sp, now + cfg.register_timeout_s * (1.0 + self._rng.random())
                )
                return
        res = await client.register_peer(
            sp.peer_id,
            TaskMeta(task.task_id, task.url, priority=sp.priority),
            sp.host_info,
        )
        rep.registered += 1
        if res.error == "overloaded":
            # the typed brownout answer: costs one priority compare server-
            # side; the retry_after_s hint schedules the comeback (jittered
            # UP only, like the real conductor's _register_admitted)
            if cost_s > 0:
                self._busy_until[name] = max(now, self._busy_until[name]) + cost_s * 0.1
            rep.overload_refused += 1
            cls = f"{sp.priority:g}"
            rep.shed_by_class[cls] = rep.shed_by_class.get(cls, 0) + 1
            self._bucket()["refused_overload"] += 1
            retry_after = max(float(getattr(res, "retry_after_s", 0.0)), 0.5)
            self._requeue_register(
                sp, now + retry_after * (1.0 + 0.5 * self._rng.random())
            )
            return
        if cost_s > 0:
            self._busy_until[name] = max(now, self._busy_until[name]) + cost_s
        if res.error:
            rep.refused += 1
            sp.state = "failed"
            return
        if sp.arrived_at >= 0:
            # first successful admission: arrival -> admitted latency, once
            self._admit_waits.append((now - sp.arrived_at) + wait + cost_s)
            sp.arrived_at = -1.0
        if res.back_to_source:
            sp.state = "origin"
            rep.back_to_source += 1
            self._bucket()["back_to_source"] += 1
            # the real daemon learns the length from the origin's first
            # response within ~one RTT; report it now so later registrations
            # see real task metadata (size scope, piece math)
            await client.report_task_metadata(
                task.task_id,
                content_length=task.content_length,
                piece_size=task.piece_size,
            )
            rate = self.topology.origin_rate_bps(sp.placement)
            sp.rate_bps = rate
            sp.finish_at = self.clock.monotonic() + task.content_length / rate
            self._push(sp.finish_at, "origin_done", sp)
            return
        if res.scope in ("empty", "tiny"):
            # content rode the register response itself; no transfer to model
            sp.state = "seeded"
            rep.completed += 1
            self._bucket()["completions"] += 1
            return
        if res.parents:
            self._note_placement(sp, res.parents)
            self._start_transfer(sp, res.parents)
            return
        # empty round (retries exhausted inside the scheduler): the real
        # daemon keeps the task alive and re-registers; cap the attempts
        sp.attempts += 1
        if sp.attempts <= self.config.register_retry_limit:
            self._push(self.clock.monotonic() + 2.0 * sp.attempts, "register", sp)
        else:
            sp.state = "failed"
            rep.failed += 1
            await client.report_peer_result(sp.peer_id, success=False)

    def _requeue_register(self, sp: _SimPeer, at_s: float) -> None:
        """Schedule a re-register after an overloaded answer or a modeled
        client timeout; gives up (peer failed) past overload_retry_limit."""
        sp.overload_attempts += 1
        if sp.overload_attempts > self.config.overload_retry_limit:
            sp.state = "failed"
            self.report.failed += 1
            return
        self.report.overload_retries += 1
        self._push(at_s, "register", sp)

    def _note_placement(self, sp: _SimPeer, parents: list) -> None:
        rep = self.report
        rep.rounds_with_parents += 1
        bucket = self._bucket()
        bucket["rounds"] += 1
        for pi in parents:
            if pi.peer_id in self._departed_pids:
                rep.departed_parent_rounds += 1
                sim_metrics.SIM_DEPARTED_PARENT_ROUNDS.inc()
            placement = self._placements.get(pi.host_id)
            if placement is None:
                continue
            rep.parents_assigned += 1
            bucket["parents"] += 1
            self._rtt_sum += self.topology.rtt_ms(sp.placement, placement)
            if placement.region == sp.placement.region:
                self._same_region += 1
                bucket["same_region"] += 1
                if placement.rack == sp.placement.rack:
                    self._same_rack += 1

    def _transfer_rate_bps(self, sp: _SimPeer, parents: list) -> float:
        """Aggregate child rate: per-parent flows capped by the path link
        and the parent's uplink divided by its LIVE upload-slot occupancy
        (read off the owning scheduler's Host row — the DAG itself models
        the contention), summed, then capped by the child downlink."""
        cfg = self.topology.config
        svc = self.services[self.ring.pick(sp.task.task_id)]
        total = 0.0
        for pi in parents:
            placement = self._placements.get(pi.host_id)
            if placement is None:
                continue
            host = svc.pool.hosts.get(pi.host_id)
            share = max(1, host.concurrent_uploads) if host is not None else 1
            # gray parent (ISSUE 17): alive and registered, but its uplink
            # serves at a crawl — the degradation the scheduler can only see
            # through bandwidth feedback, never through liveness
            parent_sp = self._peers_by_pid.get(pi.peer_id)
            uplink = cfg.uplink_bps * (
                self.config.gray_uplink_frac
                if parent_sp is not None and parent_sp.gray
                else 1.0
            )
            total += min(
                self.topology.link_bps(placement, sp.placement),
                uplink / share,
            )
        return min(cfg.downlink_bps, total) if total > 0 else cfg.downlink_bps * 0.01

    def _start_transfer(self, sp: _SimPeer, parents: list) -> None:
        sp.state = "downloading"
        sp.parents = list(parents)
        rate = self._transfer_rate_bps(sp, parents)
        sp.rate_bps = rate
        now = self.clock.monotonic()
        setup_s = max(
            (
                self.topology.rtt_ms(sp.placement, self._placements[pi.host_id])
                for pi in parents
                if pi.host_id in self._placements
            ),
            default=0.0,
        ) / 1000.0
        finish = now + setup_s + sp.task.content_length / rate
        # a still-downloading parent streams pieces as it lands them: the
        # child can finish only shortly after the slowest such parent does
        for pi in parents:
            parent_sp = self._peers_by_pid.get(pi.peer_id)
            if parent_sp is not None and parent_sp.state in ("downloading", "origin"):
                finish = max(finish, parent_sp.finish_at + self.config.stream_lag_s)
        sp.finish_at = finish
        self._push(finish, "transfer_done", sp)

    async def _finish_success(self, sp: _SimPeer, parent_ids: list[str]) -> None:
        task = sp.task
        client = self._for_task(task.task_id)
        pieces = task.total_pieces
        cost_ms = max(0.1, (task.content_length / sp.rate_bps) * 1000.0 / pieces)
        np_ = len(parent_ids)
        await client.report_pieces(
            sp.peer_id,
            [
                (i, cost_ms, parent_ids[i % np_] if np_ else "")
                for i in range(pieces)
            ],
        )
        await client.report_peer_result(
            sp.peer_id, success=True, bandwidth_bps=sp.rate_bps
        )
        sp.state = "seeded"
        self.report.completed += 1
        self._bucket()["completions"] += 1
        self._schedule_after_download(sp)

    async def _on_transfer_done(self, sp: _SimPeer) -> None:
        if not sp.alive:
            return
        dead = [
            pi for pi in sp.parents
            if (p := self._peers_by_pid.get(pi.peer_id)) is not None and not p.alive
        ]
        if dead and sp.reschedules < self.config.reschedule_limit:
            # parents died mid-transfer: report the failures (drives
            # block_parents) and run a real reschedule round
            sp.reschedules += 1
            self.report.reschedules += 1
            client = self._for_task(sp.task.task_id)
            for pi in dead:
                await client.report_piece_result(  # dflint: disable=DF025 the REAL conductor reports failures unary+promptly (PR 5 rule: failures never ride a batch); ≤4 in-process calls
                    sp.peer_id, 0, success=False, parent_id=pi.peer_id
                )
            res = await client.reschedule(sp.peer_id)
            if res.back_to_source:
                self.report.back_to_source += 1
                rate = self.topology.origin_rate_bps(sp.placement)
                sp.rate_bps = rate
                # roughly half the task survived the dead parents
                sp.finish_at = self.clock.monotonic() + 0.5 * sp.task.content_length / rate
                self._push(sp.finish_at, "origin_done", sp)
                sp.state = "origin"
                return
            if res.parents:
                self._note_placement(sp, res.parents)
                self._start_transfer(sp, res.parents)
                return
            sp.state = "failed"
            self.report.failed += 1
            await client.report_peer_result(sp.peer_id, success=False)
            return
        parent_ids = [pi.peer_id for pi in sp.parents if pi.peer_id not in
                      {d.peer_id for d in dead}] or [pi.peer_id for pi in sp.parents]
        nbytes = sp.task.content_length
        self.report.p2p_bytes += nbytes
        bucket = self._bucket()
        bucket["p2p_bytes"] += nbytes
        for pi in sp.parents:
            placement = self._placements.get(pi.host_id)
            if placement is not None and placement.region != sp.placement.region:
                self.report.cross_region_bytes += nbytes // max(1, len(sp.parents))
        await self._finish_success(sp, parent_ids)

    async def _on_origin_done(self, sp: _SimPeer) -> None:
        if not sp.alive:
            return
        nbytes = sp.task.content_length
        region = sp.placement.region
        self.report.origin_egress_bytes[region] = (
            self.report.origin_egress_bytes.get(region, 0) + nbytes
        )
        sim_metrics.SIM_ORIGIN_EGRESS_BYTES.inc(float(nbytes), region=region)
        self._bucket()["origin_bytes"] += nbytes
        await self._finish_success(sp, [])

    def _schedule_after_download(self, sp: _SimPeer) -> None:
        now = self.clock.monotonic()
        if self.workload.runs_probes():
            sp.probes_left = self.config.workload.probe_rounds
            self._push(now + 0.5, "probe", sp)
        lifetime = self.workload.lifetime_s()
        if lifetime is not None:
            self._push(now + lifetime, "depart", sp)

    async def _on_probe(self, sp: _SimPeer) -> None:
        if not sp.alive:
            return
        results = [
            {
                "dst_host_id": host_id,
                "rtt_ms": self.topology.rtt_ms(sp.placement, self._placements[host_id]),
                "success": True,
            }
            for host_id in sp.probe_targets
            if host_id in self._placements
        ]
        if results:
            sp.probes_left -= 1  # the first call only FETCHES targets
        client = self._for_host(sp.host_id)
        targets = await client.sync_probes(sp.host_id, results)
        sp.probe_targets = [t["host_id"] for t in targets]
        if sp.probes_left > 0 and sp.probe_targets:
            self._push(
                self.clock.monotonic() + self.config.workload.probe_interval_s,
                "probe", sp,
            )

    async def _on_depart(self, sp: _SimPeer) -> None:
        if not sp.alive:
            return
        sp.alive = False
        self._live -= 1
        sim_metrics.SIM_PEERS.set(float(self._live))
        if self.workload.departure_is_crash():
            # crash: no goodbye — the scheduler keeps a ghost row until
            # supersede/TTL GC (the restart suite's resurrection semantics)
            sp.crashed_flag = True
            self.report.crashed += 1
            return
        self.report.departed += 1
        self._departed_pids.add(sp.peer_id)
        client = self._for_task(sp.task.task_id)
        await client.leave_peer(sp.peer_id)
        for c in self.clients.values():
            await c.leave_host(sp.host_id)  # dflint: disable=DF025 broadcast to every ring member (each may hold rows for this host); in-process, N≤schedulers

    async def _on_fed_sync(self, _payload) -> None:
        ok = failed = 0
        for fed in self.federation.values():
            await fed.sync_once()
            ok += fed.syncs_ok
            failed += fed.syncs_failed
        self._fed_history.append(
            {
                "t_s": round(self.clock.monotonic(), 3),
                "remote_edges": [
                    self.services[n].topology.remote_edge_count() for n in self.names
                ],
                "syncs_ok": ok,
                "syncs_failed": failed,
            }
        )
        if self._heap_has_work():
            self._push(
                self.clock.monotonic() + self.config.federation_interval_s,
                "fed_sync", None,
            )

    async def _on_gc(self, _payload) -> None:
        for svc in self.services.values():
            removed = svc.pool.gc()
            for k, v in removed.items():
                self.report.gc_removed[k] = self.report.gc_removed.get(k, 0) + v
        if self._heap_has_work():
            self._push(self.clock.monotonic() + self.config.gc_interval_s, "gc", None)

    async def _on_sample(self, _payload) -> None:
        if self._recorder is not None:
            self._recorder.sample_once(now=self.clock.time())
            if self._heap_has_work():
                self._push(
                    self.clock.monotonic() + self.config.sample_interval_s,
                    "sample", None,
                )

    async def _on_degrade(self, _payload) -> None:
        """One hysteresis tick on every attached brownout ladder — keeps
        ticking past heap drain until every ladder is back at level 0, so a
        run never ends with shedding still engaged but unevaluated."""
        lvl = 0
        now = self.clock.monotonic()
        for ctrl in self.degradation_controllers.values():
            lvl = max(lvl, ctrl.evaluate_once(now=now))
        self._deg_max = max(self._deg_max, lvl)
        if self._recorder is not None:
            # the periodic "sample" tick stops when the workload drains, but
            # the ladder may still be stepping down — sample here too so the
            # alert engine sees the gauge reach 0, not its last loaded value
            self._recorder.sample_once(now=self.clock.time())
        if self._heap_has_work() or lvl > 0:
            self._push(now + self.config.degradation_interval_s, "degrade", None)

    async def _on_keepalive(self, agent: _KeepaliveAgent) -> None:
        now = self.clock.monotonic()
        self._bucket()["keepalives"] += 1
        next_at = now + agent.keepalive_interval
        if self.manager_down:
            agent.failures += 1
            # the real threshold (ManagerLink.keepalive_once): one blip is
            # not a blackout, two consecutive failures are
            if agent.failures >= 2 and not agent.unreachable:
                agent.unreachable = True
                self._mgr_stats["unreachable_declared"] += 1
        else:
            if agent.unreachable:
                agent.unreachable = False
                agent.failures = 0
                self._mgr_stats["recovered"] += 1
                # recovery catch-up after the PRODUCTION jitter function —
                # deterministic per-host spread across the keepalive
                # interval. The rejoin replaces this agent's next keepalive
                # slot, exactly like the inline await in keepalive_once.
                from dragonfly2_tpu.scheduler.manager_link import ManagerLink

                delay = ManagerLink._rejoin_delay(agent)
                self._push(now + delay, "rejoin", agent)
                next_at = now + delay + agent.keepalive_interval
            else:
                agent.failures = 0
        if next_at <= self.config.keepalive_horizon_s:
            self._push(next_at, "keepalive", agent)

    async def _on_rejoin(self, agent: _KeepaliveAgent) -> None:
        self._bucket()["rejoins"] += 1
        self._mgr_stats["rejoined"] += 1

    async def _on_control(self, fn: Callable[[], Any]) -> None:
        out = fn()
        if hasattr(out, "__await__"):
            await out

    # ---- the loop ----

    _PERIODIC = ("fed_sync", "gc", "sample", "degrade")

    def _heap_has_work(self) -> bool:
        """True while any non-periodic event remains — periodic ticks
        reschedule themselves only then, so the heap drains when the
        workload does instead of ticking to max_virtual_s forever."""
        return self._pending_work > 0

    async def _run(self) -> None:
        handlers = {
            "arrival": self._on_arrival,
            "register": self._register,
            "transfer_done": self._on_transfer_done,
            "origin_done": self._on_origin_done,
            "probe": self._on_probe,
            "depart": self._on_depart,
            "fed_sync": self._on_fed_sync,
            "gc": self._on_gc,
            "sample": self._on_sample,
            "degrade": self._on_degrade,
            "keepalive": self._on_keepalive,
            "rejoin": self._on_rejoin,
            "control": self._on_control,
        }
        inc = sim_metrics.SIM_EVENTS_TOTAL.inc
        cfg = self.config
        heap = self._heap
        periodic = self._PERIODIC
        while heap:
            t, _seq, kind, payload = heapq.heappop(heap)
            if kind not in periodic:
                self._pending_work -= 1
            if t > cfg.max_virtual_s:
                break
            if t > self._last_arrival_s + cfg.drain_grace_s and not self._heap_has_work():
                break  # straggler churn past the grace window: stop waiting
            self.clock.advance_to(t)
            self.report.events += 1
            inc(kind=kind)
            await handlers[kind](payload)

    def run(self) -> SimReport:
        cfg = self.config
        arrivals = self.workload.arrivals()
        for a in arrivals:
            sp = self._new_peer(a.task, region=a.region)
            self._push(a.at_s, "arrival", sp)
        self._last_arrival_s = arrivals[-1].at_s if arrivals else 0.0
        if self.federation:
            self._push(cfg.federation_interval_s, "fed_sync", None)
        if cfg.gc_interval_s > 0:
            self._push(cfg.gc_interval_s, "gc", None)
        if self._recorder is not None:
            self._push(0.0, "sample", None)
        if self.degradation_controllers:
            self._push(cfg.degradation_interval_s, "degrade", None)
        if cfg.keepalive_agents > 0:
            # initial phases staggered across one interval (daemons start at
            # different times) — steady-state keepalive load is uniform
            self._agents = [
                _KeepaliveAgent(i, cfg.keepalive_interval_s)
                for i in range(cfg.keepalive_agents)
            ]
            for i, agent in enumerate(self._agents):
                first = (
                    cfg.keepalive_interval_s
                    if cfg.keepalive_sync_start
                    else (i + 1) * cfg.keepalive_interval_s / cfg.keepalive_agents
                )
                self._push(first, "keepalive", agent)

        from dragonfly2_tpu.observability.tracing import default_tracer

        # head-sampling OFF for the run (restored after): the in-process
        # default tracer samples at 1.0, and recording a span per simulated
        # scheduling round measurably taxes the event loop at 10^5 peers
        tracer = default_tracer()
        prev_rate = tracer.sample_rate
        tracer.sample_rate = 0.0
        t0 = _walltime.perf_counter()  # dflint: disable=DF029 the honest wall-time events/s meter — never feeds event ordering
        try:
            run_virtual(self._run(), self.clock)
        finally:
            tracer.sample_rate = prev_rate
        wall = _walltime.perf_counter() - t0  # dflint: disable=DF029 same meter

        rep = self.report
        # scoring plane (ISSUE 18): rounds + seconds off each service's
        # PRIVATE schedule_duration histogram (wall time inside scheduling,
        # this run's services only — the global family would mix in other
        # sims of the process)
        rep.scoring = self._scoring
        sched_child = [
            svc.local_metrics.schedule_duration.labels()
            for svc in self.services.values()
        ]
        rep.sched_rounds = int(sum(c.count for c in sched_child))
        rep.sched_s = round(sum(c.total for c in sched_child), 3)
        if rep.sched_s > 0:
            rep.sched_rounds_per_s = round(rep.sched_rounds / rep.sched_s, 1)
        for svc in self.services.values():
            sched = svc.scheduling
            rep.mirror_rounds += sched.mirror_rounds_served
            rep.mirror_stale_rounds += sched.mirror_stale_rounds
            rep.native_rounds += (
                sched.native_rounds_served
                + sched.mirror_rounds_served
                + sched.mirror_stale_rounds
            )
            client = sched._mirror
            if client is not None and client.ready:
                try:
                    rep.mirror_full_syncs += int(client.stats()["full_syncs"])
                except Exception:  # noqa: BLE001  # dflint: disable=DF031 teardown best-effort: a stats read must not clobber the finished report
                    pass
        for scorer in self._scorers:
            try:
                scorer.close()
            except Exception:  # noqa: BLE001  # dflint: disable=DF031 teardown best-effort: a failed scorer close must not clobber the finished report
                pass
        self._scorers.clear()
        rep.peers = len(self._peers)
        rep.wall_s = round(wall, 3)
        rep.virtual_s = round(self.clock.monotonic(), 3)
        rep.events_per_sec = round(rep.events / wall, 1) if wall > 0 else 0.0
        rep.time_compression = round(rep.virtual_s / wall, 1) if wall > 0 else 0.0
        if rep.parents_assigned:
            rep.same_region_frac = round(self._same_region / rep.parents_assigned, 4)
            rep.same_rack_frac = round(self._same_rack / rep.parents_assigned, 4)
            rep.mean_parent_rtt_ms = round(self._rtt_sum / rep.parents_assigned, 3)
        rep.fairness_jain = round(self._jain_fairness(), 4)
        rep.per_scheduler = [self.services[n].federation_state() for n in self.names]
        if self._fed_history:
            rep.federation = {
                "syncs_ok": self._fed_history[-1]["syncs_ok"],
                "syncs_failed": self._fed_history[-1]["syncs_failed"],
                "first_remote_edge_s": self._first_remote_edge_s(),
                "history": self._fed_history,
            }
        if self._admit_waits:
            ws = sorted(self._admit_waits)
            rep.admitted_p50_ms = round(ws[len(ws) // 2] * 1e3, 2)
            rep.admitted_p99_ms = round(
                ws[min(len(ws) - 1, int(0.99 * len(ws)))] * 1e3, 2
            )
        if self.degradation_controllers:
            rep.degradation = {
                "max_level": self._deg_max,
                "final_level": max(
                    c.level for c in self.degradation_controllers.values()
                ),
                "per_scheduler": {
                    n: c.stats() for n, c in self.degradation_controllers.items()
                },
            }
        if self._agents:
            rep.manager = dict(self._mgr_stats)
            rep.manager["agents"] = len(self._agents)
        rep.buckets = [self._buckets[k] for k in sorted(self._buckets)]
        return rep

    def _jain_fairness(self) -> float:
        """Jain index over per-host upload counts (served parents only):
        1.0 = perfectly even fan-out, 1/n = one parent served everything."""
        counts = [
            h.upload_count
            for svc in self.services.values()
            for h in svc.pool.hosts.values()
            if h.upload_count > 0
        ]
        if not counts:
            return 0.0
        return (sum(counts) ** 2) / (len(counts) * sum(c * c for c in counts))

    def _first_remote_edge_s(self) -> float | None:
        for row in self._fed_history:
            if all(c > 0 for c in row["remote_edges"]):
                return row["t_s"]
        return None

    # ---- telemetry bridge (ISSUE 14: simulated traffic -> the ML plane) ----

    def build_dataset(self, *, max_neighbors: int = 16) -> dict[str, Any]:
        """Feed every scheduler's captured download/probe records through the
        EXISTING DatasetAccumulator ingest and finalize a Dataset — the same
        path the announcer->trainer pipeline drives with production traffic.
        Returns {nodes, edges, pairs, download_rows, probe_rows}; the Dataset
        itself is under the "dataset" key for callers that train on it."""
        from dragonfly2_tpu.trainer.dataset import DatasetAccumulator

        acc = DatasetAccumulator()
        download_rows = probe_rows = 0
        for name in self.names:
            telemetry = self._telemetry.get(name)
            if telemetry is None:
                continue
            downloads, _files = telemetry.downloads.snapshot()
            probes, _pfiles = telemetry.probes.snapshot()
            if len(downloads):
                download_rows += acc.add_downloads(downloads)
            if len(probes):
                probe_rows += acc.add_probes(probes)
        dataset = acc.finalize(max_neighbors=max_neighbors)
        out = {
            "nodes": dataset.num_nodes,
            "edges": int(acc.num_edges),
            "pairs": dataset.num_pairs,
            "download_rows": download_rows,
            "probe_rows": probe_rows,
            "dataset": dataset,
        }
        self.report.dataset = {k: v for k, v in out.items() if k != "dataset"}
        return out

    def close(self) -> None:
        for svc in self.services.values():
            svc.close()


def _uncached_pair_features(child, parents, topology=None, bandwidth=None):
    """build_pair_features without the per-parent pair-row cache writes —
    identical output (the cache is read-through), zero retained rows. The
    simulator schedules each (parent, child-host) pair at most once, so the
    cache can only cost memory at 10^5-peer scale."""
    from dragonfly2_tpu.scheduler.evaluator import _build_pair_features_rowwise

    return _build_pair_features_rowwise(child, parents, topology, bandwidth)


class _ModNodeIndex(dict):
    """node_index over the open-ended sim host population: any `sim-hNNNNNNN`
    id maps to NNNNNNN mod n_nodes (peer count is not known at service
    construction, and the evaluator only ever calls .get). Non-sim ids miss,
    exercising the unknown-host fallback exactly like production."""

    def __init__(self, n_nodes: int):
        super().__init__()
        self._n = n_nodes

    def __bool__(self):
        # truthy despite holding no materialized entries — ModelBundle
        # normalizes a falsy node_index to a plain empty dict
        return True

    def get(self, key, default=None):
        if isinstance(key, str) and key.startswith("sim-h"):
            try:
                return int(key[5:]) % self._n
            except ValueError:
                return default
        return default


def _synthetic_scorer_artifact(path: str, *, n_nodes: int = 256,
                               seed: int = 0) -> str:
    """A structurally valid scorer artifact with seeded random weights — the
    sim's scoring A/B measures ROUND-LOOP mechanics (serial Python loop vs
    df_round_drive), for which any fixed model serves; no jax needed."""
    import struct

    import numpy as np

    from dragonfly2_tpu.scheduler.evaluator import FEATURE_DIM

    d, h1, h2 = 32, 64, 32
    rng = np.random.default_rng(seed)
    din = 3 * d + FEATURE_DIM
    with open(path, "wb") as f:
        f.write(struct.pack("<7I", 0x44465343, 1, n_nodes, d, FEATURE_DIM, h1, h2))
        for shape, scale in (((n_nodes, d), 1.0), ((din, h1), 0.2), ((h1,), 0.1),
                             ((h1, h2), 0.2), ((h2,), 0.1), ((h2, 1), 0.2),
                             ((1,), 0.1)):
            f.write((rng.standard_normal(shape) * scale).astype(np.float32).tobytes())
    return path
