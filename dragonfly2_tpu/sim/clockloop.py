"""Virtual-clock asyncio event loop.

The scheduler's retry path awaits real coroutine sleeps (the resilience
BackoffPolicy): under a flash crowd an empty scheduling round backs off
50-800 ms before retrying. Simulating 10^5 peers cannot pay those sleeps in
wall time — so the simulator runs the whole control plane on an event loop
whose `time()` is the shared VirtualClock and whose selector, instead of
blocking, ADVANCES the clock to the next timer deadline. An `asyncio.sleep`
inside the scheduler then costs nanoseconds of wall time while still moving
simulated time by exactly its delay, in correct order against every other
pending timer.

No sockets exist in the simulator, so nothing can ever become ready on the
selector: advancing virtual time to the next timer IS the wait. A select with
no timeout (no timers, no ready callbacks) is a deadlock — some coroutine
awaits a future nothing will resolve — and raises instead of spinning.
"""

from __future__ import annotations

import asyncio
import selectors
from typing import Any, Coroutine, TypeVar

from dragonfly2_tpu.utils.clock import VirtualClock

T = TypeVar("T")


class _TimeAdvancingSelector(selectors.SelectSelector):
    """select(timeout) advances the virtual clock by `timeout` and reports
    no ready file objects (the loop's self-pipe is registered but never
    written: the simulator is single-threaded with no signals in flight)."""

    def __init__(self, clock: VirtualClock):
        super().__init__()
        self._vclock = clock

    def select(self, timeout: float | None = None) -> list:
        if timeout is None:
            raise RuntimeError(
                "virtual-clock loop would block forever: no scheduled timers "
                "and no ready callbacks (a coroutine is awaiting a future "
                "nothing in the simulation will resolve)"
            )
        if timeout > 0:
            self._vclock.advance(timeout)
        return []


class VirtualClockLoop(asyncio.SelectorEventLoop):
    """SelectorEventLoop reading time from a VirtualClock.

    call_later/call_at deadlines, asyncio.sleep, and wait_for timeouts all
    resolve against virtual time; the loop's own timer heap keeps them
    ordered. The clock object is shared with the scheduler services under
    simulation (their TTL sweeps and freshness stamps read the same time).
    """

    def __init__(self, clock: VirtualClock | None = None):
        self.vclock = clock or VirtualClock()
        super().__init__(_TimeAdvancingSelector(self.vclock))

    def time(self) -> float:
        return self.vclock.monotonic()


def run_virtual(
    coro: Coroutine[Any, Any, T], clock: VirtualClock | None = None
) -> T:
    """asyncio.run for simulated time: run `coro` to completion on a fresh
    VirtualClockLoop over `clock` (or a new one), closing the loop after."""
    loop = VirtualClockLoop(clock)
    try:
        asyncio.set_event_loop(loop)
        return loop.run_until_complete(coro)
    finally:
        asyncio.set_event_loop(None)
        loop.close()
