"""Scenario packs: configured simulations + their cluster-level assertions.

Each scenario is a builder returning a ready Simulation and a checker that
raises AssertionError (with the offending numbers) against its SimReport —
shared verbatim by tests/test_sim.py, cli/dfsim.py, bench.py's swarm_sim
section, and check.sh's sim-smoke leg, so "the scenario passes" means the
same thing everywhere.

  flash_crowd             N peers pull ONE task inside a short window (the
                          deploy-wave image pull). Asserts origin egress is
                          O(1) per region — a bounded number of task-sized
                          fetches, NOT proportional to peers — placement
                          stays region-local, and no scheduling round ever
                          hands out a cleanly-departed peer.
  cross_region_cold_start the task is seeded in one region; a crowd wakes in
                          another. Asserts the cold region bootstraps over a
                          bounded number of cross-region transfers and then
                          fans out locally.
  partition_and_heal      2 federated schedulers; the gossip link is severed
                          mid-run and healed. Asserts sync errors appear
                          during the partition, convergence (remote edges on
                          every member) within bounded virtual time after
                          heal, and the departed-peer invariant throughout.

Chaos packs (ISSUE 17 — graceful degradation under overload):

  overload_flash          arrivals at several times the scheduler's modeled
                          register capacity. The REAL DegradationController
                          rides the modeled queue depth: asserts the ladder
                          climbs to admission control, sheds lowest-priority
                          first with typed overloaded answers, goodput
                          recovers, admitted-round p99 stays bounded, and the
                          ladder steps back to 0 — via the stock
                          scheduler_degraded alert at virtual timestamps.
  manager_blackout        the modeled manager goes dark mid-crowd. Asserts
                          every keepalive agent declares manager_unreachable
                          (2+ consecutive failures, the production
                          threshold), in-flight downloads all complete, and
                          the rejoin wave after restore is spread by the REAL
                          ManagerLink._rejoin_delay jitter (no keepalive
                          bucket above 2x steady-state).
  gray_parents            a fraction of peers serve their uplink at a crawl —
                          alive, registered, invisible to liveness. Asserts
                          the swarm still completes and the origin is not
                          stampeded as a panic fallback.
  thundering_rejoin       keepalive-agents-only fleet (no downloads); a long
                          blackout, then restore. Asserts the rejoin burst
                          stays within 1.5x steady-state keepalive load — a
                          synchronized (unjittered) rejoin wave reads ~2x.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from dragonfly2_tpu.sim.engine import SimConfig, SimReport, Simulation
from dragonfly2_tpu.sim.topology import TopologyConfig
from dragonfly2_tpu.sim.workload import FlashCrowd, TaskSpec, WorkloadConfig


@dataclass
class Scenario:
    name: str
    sim: Simulation
    check: Callable[[SimReport], None]
    # the crowd task's size — origin-egress ratios are in units of it
    content_length: int


def _task(content_mb: int = 256, piece_mb: int = 16) -> TaskSpec:
    return TaskSpec(
        "sim-task-0000", "http://origin/sim-0.bin", content_mb << 20, piece_mb << 20
    )


def _probe_fraction(peers: int) -> float:
    # enough probe traffic to populate topology/dataset edges, bounded so
    # probe rounds stay a small slice of the event budget at 10^5 peers
    return min(0.25, 20_000 / max(peers, 1))


def flash_crowd(
    *,
    peers: int = 2_000,
    schedulers: int = 2,
    seed: int = 0,
    crowd_window_s: float = 60.0,
    telemetry_dir: str | None = None,
    regions: tuple[str, ...] = ("us-east", "us-west", "eu-west"),
    churn_lifetime_mean_s: float = 600.0,
    churn_crash_fraction: float = 0.25,
    sample_interval_s: float = 10.0,
    scoring: str = "base",
) -> Scenario:
    task = _task()
    cfg = SimConfig(
        schedulers=schedulers,
        seed=seed,
        scoring=scoring,
        topology=TopologyConfig(regions=regions),
        workload=WorkloadConfig(
            flash_crowds=(FlashCrowd(1.0, peers, crowd_window_s),),
            tasks=(task,),
            churn_lifetime_mean_s=churn_lifetime_mean_s,
            churn_crash_fraction=churn_crash_fraction,
            probe_fraction=_probe_fraction(peers),
        ),
        telemetry_dir=telemetry_dir,
        sample_interval_s=sample_interval_s,
    )
    sim = Simulation(cfg, scenario="flash_crowd")

    # Cluster properties are read off the metrics PLANE, not ad-hoc
    # counters: a mid-crowd control event queries the recorder's windowed
    # rates at VIRTUAL timestamps (observability/timeseries.py — the same
    # instrument dftop and the SLO engine read in production).
    ts_probe: dict = {}

    def probe_rates() -> None:
        rec = sim.recorder
        now = sim.clock.time()
        ts_probe["events_rate"] = rec.rate(
            "dragonfly_sim_events_total", window_s=30.0, now=now
        )
        ts_probe["egress_rate"] = rec.rate(
            "dragonfly_sim_origin_egress_bytes_total", window_s=30.0, now=now
        )
        ts_probe["peers"] = rec.latest("dragonfly_sim_peers")

    sim.at(1.0 + crowd_window_s * 0.6, probe_rates)

    def check(rep: SimReport) -> None:
        # ---- the timeseries plane saw the crowd: live windowed event rate
        # and population mid-crowd, origin egress RATE bounded in-window ----
        assert ts_probe.get("events_rate"), ts_probe
        assert ts_probe.get("peers"), ts_probe
        assert (ts_probe.get("egress_rate") or 0.0) * 30.0 <= 8.0 * task.content_length, (
            ts_probe
        )
        # ---- origin egress is O(1) per region: a bounded number of
        # task-sized fetches, independent of crowd size ----
        for region, nbytes in rep.origin_egress_bytes.items():
            fetches = nbytes / task.content_length
            assert fetches <= 8.0, (
                f"origin egress in {region} is {fetches:.1f} task-sized fetches "
                f"for {peers} peers — not O(1) per region"
            )
        assert sum(rep.origin_egress_bytes.values()) > 0, "nobody fetched the origin"
        # ---- the crowd actually completed through P2P ----
        assert rep.completed >= 0.95 * peers, (rep.completed, peers)
        assert rep.p2p_bytes >= 0.9 * peers * task.content_length * 0.5
        # ---- placement quality: the evaluator's locality features must beat
        # a uniform random draw (which would land ~1/len(regions) local) ----
        assert rep.same_region_frac >= 1.5 / len(regions), rep.same_region_frac
        # ---- no scheduling round ever observed a cleanly-departed peer ----
        assert rep.departed_parent_rounds == 0, rep.departed_parent_rounds
        # fan-out is shared, not one hero parent
        assert rep.fairness_jain > 0.1, rep.fairness_jain

    return Scenario("flash_crowd", sim, check, task.content_length)


def cross_region_cold_start(
    *,
    peers: int = 1_500,
    seed: int = 0,
    telemetry_dir: str | None = None,
) -> Scenario:
    """Task seeded (announce path) in region A; the crowd wakes in region B."""
    task = _task()
    regions = ("us-east", "eu-west")
    cfg = SimConfig(
        schedulers=2,
        seed=seed,
        topology=TopologyConfig(regions=regions, origin_region="us-east"),
        workload=WorkloadConfig(
            flash_crowds=(FlashCrowd(1.0, peers, 45.0, region="eu-west"),),
            tasks=(task,),
            probe_fraction=_probe_fraction(peers),
        ),
        telemetry_dir=telemetry_dir,
    )
    sim = Simulation(cfg, scenario="cross_region_cold_start")
    sim.preseed(task, "us-east", count=2)

    def check(rep: SimReport) -> None:
        assert rep.completed >= 0.95 * peers, (rep.completed, peers)
        # cold start crosses the WAN a bounded number of times (the seeds
        # and the origin sit in us-east), then fan-out happens locally:
        # cross-region bytes stay a small fraction of total P2P traffic
        frac = rep.cross_region_bytes / max(rep.p2p_bytes, 1)
        assert frac <= 0.25, f"cross-region fraction {frac:.3f} — no local fan-out"
        # origin egress bounded as ever
        total_fetches = sum(rep.origin_egress_bytes.values()) / task.content_length
        assert total_fetches <= 8.0, total_fetches
        assert rep.departed_parent_rounds == 0

    return Scenario("cross_region_cold_start", sim, check, task.content_length)


def partition_and_heal(
    *,
    peers: int = 1_200,
    seed: int = 0,
    partition_at_s: float = 20.0,
    heal_at_s: float = 120.0,
    convergence_budget_s: float = 60.0,
    telemetry_dir: str | None = None,
) -> Scenario:
    """Two federated ring members; gossip severed mid-crowd, then healed."""
    task = _task()
    cfg = SimConfig(
        schedulers=2,
        seed=seed,
        topology=TopologyConfig(regions=("us-east", "us-west")),
        workload=WorkloadConfig(
            flash_crowds=(
                FlashCrowd(1.0, peers // 2, 30.0),
                # a second wave keeps probe/scheduling traffic flowing after
                # the heal so convergence has deltas to carry
                FlashCrowd(heal_at_s + 5.0, peers - peers // 2, 30.0),
            ),
            tasks=(task,),
            probe_fraction=_probe_fraction(peers),
            churn_lifetime_mean_s=400.0,
            churn_crash_fraction=0.2,
        ),
        telemetry_dir=telemetry_dir,
        federation_interval_s=2.0,
        sample_interval_s=5.0,
    )
    sim = Simulation(cfg, scenario="partition_and_heal")
    a, b = sim.names[0], sim.names[1]
    sim.at(partition_at_s, lambda: sim.partition(a, b))
    sim.at(heal_at_s, lambda: sim.heal(a, b))

    # The production paging path, in virtual time: an AlertEngine over the
    # sim's recorder evaluates the stock federation_sync_failures rule
    # DURING the partition (two evaluations, spaced past the rule's for_s)
    # and again after the heal — the scenario asserts the alert fires while
    # severed and resolves once healed.
    from dragonfly2_tpu.observability.alerts import AlertEngine

    engine = AlertEngine(sim.recorder, export=False)
    alert_seen: dict = {}

    def _active() -> set:
        engine.evaluate_once(now=sim.clock.time())
        return {al["name"] for al in engine.active()}

    sim.at(partition_at_s + 45.0, lambda: _active())
    sim.at(
        partition_at_s + 60.0,
        lambda: alert_seen.__setitem__("during", "federation_sync_failures" in _active()),
    )
    sim.at(
        heal_at_s + 120.0,
        lambda: alert_seen.__setitem__("after", "federation_sync_failures" in _active()),
    )

    def check(rep: SimReport) -> None:
        fed = rep.federation
        assert fed, "no federation ticks ran"
        # the partition was real: sync errors accumulated while severed
        assert fed["syncs_failed"] > 0, fed
        # ... and the stock SLO rule saw it through the timeseries plane,
        # then resolved after the heal
        assert alert_seen.get("during") is True, alert_seen
        assert alert_seen.get("after") is False, alert_seen
        # and it healed: convergence (remote edges on EVERY member) within
        # the virtual budget after heal
        converged_at = None
        for row in fed["history"]:
            if row["t_s"] > heal_at_s and all(c > 0 for c in row["remote_edges"]):
                converged_at = row["t_s"]
                break
        assert converged_at is not None, "never converged after heal"
        assert converged_at - heal_at_s <= convergence_budget_s, (
            f"convergence took {converged_at - heal_at_s:.1f}s virtual "
            f"(budget {convergence_budget_s}s)"
        )
        assert rep.departed_parent_rounds == 0
        assert rep.completed >= 0.9 * peers, (rep.completed, peers)

    return Scenario("partition_and_heal", sim, check, task.content_length)


def overload_flash(
    *,
    peers: int = 10_000,
    seed: int = 0,
    overload_factor: float = 4.0,
    burst_s: float = 10.0,
    register_timeout_s: float = 10.0,
    shedding: bool = True,
    telemetry_dir: str | None = None,
    sample_interval_s: float = 2.0,
) -> Scenario:
    """Arrivals at `overload_factor` x the scheduler's modeled register
    capacity. With `shedding` the REAL brownout ladder (fed by the modeled
    queue-depth probe) engages through rung 4 and the typed overloaded
    answers spread the comeback; without it the modeled client timeouts
    amplify into a retry storm (bench.py's overload A/B runs both).

    The burst WINDOW is fixed and the per-register service cost derived
    from `peers`, so the backlog-vs-timeout dynamics (what ignites the
    storm and climbs the ladder) are identical at any scale — a 2k-peer
    smoke exercises the same time-shape as the 10^4-peer acceptance run."""
    task = _task(content_mb=64, piece_mb=4)
    register_cost_ms = 1000.0 * burst_s * overload_factor / peers
    capacity_per_s = 1000.0 / register_cost_ms  # one scheduler serves the task
    window_s = burst_s
    cfg = SimConfig(
        schedulers=1,
        seed=seed,
        topology=TopologyConfig(regions=("us-east", "us-west")),
        workload=WorkloadConfig(
            flash_crowds=(FlashCrowd(1.0, peers, window_s),),
            tasks=(task,),
            probe_fraction=0.0,
            # two traffic-shaper classes: admission must shed 1.0 before 5.0
            priority_classes=(1.0, 5.0),
        ),
        telemetry_dir=telemetry_dir,
        sample_interval_s=sample_interval_s,
        register_cost_ms=register_cost_ms,
        register_timeout_s=register_timeout_s,
        degradation=shedding,
        max_virtual_s=900.0,
    )
    sim = Simulation(cfg, scenario="overload_flash")

    # the production paging path at virtual timestamps: the stock
    # scheduler_degraded rule must FIRE mid-overload and RESOLVE by run end
    from dragonfly2_tpu.observability.alerts import AlertEngine

    engine = AlertEngine(sim.recorder, export=False)
    alert_seen: dict = {}

    def _degraded_active() -> bool:
        engine.evaluate_once(now=sim.clock.time())
        return "scheduler_degraded" in {al["name"] for al in engine.active()}

    sim.at(1.0 + window_s + 8.0, lambda: alert_seen.__setitem__(
        "during", _degraded_active()))

    def check(rep: SimReport) -> None:
        if not shedding:
            return  # the unshedded arm exists as the bench A/B baseline
        deg = rep.degradation
        assert deg, "degradation controller never attached"
        # the ladder climbed all the way to admission control, engaged
        # rung-by-rung under sustained pressure...
        assert deg["max_level"] == 4, deg
        # ...and stepped fully back down once the backlog drained
        assert deg["final_level"] == 0, deg
        # typed overloaded answers went out, lowest priority class first
        assert rep.overload_refused > 0, rep.overload_refused
        low = rep.shed_by_class.get("1", 0)
        high = rep.shed_by_class.get("5", 0)
        assert low > 0 and low >= high, rep.shed_by_class
        # the stock alert saw the brownout mid-overload and resolved
        assert alert_seen.get("during") is True, alert_seen
        assert _degraded_active() is False, "scheduler_degraded still active at end"
        # goodput: the crowd completes despite 4x overload (no collapse)
        assert rep.completed >= 0.9 * peers, (rep.completed, peers)
        assert rep.failed <= 0.05 * peers, rep.failed
        # admitted-round p99 bounded: shed peers come back and get through,
        # they don't queue unboundedly behind a melting scheduler (~120s
        # observed at 4x overload vs the unshedded arm's 1377/2000 failures)
        assert 0 < rep.admitted_p99_ms <= 150_000.0, rep.admitted_p99_ms
        assert rep.departed_parent_rounds == 0

    return Scenario("overload_flash", sim, check, task.content_length)


def manager_blackout(
    *,
    peers: int = 2_000,
    seed: int = 0,
    agents: int = 40,
    keepalive_interval_s: float = 20.0,
    blackout_at_s: float = 35.0,
    restore_at_s: float = 155.0,
    telemetry_dir: str | None = None,
) -> Scenario:
    """The modeled manager goes dark mid-crowd. The download plane never
    touches the manager (last-good scheduler snapshots serve — the autonomy
    contract), so every in-flight download must complete; the keepalive
    agents must declare unreachable on the production 2-consecutive-failures
    threshold and rejoin spread by the production jitter after restore. The
    rollout-watch freeze itself is pinned by tests/test_manager_link.py —
    the sim asserts the swarm-level invariants around it."""
    task = _task(content_mb=64, piece_mb=4)
    cfg = SimConfig(
        schedulers=2,
        seed=seed,
        topology=TopologyConfig(regions=("us-east", "us-west")),
        workload=WorkloadConfig(
            flash_crowds=(FlashCrowd(1.0, peers, 30.0),),
            tasks=(task,),
            probe_fraction=_probe_fraction(peers),
        ),
        telemetry_dir=telemetry_dir,
        keepalive_agents=agents,
        keepalive_interval_s=keepalive_interval_s,
        keepalive_horizon_s=restore_at_s + 6.0 * keepalive_interval_s,
    )
    sim = Simulation(cfg, scenario="manager_blackout")
    sim.at(blackout_at_s, sim.blackout)
    sim.at(restore_at_s, sim.restore)
    bucket_s = cfg.bucket_s

    def check(rep: SimReport) -> None:
        mgr = rep.manager
        assert mgr, "keepalive agents never ran"
        # every agent declared the blackout (>= 2 consecutive failures) and
        # recovered + rejoined after restore
        assert mgr["unreachable_declared"] == agents, mgr
        assert mgr["recovered"] == agents, mgr
        assert mgr["rejoined"] == agents, mgr
        # the rejoin wave is jitter-spread: no bucket's keepalive+rejoin load
        # exceeds 2x the steady-state keepalive rate (the ISSUE 17 bound)
        steady = agents * bucket_s / keepalive_interval_s
        worst = max(
            (b["keepalives"] + b["rejoins"] for b in rep.buckets), default=0
        )
        assert worst <= 2.0 * steady, (worst, steady)
        # manager loss never lost a download: everything in flight completed
        assert rep.completed >= 0.97 * peers, (rep.completed, peers)
        assert rep.failed == 0, rep.failed
        assert rep.departed_parent_rounds == 0

    return Scenario("manager_blackout", sim, check, task.content_length)


def gray_parents(
    *,
    peers: int = 3_000,
    seed: int = 0,
    gray_fraction: float = 0.3,
    gray_uplink_frac: float = 0.005,
    telemetry_dir: str | None = None,
) -> Scenario:
    """A slice of the swarm serves its uplink at a crawl — alive and
    registered, so liveness never flags it; only bandwidth feedback can.
    The swarm must still complete (children of gray parents just go slow or
    aggregate healthy parents) and must NOT stampede the origin as a panic
    fallback."""
    task = _task(content_mb=64, piece_mb=4)
    cfg = SimConfig(
        schedulers=2,
        seed=seed,
        topology=TopologyConfig(regions=("us-east", "us-west", "eu-west")),
        workload=WorkloadConfig(
            flash_crowds=(FlashCrowd(1.0, peers, 45.0),),
            tasks=(task,),
            probe_fraction=_probe_fraction(peers),
            gray_fraction=gray_fraction,
        ),
        telemetry_dir=telemetry_dir,
        gray_uplink_frac=gray_uplink_frac,
    )
    sim = Simulation(cfg, scenario="gray_parents")

    def check(rep: SimReport) -> None:
        # the draw actually produced a gray population near the target
        assert 0.6 * gray_fraction * peers <= rep.gray_peers <= 1.4 * gray_fraction * peers, (
            rep.gray_peers
        )
        # the swarm drains despite the gray slice
        assert rep.completed >= 0.95 * peers, (rep.completed, peers)
        # ... WITHOUT a panic stampede to the origin: egress stays a bounded
        # number of task-sized fetches per region, same as a healthy swarm
        for region, nbytes in rep.origin_egress_bytes.items():
            fetches = nbytes / task.content_length
            assert fetches <= 10.0, (region, fetches)
        assert rep.departed_parent_rounds == 0

    return Scenario("gray_parents", sim, check, task.content_length)


def thundering_rejoin(
    *,
    peers: int = 4_000,
    seed: int = 0,
    keepalive_interval_s: float = 20.0,
    blackout_at_s: float = 60.0,
    restore_at_s: float = 300.0,
    telemetry_dir: str | None = None,
) -> Scenario:
    """`peers` keepalive agents (no download workload) whose poll phases are
    SYNCHRONIZED (one deploy restarted the fleet — the worst thundering-herd
    shape), a long blackout, then restore. The whole fleet detects recovery
    on the same poll tick; only the production ManagerLink._rejoin_delay
    jitter spreads the catch-up wave. With it, the worst bucket stays within
    1.75x a steady poll tick and rejoins alone within 0.75x the fleet; a
    synchronized (unjittered) wave reads 2x / 1.0x and fails both."""
    agents = peers
    cfg = SimConfig(
        schedulers=1,
        seed=seed,
        workload=WorkloadConfig(),  # no arrivals: pure keepalive plane
        telemetry_dir=telemetry_dir,
        keepalive_agents=agents,
        keepalive_interval_s=keepalive_interval_s,
        keepalive_horizon_s=restore_at_s + 8.0 * keepalive_interval_s,
        keepalive_sync_start=True,
    )
    sim = Simulation(cfg, scenario="thundering_rejoin")
    sim.at(blackout_at_s, sim.blackout)
    sim.at(restore_at_s, sim.restore)

    def check(rep: SimReport) -> None:
        mgr = rep.manager
        assert mgr, "keepalive agents never ran"
        assert mgr["unreachable_declared"] == agents, mgr
        assert mgr["rejoined"] == agents, mgr
        # synchronized fleet: a steady poll tick is the whole fleet in one
        # bucket. The recovery bucket adds the rejoin wave on top — jitter
        # must keep it under 1.75x a tick (unjittered reads 2.0x)...
        worst_total = max(
            (b["keepalives"] + b["rejoins"] for b in rep.buckets), default=0
        )
        assert worst_total <= 1.75 * agents, (
            f"recovery burst {worst_total} events/bucket vs fleet {agents} "
            f"— jitter failed to spread the catch-up wave"
        )
        # ... and the rejoin RPCs themselves (re-register + dynconfig
        # refresh, the expensive leg) must spread across the interval
        worst_rejoins = max((b["rejoins"] for b in rep.buckets), default=0)
        assert worst_rejoins <= 0.75 * agents, (
            f"{worst_rejoins} rejoins in one bucket for a {agents}-agent fleet"
        )

    return Scenario("thundering_rejoin", sim, check, _task().content_length)


SCENARIOS: dict[str, Callable[..., Scenario]] = {
    "flash-crowd": flash_crowd,
    "cross-region-cold-start": cross_region_cold_start,
    "partition-and-heal": partition_and_heal,
    "overload-flash": overload_flash,
    "manager-blackout": manager_blackout,
    "gray-parents": gray_parents,
    "thundering-rejoin": thundering_rejoin,
}
