"""Scenario packs: configured simulations + their cluster-level assertions.

Each scenario is a builder returning a ready Simulation and a checker that
raises AssertionError (with the offending numbers) against its SimReport —
shared verbatim by tests/test_sim.py, cli/dfsim.py, bench.py's swarm_sim
section, and check.sh's sim-smoke leg, so "the scenario passes" means the
same thing everywhere.

  flash_crowd             N peers pull ONE task inside a short window (the
                          deploy-wave image pull). Asserts origin egress is
                          O(1) per region — a bounded number of task-sized
                          fetches, NOT proportional to peers — placement
                          stays region-local, and no scheduling round ever
                          hands out a cleanly-departed peer.
  cross_region_cold_start the task is seeded in one region; a crowd wakes in
                          another. Asserts the cold region bootstraps over a
                          bounded number of cross-region transfers and then
                          fans out locally.
  partition_and_heal      2 federated schedulers; the gossip link is severed
                          mid-run and healed. Asserts sync errors appear
                          during the partition, convergence (remote edges on
                          every member) within bounded virtual time after
                          heal, and the departed-peer invariant throughout.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from dragonfly2_tpu.sim.engine import SimConfig, SimReport, Simulation
from dragonfly2_tpu.sim.topology import TopologyConfig
from dragonfly2_tpu.sim.workload import FlashCrowd, TaskSpec, WorkloadConfig


@dataclass
class Scenario:
    name: str
    sim: Simulation
    check: Callable[[SimReport], None]
    # the crowd task's size — origin-egress ratios are in units of it
    content_length: int


def _task(content_mb: int = 256, piece_mb: int = 16) -> TaskSpec:
    return TaskSpec(
        "sim-task-0000", "http://origin/sim-0.bin", content_mb << 20, piece_mb << 20
    )


def _probe_fraction(peers: int) -> float:
    # enough probe traffic to populate topology/dataset edges, bounded so
    # probe rounds stay a small slice of the event budget at 10^5 peers
    return min(0.25, 20_000 / max(peers, 1))


def flash_crowd(
    *,
    peers: int = 2_000,
    schedulers: int = 2,
    seed: int = 0,
    crowd_window_s: float = 60.0,
    telemetry_dir: str | None = None,
    regions: tuple[str, ...] = ("us-east", "us-west", "eu-west"),
    churn_lifetime_mean_s: float = 600.0,
    churn_crash_fraction: float = 0.25,
    sample_interval_s: float = 10.0,
) -> Scenario:
    task = _task()
    cfg = SimConfig(
        schedulers=schedulers,
        seed=seed,
        topology=TopologyConfig(regions=regions),
        workload=WorkloadConfig(
            flash_crowds=(FlashCrowd(1.0, peers, crowd_window_s),),
            tasks=(task,),
            churn_lifetime_mean_s=churn_lifetime_mean_s,
            churn_crash_fraction=churn_crash_fraction,
            probe_fraction=_probe_fraction(peers),
        ),
        telemetry_dir=telemetry_dir,
        sample_interval_s=sample_interval_s,
    )
    sim = Simulation(cfg, scenario="flash_crowd")

    # Cluster properties are read off the metrics PLANE, not ad-hoc
    # counters: a mid-crowd control event queries the recorder's windowed
    # rates at VIRTUAL timestamps (observability/timeseries.py — the same
    # instrument dftop and the SLO engine read in production).
    ts_probe: dict = {}

    def probe_rates() -> None:
        rec = sim.recorder
        now = sim.clock.time()
        ts_probe["events_rate"] = rec.rate(
            "dragonfly_sim_events_total", window_s=30.0, now=now
        )
        ts_probe["egress_rate"] = rec.rate(
            "dragonfly_sim_origin_egress_bytes_total", window_s=30.0, now=now
        )
        ts_probe["peers"] = rec.latest("dragonfly_sim_peers")

    sim.at(1.0 + crowd_window_s * 0.6, probe_rates)

    def check(rep: SimReport) -> None:
        # ---- the timeseries plane saw the crowd: live windowed event rate
        # and population mid-crowd, origin egress RATE bounded in-window ----
        assert ts_probe.get("events_rate"), ts_probe
        assert ts_probe.get("peers"), ts_probe
        assert (ts_probe.get("egress_rate") or 0.0) * 30.0 <= 8.0 * task.content_length, (
            ts_probe
        )
        # ---- origin egress is O(1) per region: a bounded number of
        # task-sized fetches, independent of crowd size ----
        for region, nbytes in rep.origin_egress_bytes.items():
            fetches = nbytes / task.content_length
            assert fetches <= 8.0, (
                f"origin egress in {region} is {fetches:.1f} task-sized fetches "
                f"for {peers} peers — not O(1) per region"
            )
        assert sum(rep.origin_egress_bytes.values()) > 0, "nobody fetched the origin"
        # ---- the crowd actually completed through P2P ----
        assert rep.completed >= 0.95 * peers, (rep.completed, peers)
        assert rep.p2p_bytes >= 0.9 * peers * task.content_length * 0.5
        # ---- placement quality: the evaluator's locality features must beat
        # a uniform random draw (which would land ~1/len(regions) local) ----
        assert rep.same_region_frac >= 1.5 / len(regions), rep.same_region_frac
        # ---- no scheduling round ever observed a cleanly-departed peer ----
        assert rep.departed_parent_rounds == 0, rep.departed_parent_rounds
        # fan-out is shared, not one hero parent
        assert rep.fairness_jain > 0.1, rep.fairness_jain

    return Scenario("flash_crowd", sim, check, task.content_length)


def cross_region_cold_start(
    *,
    peers: int = 1_500,
    seed: int = 0,
    telemetry_dir: str | None = None,
) -> Scenario:
    """Task seeded (announce path) in region A; the crowd wakes in region B."""
    task = _task()
    regions = ("us-east", "eu-west")
    cfg = SimConfig(
        schedulers=2,
        seed=seed,
        topology=TopologyConfig(regions=regions, origin_region="us-east"),
        workload=WorkloadConfig(
            flash_crowds=(FlashCrowd(1.0, peers, 45.0, region="eu-west"),),
            tasks=(task,),
            probe_fraction=_probe_fraction(peers),
        ),
        telemetry_dir=telemetry_dir,
    )
    sim = Simulation(cfg, scenario="cross_region_cold_start")
    sim.preseed(task, "us-east", count=2)

    def check(rep: SimReport) -> None:
        assert rep.completed >= 0.95 * peers, (rep.completed, peers)
        # cold start crosses the WAN a bounded number of times (the seeds
        # and the origin sit in us-east), then fan-out happens locally:
        # cross-region bytes stay a small fraction of total P2P traffic
        frac = rep.cross_region_bytes / max(rep.p2p_bytes, 1)
        assert frac <= 0.25, f"cross-region fraction {frac:.3f} — no local fan-out"
        # origin egress bounded as ever
        total_fetches = sum(rep.origin_egress_bytes.values()) / task.content_length
        assert total_fetches <= 8.0, total_fetches
        assert rep.departed_parent_rounds == 0

    return Scenario("cross_region_cold_start", sim, check, task.content_length)


def partition_and_heal(
    *,
    peers: int = 1_200,
    seed: int = 0,
    partition_at_s: float = 20.0,
    heal_at_s: float = 120.0,
    convergence_budget_s: float = 60.0,
    telemetry_dir: str | None = None,
) -> Scenario:
    """Two federated ring members; gossip severed mid-crowd, then healed."""
    task = _task()
    cfg = SimConfig(
        schedulers=2,
        seed=seed,
        topology=TopologyConfig(regions=("us-east", "us-west")),
        workload=WorkloadConfig(
            flash_crowds=(
                FlashCrowd(1.0, peers // 2, 30.0),
                # a second wave keeps probe/scheduling traffic flowing after
                # the heal so convergence has deltas to carry
                FlashCrowd(heal_at_s + 5.0, peers - peers // 2, 30.0),
            ),
            tasks=(task,),
            probe_fraction=_probe_fraction(peers),
            churn_lifetime_mean_s=400.0,
            churn_crash_fraction=0.2,
        ),
        telemetry_dir=telemetry_dir,
        federation_interval_s=2.0,
        sample_interval_s=5.0,
    )
    sim = Simulation(cfg, scenario="partition_and_heal")
    a, b = sim.names[0], sim.names[1]
    sim.at(partition_at_s, lambda: sim.partition(a, b))
    sim.at(heal_at_s, lambda: sim.heal(a, b))

    # The production paging path, in virtual time: an AlertEngine over the
    # sim's recorder evaluates the stock federation_sync_failures rule
    # DURING the partition (two evaluations, spaced past the rule's for_s)
    # and again after the heal — the scenario asserts the alert fires while
    # severed and resolves once healed.
    from dragonfly2_tpu.observability.alerts import AlertEngine

    engine = AlertEngine(sim.recorder, export=False)
    alert_seen: dict = {}

    def _active() -> set:
        engine.evaluate_once(now=sim.clock.time())
        return {al["name"] for al in engine.active()}

    sim.at(partition_at_s + 45.0, lambda: _active())
    sim.at(
        partition_at_s + 60.0,
        lambda: alert_seen.__setitem__("during", "federation_sync_failures" in _active()),
    )
    sim.at(
        heal_at_s + 120.0,
        lambda: alert_seen.__setitem__("after", "federation_sync_failures" in _active()),
    )

    def check(rep: SimReport) -> None:
        fed = rep.federation
        assert fed, "no federation ticks ran"
        # the partition was real: sync errors accumulated while severed
        assert fed["syncs_failed"] > 0, fed
        # ... and the stock SLO rule saw it through the timeseries plane,
        # then resolved after the heal
        assert alert_seen.get("during") is True, alert_seen
        assert alert_seen.get("after") is False, alert_seen
        # and it healed: convergence (remote edges on EVERY member) within
        # the virtual budget after heal
        converged_at = None
        for row in fed["history"]:
            if row["t_s"] > heal_at_s and all(c > 0 for c in row["remote_edges"]):
                converged_at = row["t_s"]
                break
        assert converged_at is not None, "never converged after heal"
        assert converged_at - heal_at_s <= convergence_budget_s, (
            f"convergence took {converged_at - heal_at_s:.1f}s virtual "
            f"(budget {convergence_budget_s}s)"
        )
        assert rep.departed_parent_rounds == 0
        assert rep.completed >= 0.9 * peers, (rep.completed, peers)

    return Scenario("partition_and_heal", sim, check, task.content_length)


SCENARIOS: dict[str, Callable[..., Scenario]] = {
    "flash-crowd": flash_crowd,
    "cross-region-cold-start": cross_region_cold_start,
    "partition-and-heal": partition_and_heal,
}
