"""Discrete-event swarm simulator (ISSUE 14 / ROADMAP #5).

Drives the REAL control plane — SchedulerService / Scheduling / MLEvaluator /
FederationSync — in-process at 10^5+ simulated peers: zero sockets, zero wall
sleeps, one injectable VirtualClock (utils/clock.py). Virtual peers speak the
same client protocol daemons do; piece transfers are completion-time models
over a synthetic region/rack topology; the scheduler's telemetry records flow
through the existing DatasetAccumulator ingest so a trainer can consume
simulated traffic.

Layout:
  clockloop   asyncio event loop whose time IS the virtual clock
  topology    synthetic region/rack RTT + bandwidth model
  workload    arrival (Poisson + flash crowd), churn, task catalog
  engine      event heap + virtual peers + the in-process cluster
  scenarios   scenario packs (flash crowd, cross-region cold start,
              partition-and-heal) shared by tests, dfsim, and bench
  metrics     dragonfly_sim_* families + the sim alert rule's inputs

Wall-clock discipline: nothing in this package may read the wall clock or
sleep for real (dflint DF029) — a single stray time.time() silently corrupts
event ordering. The one exception is the engine's honest events/s meter,
suppressed with a reason at the site.
"""

from dragonfly2_tpu.sim.engine import SimConfig, SimReport, Simulation  # noqa: F401
from dragonfly2_tpu.sim.topology import SyntheticTopology, TopologyConfig  # noqa: F401
from dragonfly2_tpu.sim.workload import Workload, WorkloadConfig  # noqa: F401
