"""Workload models: peer arrival, flash crowds, churn, task catalog.

Arrivals are a Poisson process (exponential inter-arrival gaps) plus zero or
more flash-crowd bursts — N peers arriving over a short window, all pulling
ONE task (the "image pull" shape: a deploy wave hits every node at once).
Churn draws a lifetime per peer; at end-of-life a peer either LEAVES cleanly
(daemon shutdown: leave_peer/leave_host reach the scheduler) or CRASHES
(silent: the scheduler keeps a ghost row until supersede/GC — the resurrection
path the restart suite proves). All draws are seeded: a scenario replays
bit-identically for a given (workload seed, topology seed) pair.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field


@dataclass
class TaskSpec:
    task_id: str
    url: str
    content_length: int
    piece_size: int

    @property
    def total_pieces(self) -> int:
        return max(1, -(-self.content_length // self.piece_size))


@dataclass
class FlashCrowd:
    start_s: float
    peers: int
    duration_s: float  # arrivals spread uniformly across the window
    task_index: int = 0  # index into the task catalog
    region: str | None = None  # None: weighted draw across regions


@dataclass
class WorkloadConfig:
    # steady-state Poisson arrivals (0 = bursts only)
    poisson_rate_per_s: float = 0.0
    poisson_peers: int = 0  # total steady-state arrivals to generate
    flash_crowds: tuple[FlashCrowd, ...] = ()
    tasks: tuple[TaskSpec, ...] = (
        TaskSpec("sim-task-0000", "http://origin/sim-0.bin", 256 << 20, 4 << 20),
    )
    # churn: mean exponential lifetime AFTER download completes; 0 = immortal
    churn_lifetime_mean_s: float = 0.0
    churn_crash_fraction: float = 0.0  # of departures, fraction that crash
    # fraction of peers that run RTT probe rounds (feeds topology + dataset)
    probe_fraction: float = 0.25
    probe_rounds: int = 2
    probe_interval_s: float = 5.0
    # gray parents (ISSUE 17): fraction of peers whose uplink serves at a
    # crawl after completion (engine caps it at gray_uplink_frac) — degraded
    # but alive, invisible to liveness checks
    gray_fraction: float = 0.0
    # traffic-shaper priority classes, drawn uniformly per peer — feeds the
    # admission-control rung's lowest-first shed order
    priority_classes: tuple[float, ...] = (1.0,)


@dataclass
class PeerArrival:
    at_s: float
    index: int
    task: TaskSpec
    region: str | None  # pin to a region (flash crowd) or None


@dataclass
class Workload:
    config: WorkloadConfig = field(default_factory=WorkloadConfig)
    seed: int = 0

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def arrivals(self) -> list[PeerArrival]:
        """The full seeded arrival schedule, time-ordered."""
        cfg = self.config
        rng = self._rng
        out: list[PeerArrival] = []
        t = 0.0
        for _ in range(cfg.poisson_peers):
            t += rng.expovariate(cfg.poisson_rate_per_s) if cfg.poisson_rate_per_s else 1.0
            out.append(PeerArrival(t, 0, cfg.tasks[0], None))
        for crowd in cfg.flash_crowds:
            task = cfg.tasks[crowd.task_index]
            for _ in range(crowd.peers):
                at = crowd.start_s + rng.uniform(0.0, max(crowd.duration_s, 1e-9))
                out.append(PeerArrival(at, 0, task, crowd.region))
        out.sort(key=lambda a: a.at_s)
        for i, a in enumerate(out):
            a.index = i
        return out

    def lifetime_s(self) -> float | None:
        """Post-download lifetime draw; None = stays for the whole run."""
        mean = self.config.churn_lifetime_mean_s
        if mean <= 0:
            return None
        return self._rng.expovariate(1.0 / mean)

    def departure_is_crash(self) -> bool:
        return self._rng.random() < self.config.churn_crash_fraction

    def runs_probes(self) -> bool:
        return self._rng.random() < self.config.probe_fraction

    def is_gray(self) -> bool:
        return self._rng.random() < self.config.gray_fraction

    def draw_priority(self) -> float:
        classes = self.config.priority_classes
        if not classes:
            return 1.0
        return classes[self._rng.randrange(len(classes))]
