"""Synthetic cluster topology: regions, racks, RTT and link-capacity models.

The simulator needs ground truth the real wire provides for free: how far
apart two hosts are and how fast bytes move between them. The model is
deliberately simple — a region/rack tree with level-dependent RTT bands and
per-host uplink/downlink caps plus a cross-region bottleneck — because the
properties under test (placement locality, O(1)-per-region origin egress,
federation convergence) depend on the SHAPE of the cost surface, not its
exact values.

Host placement also feeds the REAL evaluator's locality features: hosts get
`idc=<region>` and `location="<region>|<rack>"`, the exact strings
models.features.location_affinity scores, and probe rounds report model RTTs
into the scheduler's NetworkTopology — so the scheduler sees the synthetic
world through the same features it sees production, and "placement quality"
measures the actual serving policy.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field


@dataclass
class TopologyConfig:
    regions: tuple[str, ...] = ("us-east", "us-west", "eu-west")
    # arrival weight per region (normalized); len must match regions
    region_weights: tuple[float, ...] = ()
    racks_per_region: int = 8
    # RTT bands (ms) by relationship, jittered per pair (seeded)
    rtt_same_rack_ms: float = 0.25
    rtt_same_region_ms: float = 1.5
    rtt_cross_region_ms: float = 70.0
    rtt_jitter: float = 0.2  # +/- fraction of the band
    # link capacity (bytes/s)
    uplink_bps: float = 1.25e9  # 10 Gb/s host NIC
    downlink_bps: float = 1.25e9
    cross_region_bps: float = 2.5e8  # per-flow share of the WAN bottleneck
    origin_region: str = ""  # default: regions[0]
    origin_rate_bps: float = 6.25e8  # per-fetch origin share (5 Gb/s)

    def __post_init__(self):
        if not self.region_weights:
            self.region_weights = tuple(1.0 for _ in self.regions)
        if len(self.region_weights) != len(self.regions):
            raise ValueError("region_weights must match regions")
        if not self.origin_region:
            self.origin_region = self.regions[0]


@dataclass(frozen=True)
class Placement:
    region: str
    rack: int

    @property
    def idc(self) -> str:
        return self.region

    @property
    def location(self) -> str:
        # the '|'-separated path models.features.location_affinity scores
        return f"{self.region}|rack{self.rack}"


@dataclass
class SyntheticTopology:
    config: TopologyConfig = field(default_factory=TopologyConfig)
    seed: int = 0

    def __post_init__(self):
        self._rng = random.Random(self.seed)
        # per-(placement, placement) jitter memo keeps RTTs stable per pair
        # across the run (probes for one pair must agree with transfers)
        self._jitter: dict[tuple, float] = {}

    def place(self, region: str | None = None) -> Placement:
        cfg = self.config
        if region is None:
            region = self._rng.choices(cfg.regions, weights=cfg.region_weights)[0]
        return Placement(region, self._rng.randrange(cfg.racks_per_region))

    def _pair_jitter(self, a: Placement, b: Placement) -> float:
        key = (a, b) if (a.region, a.rack) <= (b.region, b.rack) else (b, a)
        j = self._jitter.get(key)
        if j is None:
            j = self._jitter[key] = self._rng.uniform(
                1.0 - self.config.rtt_jitter, 1.0 + self.config.rtt_jitter
            )
        return j

    def rtt_ms(self, a: Placement, b: Placement) -> float:
        cfg = self.config
        if a.region != b.region:
            base = cfg.rtt_cross_region_ms
        elif a.rack != b.rack:
            base = cfg.rtt_same_region_ms
        else:
            base = cfg.rtt_same_rack_ms
        return base * self._pair_jitter(a, b)

    def link_bps(self, parent: Placement, child: Placement) -> float:
        """Per-flow capacity of the parent->child path before host caps."""
        cfg = self.config
        if parent.region != child.region:
            return cfg.cross_region_bps
        return min(cfg.uplink_bps, cfg.downlink_bps)

    def origin_rate_bps(self, child: Placement) -> float:
        """Per-fetch origin rate; cross-region fetches ride the WAN share."""
        cfg = self.config
        rate = cfg.origin_rate_bps
        if child.region != cfg.origin_region:
            rate = min(rate, cfg.cross_region_bps)
        return rate
