"""Simulator metric families (dragonfly_sim_*).

The simulator is instrumented like any other subsystem (ROADMAP note: new
subsystems ship their instrument): event throughput, live peer population,
origin egress by region, and the departed-parent invariant counter. Scenario
packs read these through observability/timeseries.py — a MetricsRecorder
sampled at VIRTUAL timestamps — so cluster-level assertions ("origin egress
rate stays bounded during the crowd") are windowed-rate queries over the same
plane production dashboards read, not ad-hoc snapshot scraping.

DEPARTED_PARENT_ROUNDS backs the `sim_departed_parent` alert rule
(observability/alerts.py): a scheduling round handing out a peer that cleanly
left the cluster is an invariant violation, never noise — any sustained rate
fires.
"""

from __future__ import annotations

from dragonfly2_tpu.observability.metrics import default_registry

_r = default_registry()

SIM_EVENTS_TOTAL = _r.counter(
    "events_total",
    "Simulation events processed, by kind",
    subsystem="sim",
    labels=("kind",),
)
SIM_PEERS = _r.gauge(
    "peers", "Live simulated peers (arrived, not yet departed)", subsystem="sim"
)
SIM_ORIGIN_EGRESS_BYTES = _r.counter(
    "origin_egress_bytes_total",
    "Bytes fetched from the origin by simulated back-to-source peers",
    subsystem="sim",
    labels=("region",),
)
SIM_DEPARTED_PARENT_ROUNDS = _r.counter(
    "departed_parent_rounds_total",
    "Scheduling rounds that handed out a cleanly-departed peer as a parent "
    "(invariant violation; feeds the sim_departed_parent alert)",
    subsystem="sim",
)
