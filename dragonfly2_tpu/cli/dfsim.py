"""dfsim — run a swarm-simulation scenario against the real control plane.

    python -m dragonfly2_tpu.cli.dfsim flash-crowd --peers 100000 --json
    python -m dragonfly2_tpu.cli.dfsim partition-and-heal --peers 2000
    python -m dragonfly2_tpu.cli.dfsim cross-region-cold-start --seed 3

Human output: a per-interval table (arrivals, scheduling rounds, same-region
placement, origin egress) plus the summary block. `--json` emits ONE json
object with the stable contract keys below (check.sh's sim-smoke leg and the
bench's swarm_sim section read them):

  scenario, peers, schedulers, seed, events, wall_s, virtual_s,
  events_per_sec, time_compression,
  placement: {rounds, same_region_frac, same_rack_frac, mean_parent_rtt_ms}
  origin_egress: {bytes_per_region, max_region_fetches}
  fairness: {jain_upload_index}
  outcomes: {completed, failed, refused, back_to_source, reschedules,
             departed, crashed}
  violations: {departed_parent_rounds}
  federation: {syncs_ok, syncs_failed, first_remote_edge_s} | null
  overload: {refused, retries, timeouts, admitted_p50_ms, admitted_p99_ms,
             shed_by_class} | null      (ISSUE 17 chaos packs)
  degradation: {max_level, final_level} | null
  manager: {agents, unreachable_declared, recovered, rejoined} | null
  telemetry: {nodes, edges, pairs, download_rows, probe_rows} | null
  assertions: {passed: bool, error: str | null}
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
from typing import Any

from dragonfly2_tpu.sim.scenarios import SCENARIOS


def run_scenario(
    name: str,
    *,
    peers: int | None = None,
    schedulers: int | None = None,
    seed: int = 0,
    telemetry: bool = True,
    check: bool = True,
    **kw: Any,
) -> dict[str, Any]:
    """Build, run, bridge, and check one scenario; returns the JSON contract
    dict (the in-process entry check.sh and bench share with the CLI)."""
    builder = SCENARIOS[name]
    build_kw: dict[str, Any] = {"seed": seed, **kw}
    if peers is not None:
        build_kw["peers"] = peers
    if schedulers is not None and name == "flash-crowd":
        build_kw["schedulers"] = schedulers
    own_dir = None
    if telemetry and "telemetry_dir" not in build_kw:
        own_dir = tempfile.mkdtemp(prefix=f"dfsim-{name}-")
        build_kw["telemetry_dir"] = own_dir
    scenario = builder(**build_kw)
    try:
        rep = scenario.sim.run()
        telemetry_stats = None
        if telemetry:
            ds = scenario.sim.build_dataset()
            telemetry_stats = {k: v for k, v in ds.items() if k != "dataset"}
        passed, error = True, None
        if check:
            try:
                scenario.check(rep)
            except AssertionError as e:
                passed, error = False, str(e)
        content = scenario.content_length
        return {
            "scenario": rep.scenario,
            "peers": rep.peers,
            "schedulers": len(scenario.sim.names),
            "seed": seed,
            "events": rep.events,
            "wall_s": rep.wall_s,
            "virtual_s": rep.virtual_s,
            "events_per_sec": rep.events_per_sec,
            "time_compression": rep.time_compression,
            "scheduler": {
                # ISSUE 18: the sim-scale round-loop meter. scoring reports
                # what actually served (an ml-* request degrades to "base"
                # when the native toolchain is missing); rounds_per_s is
                # rounds / seconds INSIDE schedule_candidate_parents.
                "scoring": rep.scoring,
                "rounds": rep.sched_rounds,
                "sched_s": rep.sched_s,
                "rounds_per_s": rep.sched_rounds_per_s,
                "native_rounds": rep.native_rounds,
                # ISSUE 19: mirror-driven split of native_rounds (cached-row
                # fast path vs stale-revalidated) + the full-export counter —
                # must equal the scheduler count (one attach each, then
                # deltas only)
                "mirror_rounds": rep.mirror_rounds,
                "mirror_stale_rounds": rep.mirror_stale_rounds,
                "mirror_full_syncs": rep.mirror_full_syncs,
            },
            "placement": {
                "rounds": rep.rounds_with_parents,
                "same_region_frac": rep.same_region_frac,
                "same_rack_frac": rep.same_rack_frac,
                "mean_parent_rtt_ms": rep.mean_parent_rtt_ms,
            },
            "origin_egress": {
                "bytes_per_region": dict(rep.origin_egress_bytes),
                "max_region_fetches": round(
                    max(rep.origin_egress_bytes.values(), default=0) / content, 2
                ),
            },
            "fairness": {"jain_upload_index": rep.fairness_jain},
            "outcomes": {
                "completed": rep.completed,
                "failed": rep.failed,
                "refused": rep.refused,
                "back_to_source": rep.back_to_source,
                "reschedules": rep.reschedules,
                "departed": rep.departed,
                "crashed": rep.crashed,
            },
            "violations": {"departed_parent_rounds": rep.departed_parent_rounds},
            "overload": (
                {
                    "refused": rep.overload_refused,
                    "retries": rep.overload_retries,
                    "timeouts": rep.register_timeouts,
                    "admitted_p50_ms": rep.admitted_p50_ms,
                    "admitted_p99_ms": rep.admitted_p99_ms,
                    "shed_by_class": dict(rep.shed_by_class),
                }
                if (rep.overload_refused or rep.register_timeouts
                    or rep.admitted_p99_ms)
                else None
            ),
            "degradation": (
                {"max_level": rep.degradation["max_level"],
                 "final_level": rep.degradation["final_level"]}
                if rep.degradation else None
            ),
            "manager": dict(rep.manager) if rep.manager else None,
            "federation": (
                {k: rep.federation[k] for k in
                 ("syncs_ok", "syncs_failed", "first_remote_edge_s")}
                if rep.federation else None
            ),
            "telemetry": telemetry_stats,
            "assertions": {"passed": passed, "error": error},
            "_buckets": rep.buckets,
        }
    finally:
        scenario.sim.close()
        if own_dir is not None:
            # a dir this call created is this call's to remove — repeated
            # CLI/smoke runs must not accumulate record files in /tmp
            # (callers passing their own telemetry_dir keep theirs)
            shutil.rmtree(own_dir, ignore_errors=True)


def _print_human(out: dict) -> None:
    print(f"── dfsim · {out['scenario']} ─ {out['peers']} peers, "
          f"{out['schedulers']} scheduler(s), seed {out['seed']}")
    buckets = out.pop("_buckets", [])
    if buckets:
        print(f"{'t(s)':>7} {'arrive':>7} {'rounds':>7} {'local%':>7} "
              f"{'done':>7} {'b2s':>4} {'origin MB':>10} {'p2p GB':>8}")
        for b in buckets:
            if not (b["arrivals"] or b["rounds"] or b["completions"]):
                continue
            local = 100.0 * b["same_region"] / b["parents"] if b["parents"] else 0.0
            print(f"{b['t_s']:>7.0f} {b['arrivals']:>7} {b['rounds']:>7} "
                  f"{local:>6.1f}% {b['completions']:>7} {b['back_to_source']:>4} "
                  f"{b['origin_bytes'] / 1e6:>10.1f} {b['p2p_bytes'] / 1e9:>8.2f}")
    pl, eg = out["placement"], out["origin_egress"]
    oc, fed = out["outcomes"], out["federation"]
    print(f"events {out['events']} in {out['wall_s']}s wall "
          f"({out['events_per_sec']}/s, {out['virtual_s']}s virtual, "
          f"{out['time_compression']}x compression)")
    print(f"placement: {pl['rounds']} rounds, "
          f"{100 * pl['same_region_frac']:.1f}% same-region, "
          f"mean parent RTT {pl['mean_parent_rtt_ms']:.2f} ms")
    print(f"origin egress: {eg['bytes_per_region']} "
          f"(max {eg['max_region_fetches']} task-sized fetches/region)")
    print(f"outcomes: {oc} · fairness jain {out['fairness']['jain_upload_index']}")
    if fed:
        print(f"federation: {fed}")
    if out["telemetry"]:
        print(f"telemetry -> dataset: {out['telemetry']}")
    a = out["assertions"]
    print("scenario assertions:", "PASS" if a["passed"] else f"FAIL — {a['error']}")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dfsim", description="discrete-event swarm simulator (virtual clock)"
    )
    ap.add_argument("scenario", choices=sorted(SCENARIOS))
    ap.add_argument("--peers", type=int, default=None,
                    help="simulated peers (scenario default if omitted)")
    ap.add_argument("--schedulers", type=int, default=None,
                    help="ring members (flash-crowd only)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-telemetry", action="store_true",
                    help="skip record capture + dataset bridge (pure control plane)")
    ap.add_argument("--scoring", choices=("base", "ml-serial", "ml-native"),
                    default="base",
                    help="scoring plane: base (no model), ml-serial (synthetic "
                         "native model, per-round Python loop), ml-native (same "
                         "model through the df_round_drive round driver). "
                         "flash-crowd only; ml legs skip the placement-quality "
                         "checks (policy under a synthetic model is not the "
                         "scenario contract — the round-loop A/B is)")
    ap.add_argument("--json", action="store_true", help="one JSON object on stdout")
    args = ap.parse_args(argv)

    kw: dict[str, Any] = {}
    if args.scoring != "base":
        if args.scenario != "flash-crowd":
            ap.error("--scoring is flash-crowd only")
        kw["scoring"] = args.scoring
        kw["check"] = False
    out = run_scenario(
        args.scenario,
        peers=args.peers,
        schedulers=args.schedulers,
        seed=args.seed,
        telemetry=not args.no_telemetry,
        **kw,
    )
    if args.json:
        out.pop("_buckets", None)
        print(json.dumps(out))
    else:
        _print_human(out)
    return 0 if out["assertions"]["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
