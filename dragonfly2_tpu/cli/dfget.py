"""dfget: download a URL through the P2P cluster.

Reference equivalent: cmd/dfget + client/dfget/dfget.go:47-138 (talks to the
daemon over its unix-socket RPC; spawns the daemon if absent, the
checkAndSpawnDaemon behavior at cmd/dfget/cmd/root.go:266).

  python -m dragonfly2_tpu.cli.dfget http://origin/file -O /tmp/out \
      --scheduler 127.0.0.1:9000
"""

from __future__ import annotations

import argparse
import asyncio
import os
import subprocess
import sys
import time

from dragonfly2_tpu.rpc.core import RpcClient

DEFAULT_SOCK = "/tmp/dragonfly2_tpu_daemon.sock"


async def _daemon_alive(sock: str) -> bool:
    if not os.path.exists(sock):
        return False
    client = RpcClient(sock, retries=0)
    try:
        return await client.healthy()
    finally:
        await client.close()


def spawn_daemon(sock: str, scheduler: str, storage: str | None, *, seed: bool = False) -> None:
    """Fork a daemon process and wait for its socket (ref checkAndSpawnDaemon)."""
    cmd = [
        sys.executable, "-m", "dragonfly2_tpu.daemon.server",
        "--scheduler", scheduler, "--sock", sock,
    ]
    if storage:
        cmd += ["--storage", storage]
    if seed:
        cmd += ["--seed"]
    subprocess.Popen(
        cmd,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,  # detach: daemon outlives this CLI
    )


async def wait_daemon(sock: str, timeout: float) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if await _daemon_alive(sock):
            return True
        await asyncio.sleep(0.1)
    return False


async def ensure_daemon(
    sock: str,
    scheduler: str | None,
    storage: str | None,
    *,
    no_spawn: bool = False,
    spawn_timeout: float = 15.0,
    seed: bool = False,
) -> bool:
    """Shared alive/spawn/wait flow for all thin CLIs (ref checkAndSpawnDaemon).
    Prints the failure reason and returns False when no daemon is usable."""
    if await _daemon_alive(sock):
        return True
    if no_spawn:
        print(f"error: no daemon at {sock} (and --no-spawn set)", file=sys.stderr)
        return False
    if not scheduler:
        print("error: daemon not running; --scheduler required to spawn one", file=sys.stderr)
        return False
    spawn_daemon(sock, scheduler, storage, seed=seed)
    if not await wait_daemon(sock, spawn_timeout):
        print("error: daemon failed to start", file=sys.stderr)
        return False
    return True


async def single_download(
    client: RpcClient, args: argparse.Namespace, url: str, output: str
) -> None:
    from dragonfly2_tpu.observability.tracing import default_tracer

    t0 = time.monotonic()
    # trace ROOT for the download chain: the rpc client ships this context
    # to the daemon, whose conductor/scheduler/parent-daemon spans all join
    # the one trace (`dftrace <files>` reassembles it)
    with default_tracer().span("dfget.download", url=url, output=output):
        result = await client.call(
            "download",
            {
                "url": url,
                "output": os.path.abspath(output),
                "tag": args.tag,
                "application": args.application,
                "digest": args.digest if url == args.url else "",
                "filters": args.filter,
                "range": args.range if url == args.url else "",
            },
            timeout=args.timeout,
        )
    elapsed = time.monotonic() - t0
    size = result.get("exported_bytes", result["content_length"])
    rate = size / max(elapsed, 1e-6) / (1 << 20)
    print(
        f"downloaded {url} -> {output}: {size} bytes, "
        f"{result['pieces']} pieces, {elapsed:.2f}s ({rate:.1f} MiB/s) "
        f"task={result['task_id'][:16]}"
    )


def _accepted(url: str, accept: str, reject: str) -> bool:
    import re

    if reject and re.search(reject, url):
        return False
    if accept and not re.search(accept, url):
        return False
    return True


async def recursive_download(client: RpcClient, args: argparse.Namespace) -> int:
    """Breadth-first directory download (ref client/dfget/dfget.go:312
    recursiveDownload + pkg/source URLEntry listing): list each directory URL
    via the source client, download file entries through the daemon into the
    mirrored tree under --output, queue subdirectories."""
    from collections import deque

    from dragonfly2_tpu.daemon.source import SourceRegistry

    sources = SourceRegistry()
    # (url, output_dir, level) entries
    queue: deque[tuple[str, str, int]] = deque()  # dflint: disable=DF034 BFS frontier of the finite directory tree one CLI invocation crawls, drained in this same loop — not a service-lifetime buffer
    queue.append((args.url, args.output, args.level))
    seen: set[str] = set()
    failures = 0
    try:
        while queue:
            url, out_dir, level = queue.popleft()
            if args.level and level == 0:
                continue
            if url in seen:
                continue  # loop prevention (ref downloadMap)
            seen.add(url)
            try:
                entries = await sources.list_entries(url)
            except Exception as e:
                print(f"error: listing {url}: {e}", file=sys.stderr)
                failures += 1
                continue
            sem = asyncio.Semaphore(args.jobs)
            batch: list = []

            async def fetch(entry_url: str, out_path: str) -> int:
                async with sem:
                    try:
                        await single_download(client, args, entry_url, out_path)
                        return 0
                    except Exception as e:
                        print(f"error: {entry_url}: {e}", file=sys.stderr)
                        return 1

            for entry in entries:
                child_out = os.path.join(out_dir, entry.name)
                if entry.is_dir:
                    # accept-regex describes FILES; only reject prunes subtrees
                    # (ref recursiveDownload queues dirs before accept checks)
                    if args.reject_regex and not _accepted(entry.url, "", args.reject_regex):
                        continue
                    queue.append((entry.url, child_out, level - 1))
                    continue
                if not _accepted(entry.url, args.accept_regex, args.reject_regex):
                    continue
                if args.list_only:
                    print(entry.url)
                    continue
                batch.append(fetch(entry.url, child_out))
            if batch:
                failures += sum(await asyncio.gather(*batch))
    finally:
        await sources.close()
    return 1 if failures else 0


async def download(args: argparse.Namespace) -> int:
    sock = args.sock
    if args.recursive and args.list_only:
        # pure listing never touches the daemon
        return await recursive_download(None, args)
    if not await ensure_daemon(
        sock, args.scheduler, args.storage,
        no_spawn=args.no_spawn, spawn_timeout=args.spawn_timeout,
    ):
        return 1

    client = RpcClient(sock, timeout=args.timeout)
    try:
        if args.recursive:
            return await recursive_download(client, args)
        await single_download(client, args, args.url, args.output)
        return 0
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await client.close()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="dfget", description="P2P file download")
    ap.add_argument("url", help="source URL (http/https/file)")
    ap.add_argument("-O", "--output", required=True, help="output file path")
    ap.add_argument("--scheduler", default=os.environ.get("DF_SCHEDULER", "127.0.0.1:9000"))
    ap.add_argument("--sock", default=os.environ.get("DF_DAEMON_SOCK", DEFAULT_SOCK))
    ap.add_argument("--storage", default=None, help="daemon storage root (spawn only)")
    ap.add_argument("--tag", default="")
    ap.add_argument("--application", default="")
    ap.add_argument("--digest", default="", help="expected digest algo:hex")
    ap.add_argument("--range", default="",
                    help="byte range START-END (inclusive) to export from the task")
    ap.add_argument("--filter", action="append", default=[], help="query params to drop from task id")
    ap.add_argument("--recursive", action="store_true",
                    help="treat URL as a directory and mirror it under --output")
    ap.add_argument("--level", type=int, default=0,
                    help="recursion depth limit (0 = unlimited)")
    ap.add_argument("--accept-regex", default="", help="only download matching URLs")
    ap.add_argument("--reject-regex", default="", help="skip matching URLs")
    ap.add_argument("--list-only", action="store_true",
                    help="with --recursive: print file URLs without downloading")
    ap.add_argument("--jobs", type=int, default=8,
                    help="concurrent file downloads under --recursive")
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--spawn-timeout", type=float, default=10.0)
    ap.add_argument("--no-spawn", action="store_true", help="fail if daemon absent")
    ap.add_argument("--trace-file", default=os.environ.get("DRAGONFLY_TRACE_FILE", ""),
                    help="record this invocation's trace spans (JSON lines; "
                         "sampled at 100%% — merge with the services' files "
                         "via dftrace)")
    args = ap.parse_args(argv)
    from dragonfly2_tpu.observability.tracing import configure_default_tracer

    # --trace-file: always sampled — the operator asked for THIS download's
    # timeline, not a 1% draw. Without it, the root still opens but at the
    # SERVICE default rate: a bare dfget must not ship an always-sampled
    # context that forces the whole cluster to record every download.
    configure_default_tracer(
        "dfget",
        trace_file=args.trace_file or None,
        sample_rate=1.0 if args.trace_file else None,
    )
    return asyncio.run(download(args))


if __name__ == "__main__":
    sys.exit(main())
