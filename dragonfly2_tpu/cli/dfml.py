"""dfml: the ML plane's operator CLI — decision-record replay and
training-run history (ISSUE 15).

`explain` answers "why did THOSE parents win that scheduling round": it
fetches the scheduler's sampled decision records (scheduler/evaluator.py
DecisionRecorder over the `decision_records` RPC; also at /debug/decisions),
replays the recorded score vector through the SAME stable top-k argsort the
scheduler used, asserts the replayed choice matches the recorded one
bit-exact, and prints the per-candidate evidence — scores, ranks, and the
feature columns that separated winners from losers.

`decisions` lists recent records; `train` prints the trainer's per-run
manifests (run id, dataset size, steps, final loss, wall) with ASCII loss
curves from the bounded per-run telemetry.

  dfml explain   --scheduler host:port TASK CHILD
  dfml decisions --scheduler host:port [--task T] [--limit N] [--json]
  dfml train     --trainer host:port [--json] [--no-curves]

Exit codes: 0 ok; 1 RPC/usage error; 2 no matching record; 3 replay
mismatch (the recorded chosen set does not reproduce from the recorded
scores — a determinism bug worth paging on).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

import numpy as np

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float], width: int = 48) -> str:
    """Bounded ASCII curve: downsample to `width` evenly-spaced samples
    (linspace, so the FIRST and LAST points always render — a stride-and-
    truncate would drop the curve's tail, hiding end-of-run divergence),
    scaled to the 8-level block ramp. Non-finite points render as '!'."""
    if not values:
        return ""
    idxs = np.linspace(0, len(values) - 1, min(width, len(values)))
    vals = [values[int(round(i))] for i in idxs]
    finite = [v for v in vals if np.isfinite(v)]
    if not finite:
        return "!" * len(vals)
    lo, hi = min(finite), max(finite)
    span = (hi - lo) or 1.0
    out = []
    for v in vals:
        if not np.isfinite(v):
            out.append("!")
        else:
            out.append(_SPARK[int((v - lo) / span * (len(_SPARK) - 1))])
    return "".join(out)


def replay_topk(scores: list[float], k: int) -> list[int]:
    """EXACTLY Scheduling._top_parents' selection: stable argsort of the
    negated scores, first k indices. The bit-exact replay contract the
    mlobs-smoke leg gates on lives here."""
    order = np.argsort(-np.asarray(scores, np.float64), kind="stable")
    return [int(i) for i in order[:k]]


def explain_record(record: dict, *, out=print) -> bool:
    """Render one decision record + verify the replay. Returns replay_exact."""
    scores = record["scores"]
    parents = record["parents"]
    k = int(record.get("topk", 4))
    replay_idx = replay_topk(scores, k)
    replayed = [parents[i]["peer"] for i in replay_idx]
    exact = replayed == list(record.get("chosen", []))
    out(
        f"decision seq={record['seq']} ts={record['ts']:.3f} "
        f"task={record['task_id']} child={record['child_peer']}@{record['child_host']}"
    )
    out(
        f"  model={record.get('model_version') or '<base>'} "
        f"mode={record.get('serving_mode', '?')} "
        f"trace={record.get('trace_id') or '-'} "
        f"candidates={len(parents)} topk={k}"
    )
    feats = record.get("feats")
    fnames = None
    fmat = None
    if feats:
        from dragonfly2_tpu.models.features import FEATURE_NAMES

        if len(feats[0]) == len(FEATURE_NAMES):
            fnames = FEATURE_NAMES
        fmat = np.asarray(feats, np.float64)
        col_mean = fmat.mean(axis=0)
    order = np.argsort(-np.asarray(scores, np.float64), kind="stable")
    chosen_set = set(record.get("chosen", []))
    for rank, i in enumerate(order):
        p = parents[int(i)]
        mark = "*" if p["peer"] in chosen_set else " "
        line = (
            f"  {mark} #{rank + 1:<2} {p['peer']:<24} host={p['host']:<16} "
            f"score={scores[int(i)]:+.6f}"
        )
        if fmat is not None and fnames is not None and rank < k:
            # the columns that most separate this winner from the field:
            # largest |value - candidate-set mean| — model-agnostic evidence
            # (base/MLP weights are linear; the GNN's saliency is not, but
            # "what was unusual about this candidate" is always answerable)
            row = fmat[int(i)]
            top = np.argsort(-np.abs(row - col_mean))[:3]
            line += "  " + " ".join(
                f"{fnames[j]}={row[j]:.3f}(μ{col_mean[j]:+.3f})" for j in top
            )
        out(line)
    verdict = (
        "== recorded (bit-exact)" if exact
        else f"!= recorded {list(record.get('chosen', []))}"
    )
    out(f"  replay: argsort(stable) top-{k} -> {replayed} {verdict}")
    return exact


async def _explain(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.rpc.scheduler import RemoteSchedulerClient

    sc = RemoteSchedulerClient(args.scheduler, timeout=args.timeout)
    try:
        doc = await sc.decision_records(
            task_id=args.task, child=args.child, limit=args.limit
        )
    finally:
        await sc.close()
    records = doc.get("records") or []
    if args.json:
        # machine-readable: ONLY the JSON document on stdout (with the
        # replay verdict folded in), same contract as the sibling
        # subcommands — the human rendering below must not trail it
        verdicts = [
            [r["parents"][i]["peer"] for i in replay_topk(r["scores"], int(r.get("topk", 4)))]
            == list(r.get("chosen", []))
            for r in records
        ]
        print(json.dumps(
            {**doc, "records": records, "replay_exact": verdicts},
            indent=2, default=str,
        ))
        if not records:
            return 2
        return 0 if all(verdicts) else 3
    if not records:
        stats = doc.get("recorder") or {}
        print(
            f"no recorded decision for task={args.task} child={args.child} "
            f"(recorder: {stats.get('records', 0)} records, sample_rate="
            f"{stats.get('sample_rate')}; raise DRAGONFLY_DECISION_SAMPLE "
            f"or retry after more rounds)",
            file=sys.stderr,
        )
        return 2
    drift = doc.get("drift") or {}
    if drift.get("psi_max") is not None:
        from dragonfly2_tpu.observability.sketches import classify_psi

        label = classify_psi(drift["psi_max"])
        flag = f" [{label.upper()} SHIFT]" if label != "stable" else ""
        print(
            f"feature drift vs {drift.get('reference_version') or '?'}: "
            f"psi_max={drift['psi_max']}{flag} "
            f"drifted={drift.get('drifted') or []}"
        )
    ok = True
    for record in records[: 1 if not args.all else len(records)]:
        if not explain_record(record):
            ok = False
    return 0 if ok else 3


async def _decisions(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.rpc.scheduler import RemoteSchedulerClient

    sc = RemoteSchedulerClient(args.scheduler, timeout=args.timeout)
    try:
        doc = await sc.decision_records(
            task_id=args.task, limit=args.limit, with_features=False
        )
    finally:
        await sc.close()
    if args.json:
        print(json.dumps(doc, indent=2, default=str))
        return 0
    stats = doc.get("recorder") or {}
    print(
        f"decision recorder: {stats.get('records', 0)} records "
        f"(sample_rate={stats.get('sample_rate')}, "
        f"rounds_seen={stats.get('rounds_seen')}), serving="
        f"{doc.get('serving_version') or '<base>'}"
    )
    for r in doc.get("records") or []:
        print(
            f"  seq={r['seq']:<5} ts={r['ts']:.3f} task={r['task_id']:<20} "
            f"child={r['child_peer']:<22} candidates={len(r['parents']):<3} "
            f"chosen={','.join(r['chosen'])}"
        )
    return 0


async def _train(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.rpc.trainer import RemoteTrainerClient

    tc = RemoteTrainerClient(args.trainer, timeout=args.timeout)
    try:
        doc = await tc.train_history(
            limit=args.limit, with_curves=not args.no_curves
        )
    finally:
        await tc.close()
    if args.json:
        print(json.dumps(doc, indent=2, default=str))
        return 0
    runs = doc.get("runs") or []
    print(f"train runs: {doc.get('total', len(runs))} recorded")
    if not runs:
        return 0
    for r in runs:
        ds = r.get("dataset") or {}
        print(
            f"  {r['run_id']:<22} {r.get('status', '?'):<8} "
            f"pairs={ds.get('pairs', 0):<8} nodes={ds.get('nodes', 0):<7} "
            f"wall={r.get('wall_s', 0.0):>7.2f}s"
        )
        for m, info in sorted((r.get("models") or {}).items()):
            line = (
                f"    {m}: steps={info.get('steps', 0)} "
                f"loss={info.get('final_loss')} "
                f"grad_norm={info.get('grad_norm')} "
                f"steps/s={info.get('steps_per_sec')}"
            )
            print(line)
            curve = info.get("curve") or []
            if curve and not args.no_curves:
                print(f"    {m} loss {sparkline([c[1] for c in curve])}")
    return 0


async def _amain(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.rpc.core import RpcError

    try:
        if args.cmd == "explain":
            return await _explain(args)
        if args.cmd == "decisions":
            return await _decisions(args)
        return await _train(args)
    except RpcError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dfml",
        description="ML-plane observability: decision replay + train history",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("explain", help="replay a recorded scoring decision")
    p.add_argument("--scheduler", required=True, help="scheduler RPC host:port")
    p.add_argument("task", help="task id the round scheduled")
    p.add_argument("child", help="child peer id or child host id")
    p.add_argument("--limit", type=int, default=8)
    p.add_argument("--all", action="store_true",
                   help="explain every matching record, not just the newest")
    p.add_argument("--json", action="store_true")
    p.add_argument("--timeout", type=float, default=10.0)

    p = sub.add_parser("decisions", help="list recent decision records")
    p.add_argument("--scheduler", required=True, help="scheduler RPC host:port")
    p.add_argument("--task", default=None)
    p.add_argument("--limit", type=int, default=16)
    p.add_argument("--json", action="store_true")
    p.add_argument("--timeout", type=float, default=10.0)

    p = sub.add_parser("train", help="training-run history + loss curves")
    p.add_argument("--trainer", required=True, help="trainer RPC host:port")
    p.add_argument("--limit", type=int, default=16)
    p.add_argument("--no-curves", action="store_true")
    p.add_argument("--json", action="store_true")
    p.add_argument("--timeout", type=float, default=10.0)

    args = ap.parse_args(argv)
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    sys.exit(main())
