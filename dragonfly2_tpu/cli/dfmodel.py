"""dfmodel: publish / fetch model checkpoints over the P2P fabric, and drive
the serving-model rollout state machine.

The config-4 CLI (no reference equivalent — SURVEY.md §2.4 flags this as the
new TPU-VM component): `publish` imports a checkpoint directory into the P2P
cache and emits a manifest; `fetch` pulls a manifest's files onto this host
through the piece engine (warm peers serve over DCN, origin touched once per
cluster).

  python -m dragonfly2_tpu.cli.dfmodel publish ./llama-3-8b
  python -m dragonfly2_tpu.cli.dfmodel fetch ./llama-3-8b/dragonfly-checkpoint.json -O ./staged

Rollout operations (ISSUE 11) talk straight to the MANAGER registry — no
daemon involved:

  dfmodel status   --manager host:port [--type gnn]
  dfmodel promote  --manager host:port --version vNNN   (or --id N)
  dfmodel rollback --manager host:port [--type gnn] [--reason why]

`status` prints the active row, candidates mid-shadow (with their aggregate
divergence windows), and recent rejects; `promote` pushes a candidate /
shadowing version active (the manual gate when auto_promote is off);
`rollback` rejects the current active version and re-activates the previous
one — the registry half of what a scheduler's auto-rollback does on a
post-swap health regression.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

from dragonfly2_tpu.cli.dfget import DEFAULT_SOCK, ensure_daemon
from dragonfly2_tpu.rpc.core import RpcClient, RpcError

ROLLOUT_CMDS = ("status", "promote", "rollback")


async def _rollout_main(args: argparse.Namespace) -> int:
    """Manager-registry subcommands (status/promote/rollback)."""
    from dragonfly2_tpu.rpc.manager import RemoteManagerClient

    mc = RemoteManagerClient(args.manager, timeout=args.timeout)
    try:
        if args.cmd == "status":
            st = await mc.rollout_status(args.type, args.scheduler_id)
            if args.json:
                print(json.dumps(st, indent=2, default=str))
                return 0
            pol = st["policy"]
            print(
                f"rollout[{args.type}]: gated={pol['gated']} "
                f"auto_promote={pol['auto_promote']} "
                f"min_rounds={pol['gates']['min_rounds']}"
            )
            act = st["active"]
            print(
                "  active:    "
                + (f"{act['version']} (id {act['id']})" if act else "<none>")
            )
            for c in st["candidates"]:
                agg = (c.get("rollout") or {}).get("aggregate") or {}
                # worst-round slicing (ISSUE 12): min/p99 expose a candidate
                # that is fine on average but catastrophic on a sliver of
                # rounds — the aggregate means alone hid that
                mn = agg.get("topk_overlap_min")
                p99 = agg.get("abs_delta_p99")
                topk = f"topk={agg.get('topk_overlap_mean', 0.0):.3f}"
                if mn is not None:
                    topk += f"(min={mn:.3f})"
                delta = f"delta={agg.get('abs_delta_mean', 0.0):.4f}"
                if p99 is not None:
                    delta += f"(p99<={p99:.3f})"
                print(
                    f"  {c['state']:<9}  {c['version']} (id {c['id']})"
                    f"  rounds={agg.get('rounds', 0)}"
                    f" {topk}"
                    f" corr={agg.get('rank_corr_mean', 0.0):.3f}"
                    f" {delta}"
                    f" errors={agg.get('errors', 0)}"
                )
                # per-population slicing (ISSUE 19): region × peer-count-band
                # buckets expose a candidate that only mis-ranks one child
                # population (e.g. a single region's flash crowds)
                ws = agg.get("worst_slice")
                sl = (agg.get("slices") or {}).get(ws)
                if ws and sl:
                    print(
                        f"             worst slice {ws}:"
                        f" rounds={sl.get('rounds', 0)}"
                        f" topk={sl.get('topk_overlap_mean', 0.0):.3f}"
                        f"(min={sl.get('topk_overlap_min', 0.0):.3f})"
                        f" corr={sl.get('rank_corr_mean', 0.0):.3f}"
                        f" delta={sl.get('abs_delta_mean', 0.0):.4f}"
                    )
            for r in st["rejected"]:
                reason = (r.get("rollout") or {}).get("rejected_reason", "")
                print(f"  rejected:  {r['version']} (id {r['id']})  {reason}")
            # feature drift (ISSUE 15): each scheduler member's max PSI vs
            # the serving model's training reference, read off the stats
            # frames the members already push — best-effort (a cluster with
            # no frames yet just prints nothing extra)
            try:
                # ONE decision boundary: sketches.classify_psi is what the
                # alert rule and dfml read too
                from dragonfly2_tpu.observability.sketches import classify_psi

                cs = await mc.cluster_stats()
                for m in cs.get("members") or []:
                    if m.get("source_type") != "scheduler":
                        continue
                    rates = (m.get("frame") or {}).get("rates") or {}
                    drift = rates.get("feature_drift_max")
                    if drift is None:
                        continue
                    label = classify_psi(drift)
                    flag = f" [{label}]" if label != "stable" else ""
                    print(
                        f"  drift:     {m.get('hostname', '?')} "
                        f"feature_drift_max={drift:.3f}{flag}"
                    )
            except Exception:  # dflint: disable=DF031 drift line is best-effort decoration on status — a frameless cluster or old manager must not fail the command
                pass
            return 0
        if args.cmd == "promote":
            model_id = args.id
            if model_id is None:
                # scheduler_id is part of the row key (UNIQUE(type, version,
                # scheduler_id)) — without it the lowest-id row of ANOTHER
                # scheduler could be promoted instead of the one asked for
                rows = await mc.list_models(
                    type=args.type, version=args.version,
                    scheduler_id=args.scheduler_id,
                )
                if not rows:
                    print(
                        f"error: no {args.type} model {args.version} "
                        f"(scheduler_id {args.scheduler_id})",
                        file=sys.stderr,
                    )
                    return 1
                model_id = rows[0]["id"]
            row = await mc.promote_model(model_id)
            print(json.dumps({"id": row["id"], "version": row["version"], "state": row["state"]}))
            return 0
        # rollback
        out = await mc.rollback_model(args.type, args.scheduler_id, reason=args.reason)
        print(
            json.dumps(
                {
                    "rolled_back": out["rolled_back"]["version"],
                    "active": out["active"]["version"],
                }
            )
        )
        return 0
    except RpcError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await mc.close()


async def _amain(args: argparse.Namespace) -> int:
    if args.cmd in ROLLOUT_CMDS:
        if not args.manager:
            print(f"error: dfmodel {args.cmd} requires --manager", file=sys.stderr)
            return 2
        return await _rollout_main(args)
    if not await ensure_daemon(
        args.sock, args.scheduler, args.storage,
        no_spawn=args.no_spawn, spawn_timeout=args.spawn_timeout,
    ):
        return 1
    client = RpcClient(args.sock, timeout=args.timeout)
    try:
        # abspath everything: the detached daemon's cwd is not ours
        if args.cmd == "publish":
            result = await client.call(
                "publish_checkpoint",
                {"directory": os.path.abspath(args.directory), "name": args.name},
            )
            print(json.dumps(result))
        elif args.cmd == "fetch":
            manifest = args.manifest
            if "://" not in manifest:
                manifest = os.path.abspath(manifest)
            result = await client.call(
                "fetch_checkpoint",
                {
                    "manifest": manifest,
                    "dest": os.path.abspath(args.output),
                    "concurrency": args.concurrency,
                },
            )
            print(json.dumps(result))
        return 0
    except RpcError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await client.close()


def main() -> None:
    ap = argparse.ArgumentParser(prog="dfmodel", description="P2P checkpoint fan-out CLI")
    ap.add_argument("--sock", default=DEFAULT_SOCK)
    ap.add_argument("--scheduler", default=None, help="scheduler addr (spawn only)")
    ap.add_argument("--storage", default=None, help="daemon storage root (spawn only)")
    ap.add_argument("--timeout", type=float, default=3600.0)
    ap.add_argument("--spawn-timeout", type=float, default=15.0)
    ap.add_argument("--no-spawn", action="store_true")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("publish", help="import a checkpoint dir into the P2P cache")
    p.add_argument("directory")
    p.add_argument("--name", default="")
    p = sub.add_parser("fetch", help="pull a manifest's files through P2P")
    p.add_argument("manifest", help="manifest path or URL")
    p.add_argument("-O", "--output", required=True)
    p.add_argument("--concurrency", type=int, default=4)

    def rollout_parser(name: str, help_: str):
        rp = sub.add_parser(name, help=help_)
        rp.add_argument("--manager", required=True, help="manager address host:port")
        rp.add_argument("--type", default="gnn", help="model type (default gnn)")
        rp.add_argument("--scheduler-id", type=int, default=0)
        return rp

    p = rollout_parser("status", "rollout state: active / shadowing / rejected versions")
    p.add_argument("--json", action="store_true")
    p = rollout_parser("promote", "promote a candidate/shadowing version to active")
    p.add_argument("--version", default=None)
    p.add_argument("--id", type=int, default=None)
    p = rollout_parser("rollback", "reject the active version, re-activate the previous")
    p.add_argument("--reason", default="operator rollback")
    args = ap.parse_args()
    if args.cmd == "promote" and args.version is None and args.id is None:
        ap.error("promote needs --version or --id")
    sys.exit(asyncio.run(_amain(args)))


if __name__ == "__main__":
    main()
