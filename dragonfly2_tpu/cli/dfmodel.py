"""dfmodel: publish / fetch model checkpoints over the P2P fabric.

The config-4 CLI (no reference equivalent — SURVEY.md §2.4 flags this as the
new TPU-VM component): `publish` imports a checkpoint directory into the P2P
cache and emits a manifest; `fetch` pulls a manifest's files onto this host
through the piece engine (warm peers serve over DCN, origin touched once per
cluster).

  python -m dragonfly2_tpu.cli.dfmodel publish ./llama-3-8b
  python -m dragonfly2_tpu.cli.dfmodel fetch ./llama-3-8b/dragonfly-checkpoint.json -O ./staged
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

from dragonfly2_tpu.cli.dfget import DEFAULT_SOCK, ensure_daemon
from dragonfly2_tpu.rpc.core import RpcClient, RpcError


async def _amain(args: argparse.Namespace) -> int:
    if not await ensure_daemon(
        args.sock, args.scheduler, args.storage,
        no_spawn=args.no_spawn, spawn_timeout=args.spawn_timeout,
    ):
        return 1
    client = RpcClient(args.sock, timeout=args.timeout)
    try:
        # abspath everything: the detached daemon's cwd is not ours
        if args.cmd == "publish":
            result = await client.call(
                "publish_checkpoint",
                {"directory": os.path.abspath(args.directory), "name": args.name},
            )
            print(json.dumps(result))
        elif args.cmd == "fetch":
            manifest = args.manifest
            if "://" not in manifest:
                manifest = os.path.abspath(manifest)
            result = await client.call(
                "fetch_checkpoint",
                {
                    "manifest": manifest,
                    "dest": os.path.abspath(args.output),
                    "concurrency": args.concurrency,
                },
            )
            print(json.dumps(result))
        return 0
    except RpcError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await client.close()


def main() -> None:
    ap = argparse.ArgumentParser(prog="dfmodel", description="P2P checkpoint fan-out CLI")
    ap.add_argument("--sock", default=DEFAULT_SOCK)
    ap.add_argument("--scheduler", default=None, help="scheduler addr (spawn only)")
    ap.add_argument("--storage", default=None, help="daemon storage root (spawn only)")
    ap.add_argument("--timeout", type=float, default=3600.0)
    ap.add_argument("--spawn-timeout", type=float, default=15.0)
    ap.add_argument("--no-spawn", action="store_true")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("publish", help="import a checkpoint dir into the P2P cache")
    p.add_argument("directory")
    p.add_argument("--name", default="")
    p = sub.add_parser("fetch", help="pull a manifest's files through P2P")
    p.add_argument("manifest", help="manifest path or URL")
    p.add_argument("-O", "--output", required=True)
    p.add_argument("--concurrency", type=int, default=4)
    args = ap.parse_args()
    sys.exit(asyncio.run(_amain(args)))


if __name__ == "__main__":
    main()
