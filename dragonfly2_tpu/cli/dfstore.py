"""dfstore: object storage through the daemon's P2P object gateway.

Parity with reference client/dfstore/dfstore.go:41-71 (Dfstore SDK:
Get/Put/Delete/IsExist + request builders) and cmd/dfstore. SDK class
`Dfstore` + argparse CLI:

  python -m dragonfly2_tpu.cli.dfstore put  local.bin  df://bucket/key
  python -m dragonfly2_tpu.cli.dfstore get  df://bucket/key  local.bin
  python -m dragonfly2_tpu.cli.dfstore stat df://bucket/key
  python -m dragonfly2_tpu.cli.dfstore rm   df://bucket/key
  python -m dragonfly2_tpu.cli.dfstore ls   df://bucket[/prefix]
  python -m dragonfly2_tpu.cli.dfstore make-bucket df://bucket
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from dataclasses import dataclass
from pathlib import Path
from urllib.parse import quote

import aiohttp

DEFAULT_ENDPOINT = "http://127.0.0.1:65004"


class DfstoreError(Exception):
    pass


@dataclass
class DfUrl:
    """df://bucket/key/with/slashes"""

    bucket: str
    key: str = ""

    @classmethod
    def parse(cls, s: str) -> "DfUrl":
        if not s.startswith("df://"):
            raise DfstoreError(f"expected df://bucket/key url, got {s!r}")
        rest = s[len("df://"):]
        bucket, _, key = rest.partition("/")
        if not bucket:
            raise DfstoreError(f"missing bucket in {s!r}")
        return cls(bucket=bucket, key=key)


class Dfstore:
    """SDK over the daemon object gateway (ref Dfstore interface)."""

    def __init__(self, endpoint: str = DEFAULT_ENDPOINT, *, timeout: float = 300.0):
        self.endpoint = endpoint.rstrip("/")
        # stall-based, not total: a total cap would abort exactly the
        # multi-GB streaming transfers put_file/get_object_to_file exist for
        self._timeout = aiohttp.ClientTimeout(
            total=None, connect=30.0, sock_read=timeout
        )
        self._session: aiohttp.ClientSession | None = None

    def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(timeout=self._timeout)
        return self._session

    def _obj_url(self, bucket: str, key: str) -> str:
        return f"{self.endpoint}/buckets/{quote(bucket)}/objects/{quote(key)}"

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    @staticmethod
    async def _raise_for(resp: aiohttp.ClientResponse) -> None:
        if resp.status >= 400:
            try:
                detail = (await resp.json()).get("error", "")
            except Exception:
                detail = await resp.text()
            raise DfstoreError(f"HTTP {resp.status}: {detail}")

    async def create_bucket(self, bucket: str) -> None:
        async with self._sess().put(f"{self.endpoint}/buckets/{quote(bucket)}") as r:
            await self._raise_for(r)

    async def list_buckets(self) -> list[dict]:
        async with self._sess().get(f"{self.endpoint}/buckets") as r:
            await self._raise_for(r)
            return (await r.json())["buckets"]

    async def put_object(
        self, bucket: str, key: str, data: bytes, *, seed: bool = False
    ) -> dict:
        url = self._obj_url(bucket, key) + ("?seed=1" if seed else "")
        async with self._sess().put(url, data=data) as r:
            await self._raise_for(r)
            return await r.json()

    async def put_file(
        self, bucket: str, key: str, path: str | Path, *, seed: bool = False,
        chunk_size: int = 1 << 20,
    ) -> dict:
        """Stream a file up without holding it in RAM (the gateway streams
        the body straight into the backend's multipart path)."""
        url = self._obj_url(bucket, key) + ("?seed=1" if seed else "")

        async def chunks():
            with open(path, "rb") as f:
                while True:
                    b = await asyncio.to_thread(f.read, chunk_size)
                    if not b:
                        return
                    yield b

        async with self._sess().put(url, data=chunks()) as r:
            await self._raise_for(r)
            return await r.json()

    async def get_object(self, bucket: str, key: str, *, direct: bool = False) -> bytes:
        url = self._obj_url(bucket, key) + ("?mode=direct" if direct else "")
        async with self._sess().get(url) as r:
            await self._raise_for(r)
            return await r.read()

    async def get_object_to_file(
        self, bucket: str, key: str, dest: str | Path, *, direct: bool = False,
        chunk_size: int = 1 << 20,
    ) -> int:
        """Stream an object to disk without holding it in RAM; returns bytes
        written. Writes a temp file and renames on success so a mid-stream
        failure never leaves a silently-truncated dest behind."""
        url = self._obj_url(bucket, key) + ("?mode=direct" if direct else "")
        dest = Path(dest)
        tmp = dest.with_name(dest.name + ".dfstore-partial")
        n = 0
        try:
            async with self._sess().get(url) as r:
                await self._raise_for(r)
                with open(tmp, "wb") as f:
                    async for chunk in r.content.iter_chunked(chunk_size):
                        await asyncio.to_thread(f.write, chunk)
                        n += len(chunk)
            tmp.replace(dest)
        except BaseException:
            tmp.unlink(missing_ok=True)
            raise
        return n

    async def stat_object(self, bucket: str, key: str) -> dict:
        async with self._sess().head(self._obj_url(bucket, key)) as r:
            if r.status == 404:
                raise DfstoreError(f"object {bucket}/{key} not found")
            await self._raise_for(r)
            return {
                "content_length": int(r.headers.get("Content-Length", -1)),
                "content_type": r.headers.get("Content-Type", ""),
                "etag": r.headers.get("ETag", ""),
                "digest": r.headers.get("X-Dragonfly-Digest", ""),
            }

    async def is_object_exist(self, bucket: str, key: str) -> bool:
        try:
            await self.stat_object(bucket, key)
            return True
        except DfstoreError:
            return False

    async def delete_object(self, bucket: str, key: str) -> None:
        async with self._sess().delete(self._obj_url(bucket, key)) as r:
            await self._raise_for(r)

    async def list_objects(self, bucket: str, prefix: str = "") -> list[dict]:
        url = f"{self.endpoint}/buckets/{quote(bucket)}/objects"
        async with self._sess().get(url, params={"prefix": prefix}) as r:
            await self._raise_for(r)
            return (await r.json())["objects"]


async def _amain(args: argparse.Namespace) -> int:
    store = Dfstore(args.endpoint)
    try:
        if args.cmd == "make-bucket":
            await store.create_bucket(DfUrl.parse(args.url).bucket)
            print("created")
        elif args.cmd == "put":
            u = DfUrl.parse(args.dest)
            out = await store.put_file(
                u.bucket, u.key or Path(args.src).name, args.src, seed=args.seed
            )
            print(json.dumps(out))
        elif args.cmd == "get":
            u = DfUrl.parse(args.src)
            n = await store.get_object_to_file(
                u.bucket, u.key, args.dest, direct=args.direct
            )
            print(f"{n} bytes -> {args.dest}")
        elif args.cmd == "stat":
            u = DfUrl.parse(args.url)
            print(json.dumps(await store.stat_object(u.bucket, u.key)))
        elif args.cmd == "rm":
            u = DfUrl.parse(args.url)
            await store.delete_object(u.bucket, u.key)
            print("deleted")
        elif args.cmd == "ls":
            u = DfUrl.parse(args.url)
            for o in await store.list_objects(u.bucket, prefix=u.key):
                print(f"{o['content_length']:>12} {o['key']}")
        return 0
    except DfstoreError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await store.close()


def main() -> None:
    ap = argparse.ArgumentParser(prog="dfstore", description="P2P object storage CLI")
    ap.add_argument("--endpoint", default=DEFAULT_ENDPOINT, help="daemon object gateway")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("put")
    p.add_argument("src")
    p.add_argument("dest", help="df://bucket/key")
    p.add_argument("--seed", action="store_true", help="pre-populate the P2P cache")
    p = sub.add_parser("get")
    p.add_argument("src", help="df://bucket/key")
    p.add_argument("dest")
    p.add_argument("--direct", action="store_true", help="bypass P2P")
    for name in ("stat", "rm", "make-bucket"):
        p = sub.add_parser(name)
        p.add_argument("url", help="df://bucket[/key]")
    p = sub.add_parser("ls")
    p.add_argument("url", help="df://bucket[/prefix]")
    args = ap.parse_args()
    sys.exit(asyncio.run(_amain(args)))


if __name__ == "__main__":
    main()
