"""dftop: the cluster as one live screen.

Renders the manager's `cluster_stats` view — every member's windowed rates
(rounds/s, piece MB/s, loop lag p95, dispatcher utilization), serving mode,
rollout state, and active SLO alerts — refreshing in place like top(1).
The data is the stats frames services push on their keepalive ticks
(observability/timeseries.build_stats_frame), so dftop needs exactly one
RPC per refresh regardless of cluster size.

  python -m dragonfly2_tpu.cli.dftop --manager 127.0.0.1:9200
  python -m dragonfly2_tpu.cli.dftop --manager 127.0.0.1:9200 --once --json

--once --json prints one raw cluster_stats document and exits 0 when every
live member carries a fresh frame — the scripting/CI entry the check.sh
metrics-smoke leg drives.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

_CLEAR = "\x1b[2J\x1b[H"


def _fmt(v, nd: int = 2, width: int = 9) -> str:
    if v is None:
        return "-".rjust(width)
    if isinstance(v, float):
        return f"{v:.{nd}f}".rjust(width)
    return str(v).rjust(width)


def render(stats: dict, *, clear: bool = False) -> str:
    """One screenful of cluster state (pure text — unit-testable)."""
    cluster = stats.get("cluster") or {}
    rates = cluster.get("rates") or {}
    alerts = cluster.get("alerts") or []
    lines: list[str] = []
    if clear:
        lines.append(_CLEAR.rstrip("\n"))
    ts = stats.get("ts")
    when = time.strftime("%H:%M:%S", time.localtime(ts)) if ts else "-"
    lines.append(
        f"dftop — {when}  members {cluster.get('members_live', 0)} live"
        f" / {cluster.get('members_stale', 0)} stale"
        f"  cluster: {_fmt(rates.get('rounds_per_s')).strip()} rounds/s"
        f"  {_fmt(rates.get('piece_down_mb_per_s')).strip()} MB/s down"
        f"  {_fmt(rates.get('piece_up_mb_per_s')).strip()} MB/s up"
    )
    if alerts:
        names = ", ".join(f"{a['name']}@{a['member']}" for a in alerts)
        lines.append(f"ALERTS: {names}")
    else:
        lines.append("alerts: none")
    header = (
        f"{'member':<18} {'type':<9} {'age':>5} "
        f"{'work/s':>9} {'p95ms':>7} {'down MB/s':>10} {'up MB/s':>9} "
        f"{'cipher':>8} {'lag p95':>8} {'util':>5} {'deg':>4} {'serving':>8} "
        f"{'drift':>6} {'rollout':>12} alerts"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for m in stats.get("members") or []:
        frame = m.get("frame") or {}
        r = frame.get("rates") or {}
        name = m.get("hostname", "?")
        if m.get("stale"):
            name += " (stale)"
        member_alerts = ",".join(frame.get("alerts") or ()) or "-"
        # brownout rung (ISSUE 17): 0..4 while the member's degradation
        # ladder is engaged; a member in manager-blackout autonomy flags it
        # next to its alerts so the operator sees BOTH failure planes here
        if r.get("manager_unreachable"):
            member_alerts = (
                "mgr_down" if member_alerts == "-" else member_alerts + ",mgr_down"
            )
        # "work/s" is each member's native unit of work: scheduling rounds
        # for a scheduler, training steps for a trainer (ISSUE 15 — a
        # trainer member finally shows live learner work, not a blank)
        work = r.get("rounds_per_s")
        if work is None:
            work = r.get("train_steps_per_s")
        lines.append(
            f"{name:<18} {m.get('source_type', '?'):<9} "
            f"{_fmt(m.get('age_s'), 0, 5)} "
            f"{_fmt(work)} "
            f"{_fmt(r.get('round_p95_ms'), 2, 7)} "
            f"{_fmt(r.get('piece_down_mb_per_s'), 2, 10)} "
            f"{_fmt(r.get('piece_up_mb_per_s'), 2, 9)} "
            f"{str(frame.get('piece_cipher', '-')):>8} "
            f"{_fmt(r.get('loop_lag_p95_ms'), 1, 8)} "
            f"{_fmt(r.get('dispatcher_utilization'), 2, 5)} "
            f"{_fmt(r.get('degradation_level'), 0, 4)} "
            f"{str(frame.get('serving_mode', '-')):>8} "
            f"{_fmt(r.get('feature_drift_max'), 2, 6)} "
            f"{str(frame.get('rollout_state', '-')):>12} "
            f"{member_alerts}"
        )
    if not stats.get("members"):
        lines.append("(no members have reported a stats frame yet)")
    return "\n".join(lines)


def members_healthy(stats: dict, *, max_age_s: float | None = None) -> bool:
    """True when every non-stale member carries a frame with a rates dict
    (the --once exit-code contract the smoke leg gates on)."""
    members = [m for m in (stats.get("members") or []) if not m.get("stale")]
    if not members:
        return False
    for m in members:
        frame = m.get("frame") or {}
        if not isinstance(frame.get("rates"), dict):
            return False
        if max_age_s is not None and m.get("age_s", 1e9) > max_age_s:
            return False
    return True


async def _amain(args: argparse.Namespace) -> int:
    from dragonfly2_tpu.rpc.core import RpcError
    from dragonfly2_tpu.rpc.manager import RemoteManagerClient

    mc = RemoteManagerClient(args.manager, timeout=args.timeout)
    try:
        if args.once:
            stats = await mc.cluster_stats(history=args.history)
            if args.json:
                print(json.dumps(stats, indent=2, default=str))
            else:
                print(render(stats))
            return 0 if members_healthy(stats) else 3
        while True:
            try:
                stats = await mc.cluster_stats()
                print(render(stats, clear=True), flush=True)
            except RpcError as e:
                print(f"{_CLEAR}dftop: manager unreachable: {e}", flush=True)
            await asyncio.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    except RpcError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await mc.close()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dftop", description="live cluster metrics dashboard (manager cluster_stats)"
    )
    ap.add_argument("--manager", required=True, help="manager RPC address host:port")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh cadence in seconds (live mode)")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (0 = every live member "
                         "reported a frame, 3 = members missing/frameless)")
    ap.add_argument("--json", action="store_true",
                    help="with --once: print the raw cluster_stats JSON")
    ap.add_argument("--history", type=int, default=0,
                    help="with --once: include the last N frames per member")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    try:
        return asyncio.run(_amain(args))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
