"""dfcluster: the cluster-in-a-box — a real federation on localhost,
OUTSIDE pytest.

Boots manager + N schedulers (federated) + N daemons + an HTTP origin as
real subprocesses, runs a real dfget through the federation (first daemon
seeds from origin, second rides P2P), byte-verifies the outputs, and tears
everything down. The missing deploy story for ROADMAP #3 (the reference
ships deploy/docker-compose; this is the zero-dependency localhost
equivalent):

    python -m dragonfly2_tpu.cli.dfcluster demo
    python -m dragonfly2_tpu.cli.dfcluster demo --keep     # stay up, Ctrl-C to stop
    python -m dragonfly2_tpu.cli.dfcluster demo --swarm 100  # + dfstress swarm

With --verify-trace every process writes a span file and the run asserts
the federation's tracing story end to end: the dfget's scheduling rounds
land on EXACTLY ONE scheduler (ring ownership) while federation sync spans
appear on EVERY scheduler (the gossip is live) — the same assertions
tools/check.sh's federation-smoke leg gates on.

Schedulers are chained with static --federation-peers (scheduler i lists
0..i-1): the push-pull sync converges both directions over a one-directional
peer edge, so the chain is enough for full convergence without waiting for
the manager's dynconfig refresh.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time


class ClusterError(RuntimeError):
    pass


class Cluster:
    """Subprocess lifecycle for one cluster-in-a-box."""

    def __init__(self, root: str, *, trace: bool = False, verbose: bool = False):
        self.root = root
        self.trace = trace
        self.verbose = verbose
        self.procs: list[tuple[str, subprocess.Popen]] = []
        self.manager_addr = ""
        self.scheduler_addrs: list[str] = []
        self.daemon_socks: list[str] = []
        self.origin_port = 0
        self.trace_dir = os.path.join(root, "traces")

    def _env(self, name: str) -> dict:
        env = dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), JAX_PLATFORMS="cpu")
        if self.trace:
            os.makedirs(self.trace_dir, exist_ok=True)
            env["DRAGONFLY_TRACE_FILE"] = os.path.join(self.trace_dir, f"{name}.jsonl")
            env["DRAGONFLY_TRACE_SAMPLE"] = "1.0"
        return env

    def _spawn(self, name: str, args: list[str], ready_prefix: str) -> str:
        stderr = None if self.verbose else subprocess.DEVNULL
        p = subprocess.Popen(
            [sys.executable, "-m", *args],
            stdout=subprocess.PIPE, stderr=stderr, text=True, env=self._env(name),
        )
        self.procs.append((name, p))
        line = p.stdout.readline()
        if not line.startswith(ready_prefix):
            raise ClusterError(f"{name} failed to start: {line!r}")
        return line

    def up(self, *, schedulers: int = 2, daemons: int = 2,
           federation_interval: float = 1.0, probe_interval: float = 2.0,
           extra_scheduler_args: list[str] | None = None,
           extra_daemon_args: list[str] | None = None) -> None:
        """extra_*_args append raw flags to every scheduler/daemon spawn —
        the hook harnesses (tools/metrics_smoke.py) use for fast keepalive
        cadences or an alternate evaluator without widening this signature
        per knob."""
        t0 = time.monotonic()
        line = self._spawn(
            "manager",
            ["dragonfly2_tpu.manager.server", "--port", "0", "--rest-port", "0",
             "--db", os.path.join(self.root, "manager.db")],
            "manager ready",
        )
        self.manager_addr = line.split("rpc=")[1].split()[0]
        for i in range(schedulers):
            args = [
                "dragonfly2_tpu.scheduler.server", "--port", "0",
                "--manager", self.manager_addr,
                "--hostname", f"sched-{i}",
                "--telemetry-dir", os.path.join(self.root, f"tel-{i}"),
                "--federation-interval", str(federation_interval),
            ]
            if self.scheduler_addrs:
                args += ["--federation-peers", ",".join(self.scheduler_addrs)]
            args += extra_scheduler_args or []
            line = self._spawn(f"scheduler-{i}", args, "SCHEDULER_READY")
            self.scheduler_addrs.append(line.split()[1])
        sched_spec = ",".join(self.scheduler_addrs)
        for i in range(daemons):
            sock = os.path.join(self.root, f"daemon-{i}.sock")
            self._spawn(
                f"daemon-{i}",
                ["dragonfly2_tpu.daemon.server",
                 "--scheduler", sched_spec,
                 "--manager", self.manager_addr,
                 "--sock", sock,
                 "--storage", os.path.join(self.root, f"store-{i}"),
                 "--hostname", f"box-daemon-{i}",
                 "--probe-interval", str(probe_interval),
                 *(extra_daemon_args or [])],
                "DAEMON_READY",
            )
            self.daemon_socks.append(sock)
        # plain stdlib HTTP origin (no Range support: the daemon's
        # sequential back-to-source path covers that shape too)
        origin_dir = os.path.join(self.root, "origin")
        os.makedirs(origin_dir, exist_ok=True)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            self.origin_port = s.getsockname()[1]
        stderr = None if self.verbose else subprocess.DEVNULL
        p = subprocess.Popen(
            [sys.executable, "-m", "http.server", str(self.origin_port),
             "--bind", "127.0.0.1", "--directory", origin_dir],
            stdout=subprocess.DEVNULL, stderr=stderr,
        )
        self.procs.append(("origin", p))
        deadline = time.monotonic() + 10
        import urllib.request

        while time.monotonic() < deadline:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{self.origin_port}/", timeout=1)
                break
            except OSError:
                time.sleep(0.1)
        else:
            raise ClusterError("origin server never came up")
        print(
            f"dfcluster: up in {time.monotonic() - t0:.1f}s — manager "
            f"{self.manager_addr}, schedulers {self.scheduler_addrs}, "
            f"{len(self.daemon_socks)} daemons, origin :{self.origin_port}",
            flush=True,
        )

    def write_origin_file(self, name: str, payload: bytes) -> str:
        path = os.path.join(self.root, "origin", name)
        with open(path, "wb") as f:
            f.write(payload)
        return f"http://127.0.0.1:{self.origin_port}/{name}"

    def dfget(self, daemon_index: int, url: str, out: str, *, timeout: float = 180.0,
              trace_name: str = "") -> subprocess.CompletedProcess:
        env = self._env(trace_name or f"dfget-{daemon_index}")
        cmd = [sys.executable, "-m", "dragonfly2_tpu.cli.dfget", url,
               "-O", out, "--sock", self.daemon_socks[daemon_index], "--no-spawn",
               "--scheduler", ",".join(self.scheduler_addrs)]
        if self.trace and trace_name:
            cmd += ["--trace-file", os.path.join(self.trace_dir, f"{trace_name}.jsonl")]
        return subprocess.run(cmd, capture_output=True, text=True, env=env, timeout=timeout)

    def down(self) -> None:
        for _, p in reversed(self.procs):
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for name, p in self.procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                print(f"dfcluster: {name} ignored SIGTERM, killing", file=sys.stderr)
                p.kill()
        self.procs.clear()


def verify_trace(cluster: Cluster, dfget_trace: str) -> None:
    """Federation tracing assertions (the check.sh federation-smoke gate):
    the dfget's scheduling rounds ride EXACTLY ONE scheduler; federation
    sync/apply spans show on EVERY scheduler."""
    from dragonfly2_tpu.cli import dftrace

    client_spans = dftrace.load_spans(
        [os.path.join(cluster.trace_dir, f"{dfget_trace}.jsonl")])
    roots = [s for s in client_spans if s["name"] == "dfget.download"]
    if not roots:
        raise ClusterError(f"no dfget.download root span in {dfget_trace}")
    trace_id = roots[0]["trace_id"]

    schedulers_with_rounds = []
    schedulers_with_federation = []
    for i in range(len(cluster.scheduler_addrs)):
        path = os.path.join(cluster.trace_dir, f"scheduler-{i}.jsonl")
        spans = dftrace.load_spans([path]) if os.path.exists(path) else []
        if any(
            s["trace_id"] == trace_id and s["name"].startswith("scheduler.")
            for s in spans
        ):
            schedulers_with_rounds.append(i)
        if any(s["name"].startswith("federation.") for s in spans):
            schedulers_with_federation.append(i)
    if len(schedulers_with_rounds) != 1:
        raise ClusterError(
            f"dfget trace {trace_id[:8]} scheduling spans on schedulers "
            f"{schedulers_with_rounds}; ring affinity wants exactly one"
        )
    if len(schedulers_with_federation) != len(cluster.scheduler_addrs):
        raise ClusterError(
            f"federation spans only on schedulers {schedulers_with_federation} "
            f"of {len(cluster.scheduler_addrs)}"
        )
    print(
        f"dfcluster: trace ok — task rounds on scheduler-"
        f"{schedulers_with_rounds[0]} only, federation spans on all "
        f"{len(schedulers_with_federation)} schedulers",
        flush=True,
    )


def demo(args: argparse.Namespace) -> int:
    root = args.dir or tempfile.mkdtemp(prefix="dfcluster-")
    os.makedirs(root, exist_ok=True)
    cluster = Cluster(root, trace=args.verify_trace or args.trace,
                      verbose=args.verbose)
    rc = 0
    try:
        cluster.up(schedulers=args.schedulers, daemons=args.daemons,
                   federation_interval=args.federation_interval)
        payload = os.urandom(args.payload_kb * 1024)
        want = hashlib.sha256(payload).hexdigest()
        url = cluster.write_origin_file("demo.bin", payload)

        t0 = time.monotonic()
        r = cluster.dfget(0, url, os.path.join(root, "out-seed.bin"),
                          trace_name="dfget-seed")
        if r.returncode != 0:
            raise ClusterError(f"seed dfget failed: {r.stderr}")
        seed_s = time.monotonic() - t0
        t0 = time.monotonic()
        r = cluster.dfget(1 % args.daemons, url, os.path.join(root, "out-p2p.bin"),
                          trace_name="dfget-p2p")
        if r.returncode != 0:
            raise ClusterError(f"p2p dfget failed: {r.stderr}")
        p2p_s = time.monotonic() - t0
        for out in ("out-seed.bin", "out-p2p.bin"):
            with open(os.path.join(root, out), "rb") as f:
                got = hashlib.sha256(f.read()).hexdigest()
            if got != want:
                raise ClusterError(f"{out} corrupt: {got[:12]} != {want[:12]}")
        print(
            f"dfcluster: dfget ok — {args.payload_kb} KiB seeded in "
            f"{seed_s:.1f}s, P2P copy in {p2p_s:.1f}s, both bit-exact",
            flush=True,
        )

        # wait for at least one federation gossip round, then show the
        # merged view from every member
        time.sleep(args.federation_interval * 2 + 0.5)
        states = _federation_states(cluster)
        for i, st in enumerate(states):
            print(f"dfcluster: scheduler-{i} federation_state: {json.dumps(st)}",
                  flush=True)

        if args.swarm:
            swarm_cmd = [
                sys.executable, "-m", "dragonfly2_tpu.cli.dfstress", "--swarm",
                "--schedulers", ",".join(cluster.scheduler_addrs),
                "--peers", str(args.swarm), "--duration", str(args.swarm_duration),
            ]
            r = subprocess.run(swarm_cmd, capture_output=True, text=True,
                               env=cluster._env("dfstress"), timeout=600)
            if r.returncode != 0:
                raise ClusterError(f"swarm failed: {r.stderr or r.stdout}")
            print(f"dfcluster: swarm {r.stdout.strip()}", flush=True)

        if args.keep:
            print("dfcluster: up — Ctrl-C to tear down", flush=True)
            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                pass

        if args.verify_trace:
            # SIGTERM first so every process flushes its span file fully
            cluster.down()
            verify_trace(cluster, "dfget-p2p")
    except ClusterError as e:
        print(f"dfcluster: FAIL — {e}", file=sys.stderr, flush=True)
        rc = 1
    except Exception as e:
        # unexpected failures (hung dfget -> TimeoutExpired, etc.) must also
        # take the rc=1 path, or the finally below would rmtree the state
        # dir the debugging message promises to keep
        import traceback

        traceback.print_exc()
        print(f"dfcluster: FAIL — unexpected {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        rc = 1
    finally:
        cluster.down()
        if args.dir is None and rc == 0:
            shutil.rmtree(root, ignore_errors=True)
        elif args.dir is None:
            print(f"dfcluster: state kept at {root} for debugging", file=sys.stderr)
    return rc


def _federation_states(cluster: Cluster) -> list[dict]:
    import asyncio

    from dragonfly2_tpu.rpc.scheduler import RemoteSchedulerClient

    async def fetch() -> list[dict]:
        out = []
        for addr in cluster.scheduler_addrs:
            c = RemoteSchedulerClient(addr, retries=0)
            try:
                out.append(await c.federation_state())
            except Exception as e:
                out.append({"error": str(e)})
            finally:
                await c.close()
        return out

    return asyncio.run(fetch())


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="dragonfly2_tpu cluster-in-a-box (manager + federated "
                    "schedulers + daemons + origin on localhost)"
    )
    ap.add_argument("command", choices=["demo"],
                    help="demo: boot, run a real dfget through the federation, "
                         "verify, tear down")
    ap.add_argument("--dir", default=None,
                    help="state directory (default: fresh temp dir, removed on success)")
    ap.add_argument("--schedulers", type=int, default=2)
    ap.add_argument("--daemons", type=int, default=2)
    # default payload is multi-piece (> the 4 MiB piece size): the P2P copy
    # then runs a real NORMAL scheduling round (the SMALL single-piece fast
    # path has no scheduler.schedule span for --verify-trace to find)
    ap.add_argument("--payload-kb", type=int, default=8192)
    ap.add_argument("--federation-interval", type=float, default=1.0)
    ap.add_argument("--trace", action="store_true",
                    help="write per-process span files under <dir>/traces")
    ap.add_argument("--verify-trace", action="store_true",
                    help="assert ring ownership + federation spans from the traces")
    ap.add_argument("--swarm", type=int, default=0,
                    help="after the dfget, drive N dfstress swarm peers")
    ap.add_argument("--swarm-duration", type=float, default=5.0)
    ap.add_argument("--keep", action="store_true",
                    help="stay up after the demo until Ctrl-C")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="pass subprocess stderr through")
    args = ap.parse_args(argv)
    if args.schedulers < 1 or args.daemons < 1:
        ap.error("need at least 1 scheduler and 1 daemon")
    return demo(args)


if __name__ == "__main__":
    sys.exit(main())
