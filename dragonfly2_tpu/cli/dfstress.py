"""Load generator for a peer daemon / cluster.

Reference equivalent: test/tools/stress (Makefile:303-309) — a concurrency
driver that hammers a target and reports latency percentiles. Here it drives
the daemon's download RPC with N concurrent workers for a duration (or a
fixed request count) and prints one JSON line: throughput, latency
p50/p90/p99, error count — the shape CI perf gates consume.

    python -m dragonfly2_tpu.cli.dfstress http://origin/file \\
        --sock /tmp/df.sock --concurrency 16 --duration 10

Each request downloads the SAME task (reuse fast path after the first), so
the tool measures control-plane + storage round-trip throughput, not origin
bandwidth; pass --unique to append a counter query param and force distinct
tasks (piece engine + scheduler path per request).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

import numpy as np

from dragonfly2_tpu.cli.dfget import DEFAULT_SOCK
from dragonfly2_tpu.rpc.core import RpcClient


async def run_stress(args: argparse.Namespace) -> dict:
    client = RpcClient(args.sock, timeout=args.timeout)
    latencies: list[float] = []
    errors = 0
    counter = 0
    stop_at = time.monotonic() + args.duration if args.count is None else None

    def next_url() -> str | None:
        # no await points: atomic on the single-threaded event loop
        nonlocal counter
        if args.count is not None and counter >= args.count:
            return None
        if stop_at is not None and time.monotonic() >= stop_at:
            return None
        counter += 1
        if args.unique:
            sep = "&" if "?" in args.url else "?"
            return f"{args.url}{sep}stress={counter}"
        return args.url

    async def worker() -> None:
        nonlocal errors
        while True:
            url = next_url()
            if url is None:
                return
            t0 = time.monotonic()
            try:
                await client.call(
                    "download", {"url": url, "output": None}, timeout=args.timeout
                )
                latencies.append(time.monotonic() - t0)
            except Exception:
                errors += 1

    t0 = time.monotonic()
    await asyncio.gather(*(worker() for _ in range(args.concurrency)))
    elapsed = time.monotonic() - t0
    await client.close()

    lat = np.asarray(latencies) * 1000.0
    return {
        "metric": "daemon_download_rps",
        "value": round(len(latencies) / max(elapsed, 1e-9), 1),
        "unit": "requests/s",
        "extra": {
            "requests": len(latencies),
            "errors": errors,
            "elapsed_s": round(elapsed, 2),
            "concurrency": args.concurrency,
            "unique_tasks": bool(args.unique),
            "p50_ms": round(float(np.percentile(lat, 50)), 2) if len(lat) else None,
            "p90_ms": round(float(np.percentile(lat, 90)), 2) if len(lat) else None,
            "p99_ms": round(float(np.percentile(lat, 99)), 2) if len(lat) else None,
        },
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="dragonfly2_tpu daemon load generator")
    ap.add_argument("url", help="source URL to download repeatedly")
    ap.add_argument("--sock", default=DEFAULT_SOCK)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--duration", type=float, default=10.0,
                    help="seconds to run (ignored with --count)")
    ap.add_argument("--count", type=int, default=None, help="fixed request count")
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--unique", action="store_true",
                    help="unique task per request (full scheduler+piece path)")
    args = ap.parse_args(argv)
    result = asyncio.run(run_stress(args))
    print(json.dumps(result), flush=True)
    return 0 if result["extra"]["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
