"""Load generator for a peer daemon / cluster.

Reference equivalent: test/tools/stress (Makefile:303-309) — a concurrency
driver that hammers a target and reports latency percentiles. Here it drives
the daemon's download RPC with N concurrent workers for a duration (or a
fixed request count) and prints one JSON line: throughput, latency
p50/p90/p99, error count — the shape CI perf gates consume.

    python -m dragonfly2_tpu.cli.dfstress http://origin/file \\
        --sock /tmp/df.sock --concurrency 16 --duration 10

Each request downloads the SAME task (reuse fast path after the first), so
the tool measures control-plane + storage round-trip throughput, not origin
bandwidth; pass --unique to append a counter query param and force distinct
tasks (piece engine + scheduler path per request).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

import numpy as np

from dragonfly2_tpu.cli.dfget import DEFAULT_SOCK
from dragonfly2_tpu.rpc.core import RpcClient


async def run_stress(args: argparse.Namespace) -> dict:
    client = RpcClient(args.sock, timeout=args.timeout)
    latencies: list[float] = []
    errors = 0
    counter = 0
    stop_at = time.monotonic() + args.duration if args.count is None else None

    def next_url() -> str | None:
        # no await points: atomic on the single-threaded event loop
        nonlocal counter
        if args.count is not None and counter >= args.count:
            return None
        if stop_at is not None and time.monotonic() >= stop_at:
            return None
        counter += 1
        if args.unique:
            sep = "&" if "?" in args.url else "?"
            return f"{args.url}{sep}stress={counter}"
        return args.url

    async def worker() -> None:
        nonlocal errors
        while True:
            url = next_url()
            if url is None:
                return
            t0 = time.monotonic()
            try:
                await client.call(  # dflint: disable=DF025 load generator: one RPC per iteration IS the workload being measured
                    "download", {"url": url, "output": None}, timeout=args.timeout
                )
                latencies.append(time.monotonic() - t0)
            except Exception:
                errors += 1

    t0 = time.monotonic()
    await asyncio.gather(*(worker() for _ in range(args.concurrency)))
    elapsed = time.monotonic() - t0
    await client.close()

    lat = np.asarray(latencies) * 1000.0
    return {
        "metric": "daemon_download_rps",
        "value": round(len(latencies) / max(elapsed, 1e-9), 1),
        "unit": "requests/s",
        "extra": {
            "requests": len(latencies),
            "errors": errors,
            "elapsed_s": round(elapsed, 2),
            "concurrency": args.concurrency,
            "unique_tasks": bool(args.unique),
            "p50_ms": round(float(np.percentile(lat, 50)), 2) if len(lat) else None,
            "p90_ms": round(float(np.percentile(lat, 90)), 2) if len(lat) else None,
            "p99_ms": round(float(np.percentile(lat, 99)), 2) if len(lat) else None,
        },
    }


async def run_scoring_stress(args: argparse.Namespace) -> dict:
    """Serving-SLO stress (VERDICT r4 Next #6): drive scheduling rounds
    through the LIVE evaluator stack — MLEvaluator + MicroBatchScorer + the
    native multi-round FFI — on a real SchedulerService resource pool, and
    report rounds/s + p50/p99. This measures the END-TO-END scoring path
    (feature assembly included), not the raw FFI layer the headline bench
    isolates; the full-round number (sample + 8 filters + score + top-4) is
    reported alongside."""
    import tempfile
    from pathlib import Path

    import jax

    jax.config.update("jax_platforms", "cpu")  # artifact precompute only
    import jax.numpy as jnp

    from dragonfly2_tpu.models.graphsage import TopoGraph
    from dragonfly2_tpu.native import MicroBatchScorer, NativeScorer, export_scorer_artifact
    from dragonfly2_tpu.scheduler.evaluator import new_evaluator
    from dragonfly2_tpu.scheduler.resource import HostType
    from dragonfly2_tpu.scheduler.service import SchedulerService, TaskMeta
    from dragonfly2_tpu.trainer import synthetic, train_gnn

    n_nodes = 1024
    cluster = synthetic.make_cluster(
        num_nodes=n_nodes, num_neighbors=16, num_pairs=4096, seed=7
    )
    cfg = train_gnn.GNNTrainConfig()
    model = train_gnn.make_model(cfg)
    state = train_gnn.init_state(cfg, cluster.graph, rng_seed=7)
    g = TopoGraph(*(jnp.asarray(a) for a in cluster.graph))
    z = np.asarray(
        jax.jit(lambda p, gg: model.apply(p, gg, method=model.embed))(state.params, g)
    )
    with tempfile.TemporaryDirectory() as td:
        scorer = NativeScorer(export_scorer_artifact(state.params, z, Path(td) / "s.dfsc"))
        ev = new_evaluator("ml")
        svc = SchedulerService(evaluator=ev)

        # a live pool: one task, candidate parents with pieces, child peers
        meta = TaskMeta("stress-task", "http://origin/stress.bin")
        n_hosts = args.hosts
        hosts = []
        for i in range(n_hosts):
            h = svc.pool.load_or_create_host(
                f"h{i}", f"10.0.{i // 256}.{i % 256}", f"host{i}",
                download_port=8000,
                host_type=HostType.NORMAL, idc=f"idc-{i % 3}",
                location=f"r{i % 2}|z{i % 5}",
            )
            h.upload_limit = 10_000  # saturating the slots is not the point here
            hosts.append(h)
        task = svc.pool.load_or_create_task(meta.task_id, meta.url)
        task.set_metadata(1 << 30, 4 << 20)
        children = []
        parents = []
        for i, h in enumerate(hosts):
            p = svc.pool.create_peer(f"peer{i}", task, h)
            for evname in ("register", "download"):
                if p.fsm.can(evname):
                    p.fsm.fire(evname)
            if i < args.concurrency:
                children.append(p)
            else:
                for idx in range(8):
                    p.finished_pieces.set(idx)
                p.bump_feat()
                parents.append(p)
        node_index = {h.id: i % n_nodes for i, h in enumerate(hosts)}
        mb = MicroBatchScorer(scorer)
        ev.attach_scorer(scorer, node_index, microbatch=mb)

        cand = parents[: args.candidates]
        # warm both paths (first calls build caches / start the flusher)
        for _ in range(3):
            await asyncio.gather(*(ev.evaluate_async(c, cand) for c in children))

        async def measure(fn) -> tuple[float, np.ndarray]:
            done = 0
            lat: list[float] = []

            async def driver(c):
                nonlocal done
                while done < args.rounds:
                    done += 1
                    t1 = time.monotonic()
                    await fn(c)
                    lat.append(time.monotonic() - t1)

            t0 = time.monotonic()
            await asyncio.gather(*(driver(c) for c in children))
            return args.rounds / (time.monotonic() - t0), np.asarray(lat) * 1000

        flushes0, rounds0 = mb.flushes, mb.rounds
        eval_rps, eval_lat = await measure(lambda c: ev.evaluate_async(c, cand))
        # snapshot the coalescing stats for the EVAL phase alone (warmup and
        # the full-round phase below would otherwise pollute the ratio)
        eval_flushes, eval_rounds = mb.flushes - flushes0, mb.rounds - rounds0
        full_rps, full_lat = await measure(
            lambda c: svc.scheduling.find_candidate_parents_async(c)
        )

        # Cost decomposition → the host's serving ceiling. Everything on this
        # path is CPU work on the scheduler's event-loop core: feature
        # assembly (Python/numpy) and the native GEMMs (which sit near the
        # core's SIMD peak — see scorer.cc). 1/(prepare+ffi) is therefore the
        # best ANY single-core deployment can serve end-to-end; the gap
        # between achieved and ceiling is asyncio + micro-batch overhead. On
        # multi-core hosts the micro-batcher offloads the native call (GIL
        # released) so assembly and GEMMs pipeline, raising the ceiling
        # toward 1/max(prepare, ffi).
        probe_n = 512
        t0 = time.monotonic()
        for _ in range(probe_n):
            ev._prepare(children[0], cand)
        prepare_us = (time.monotonic() - t0) / probe_n * 1e6
        feats, cc, pp, _known = ev._prepare(children[0], cand)
        if cc is None:
            # hosts unknown to the serving graph: the per-stage ceiling
            # cannot be probed — degrade the report to null ceiling fields
            # instead of crashing after the measurements completed
            # (ADVICE r05 #2)
            ffi_us = None
            ceiling_rps = None
        else:
            M = 8
            mf = np.tile(feats, (M, 1, 1))
            mc = np.tile(cc, (M, 1))
            mp = np.tile(pp, (M, 1))
            for _ in range(5):
                scorer.score_rounds(mf, child=mc, parent=mp)
            t0 = time.monotonic()
            for _ in range(probe_n // M):
                scorer.score_rounds(mf, child=mc, parent=mp)
            ffi_us = (time.monotonic() - t0) / probe_n * 1e6
            ceiling_rps = 1e6 / (prepare_us + ffi_us)
        scorer.close()

    def pct(lat: np.ndarray, q: float) -> float:
        return round(float(np.percentile(lat, q)), 3) if len(lat) else None

    return {
        "metric": "evaluator_scoring_rounds_per_sec",
        "value": round(eval_rps, 1),
        "unit": "rounds/s (MLEvaluator+MicroBatch+native FFI, feature build included)",
        "extra": {
            "candidates_per_round": len(cand),
            "concurrency": args.concurrency,
            "rounds": args.rounds,
            "eval_p50_ms": pct(eval_lat, 50),
            "eval_p99_ms": pct(eval_lat, 99),
            "full_round_rps": round(full_rps, 1),
            "full_round_p50_ms": pct(full_lat, 50),
            "full_round_p99_ms": pct(full_lat, 99),
            "native_flushes": eval_flushes,
            "native_rounds": eval_rounds,
            "prepare_us_per_round": round(prepare_us, 1),
            "ffi_us_per_round_amortized": round(ffi_us, 1) if ffi_us is not None else None,
            "single_core_ceiling_rps": round(ceiling_rps, 1) if ceiling_rps else None,
            "ceiling_fraction_achieved": (
                round(eval_rps / ceiling_rps, 3) if ceiling_rps else None
            ),
            "host_cpu_count": os.cpu_count(),
        },
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="dragonfly2_tpu daemon load generator")
    ap.add_argument("url", nargs="?", default=None,
                    help="source URL to download repeatedly (download mode)")
    ap.add_argument("--sock", default=DEFAULT_SOCK)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--duration", type=float, default=10.0,
                    help="seconds to run (ignored with --count)")
    ap.add_argument("--count", type=int, default=None, help="fixed request count")
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--unique", action="store_true",
                    help="unique task per request (full scheduler+piece path)")
    ap.add_argument("--scoring", action="store_true",
                    help="stress the ml scoring serving path instead of downloads")
    ap.add_argument("--rounds", type=int, default=20000,
                    help="scoring rounds to drive (--scoring)")
    ap.add_argument("--candidates", type=int, default=40,
                    help="candidate parents per round (--scoring)")
    ap.add_argument("--hosts", type=int, default=256,
                    help="hosts in the stress pool (--scoring)")
    args = ap.parse_args(argv)
    if args.scoring:
        result = asyncio.run(run_scoring_stress(args))
        print(json.dumps(result), flush=True)
        return 0
    if not args.url:
        ap.error("url is required unless --scoring")
    result = asyncio.run(run_stress(args))
    print(json.dumps(result), flush=True)
    return 0 if result["extra"]["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
