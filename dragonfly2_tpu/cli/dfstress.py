"""Load generator for a peer daemon / cluster.

Reference equivalent: test/tools/stress (Makefile:303-309) — a concurrency
driver that hammers a target and reports latency percentiles. Here it drives
the daemon's download RPC with N concurrent workers for a duration (or a
fixed request count) and prints one JSON line: throughput, latency
p50/p90/p99, error count — the shape CI perf gates consume.

    python -m dragonfly2_tpu.cli.dfstress http://origin/file \\
        --sock /tmp/df.sock --concurrency 16 --duration 10

Each request downloads the SAME task (reuse fast path after the first), so
the tool measures control-plane + storage round-trip throughput, not origin
bandwidth; pass --unique to append a counter query param and force distinct
tasks (piece engine + scheduler path per request).

Two further modes:

    --scoring   drive the ml evaluator serving stack (rounds/s, latency,
                thread-scaling legs — see run_scoring_stress)
    --swarm     hundreds of simulated lightweight peers running the full
                control-plane round over the real wire against a scheduler
                FEDERATION (--schedulers a:1,b:2): aggregate rounds/s plus
                per-scheduler load share — the ring + gossip scale scenario

    python -m dragonfly2_tpu.cli.dfstress --swarm \\
        --schedulers 127.0.0.1:9000,127.0.0.1:9001 --peers 200 --duration 10
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

import numpy as np

from dragonfly2_tpu.cli.dfget import DEFAULT_SOCK
from dragonfly2_tpu.rpc.core import RpcClient


async def run_stress(args: argparse.Namespace) -> dict:
    client = RpcClient(args.sock, timeout=args.timeout)
    latencies: list[float] = []
    errors = 0
    counter = 0
    stop_at = time.monotonic() + args.duration if args.count is None else None

    def next_url() -> str | None:
        # no await points: atomic on the single-threaded event loop
        nonlocal counter
        if args.count is not None and counter >= args.count:
            return None
        if stop_at is not None and time.monotonic() >= stop_at:
            return None
        counter += 1
        if args.unique:
            sep = "&" if "?" in args.url else "?"
            return f"{args.url}{sep}stress={counter}"
        return args.url

    async def worker(priority: float) -> None:
        nonlocal errors
        while True:
            url = next_url()
            if url is None:
                return
            t0 = time.monotonic()
            try:
                await client.call(  # dflint: disable=DF025 load generator: one RPC per iteration IS the workload being measured
                    "download",
                    {"url": url, "output": None, "priority": priority},
                    timeout=args.timeout,
                )
                latencies.append(time.monotonic() - t0)
            except Exception:
                errors += 1

    # mixed tenant load: --priority-split N gives the first N workers the
    # high priority (--priority, default 3.0) and the rest weight 1.0, so the
    # traffic shaper's weighted fairness is drivable from the CLI (getattr:
    # programmatic callers predating the flags keep working)
    split = min(getattr(args, "priority_split", 0), args.concurrency)
    weights = [getattr(args, "priority", 1.0)] * split + [1.0] * (args.concurrency - split)
    t0 = time.monotonic()
    await asyncio.gather(*(worker(w) for w in weights))
    elapsed = time.monotonic() - t0
    await client.close()

    lat = np.asarray(latencies) * 1000.0
    return {
        "metric": "daemon_download_rps",
        "value": round(len(latencies) / max(elapsed, 1e-9), 1),
        "unit": "requests/s",
        "extra": {
            "requests": len(latencies),
            "errors": errors,
            "elapsed_s": round(elapsed, 2),
            "concurrency": args.concurrency,
            "priority_split": split,
            "unique_tasks": bool(args.unique),
            "p50_ms": round(float(np.percentile(lat, 50)), 2) if len(lat) else None,
            "p90_ms": round(float(np.percentile(lat, 90)), 2) if len(lat) else None,
            "p99_ms": round(float(np.percentile(lat, 99)), 2) if len(lat) else None,
        },
    }


async def run_scoring_stress(args: argparse.Namespace) -> dict:
    """Serving-SLO stress (VERDICT r4 Next #6, sharded in ISSUE 7): drive
    scheduling rounds through the LIVE evaluator stack on a real
    SchedulerService resource pool and report rounds/s + p50/p99 for THREE
    serving shapes, interleaved same-run median-of-3 (2-core box
    discipline — this container drifts ±30% run-to-run):

      microbatch    the r05 single-loop path: concurrent rounds coalesce in
                    MicroBatchScorer into one multi-round FFI call
      workers=1/2   the round dispatcher: each round's assembly+FFI runs
                    whole on a worker thread with its OWN native handle
                    (ScorerHandlePool; scorer.cc serializes a shared handle)

    The headline (`value`) is the BEST-measured serving config on this
    host, named in `eval_best_config` (on wide hosts that should be the
    dispatcher; on this 2-core box the loop's own glue + one worker already
    saturate the GIL, so workers1 or microbatch typically wins); the
    workers=1 leg isolates the thread-scaling factor from the executor-hop
    overhead both dispatcher legs pay. full_round_rps covers the complete
    round (sample + filters + score + top-4), again best-of named in
    `full_round_best_config` with both legs reported."""
    import tempfile
    from pathlib import Path

    import jax

    jax.config.update("jax_platforms", "cpu")  # artifact precompute only
    import jax.numpy as jnp

    from dragonfly2_tpu.models.graphsage import TopoGraph
    from dragonfly2_tpu.native import (
        MicroBatchScorer,
        NativeScorer,
        ScorerHandlePool,
        export_scorer_artifact,
    )
    from dragonfly2_tpu.scheduler.evaluator import new_evaluator
    from dragonfly2_tpu.scheduler.resource import HostType
    from dragonfly2_tpu.scheduler.scheduling import RoundDispatcher, usable_cpu_count
    from dragonfly2_tpu.scheduler.service import SchedulerService, TaskMeta
    from dragonfly2_tpu.trainer import synthetic, train_gnn

    n_nodes = 1024
    cluster = synthetic.make_cluster(
        num_nodes=n_nodes, num_neighbors=16, num_pairs=4096, seed=7
    )
    cfg = train_gnn.GNNTrainConfig()
    model = train_gnn.make_model(cfg)
    state = train_gnn.init_state(cfg, cluster.graph, rng_seed=7)
    g = TopoGraph(*(jnp.asarray(a) for a in cluster.graph))
    z = np.asarray(
        jax.jit(lambda p, gg: model.apply(p, gg, method=model.embed))(state.params, g)
    )
    with tempfile.TemporaryDirectory() as td:
        scorer = NativeScorer(export_scorer_artifact(state.params, z, Path(td) / "s.dfsc"))
        ev = new_evaluator("ml")
        svc = SchedulerService(evaluator=ev)

        # a live pool: one task, candidate parents with pieces, child peers
        meta = TaskMeta("stress-task", "http://origin/stress.bin")
        n_hosts = args.hosts
        hosts = []
        for i in range(n_hosts):
            h = svc.pool.load_or_create_host(
                f"h{i}", f"10.0.{i // 256}.{i % 256}", f"host{i}",
                download_port=8000,
                host_type=HostType.NORMAL, idc=f"idc-{i % 3}",
                location=f"r{i % 2}|z{i % 5}",
            )
            h.upload_limit = 10_000  # saturating the slots is not the point here
            hosts.append(h)
        task = svc.pool.load_or_create_task(meta.task_id, meta.url)
        task.set_metadata(1 << 30, 4 << 20)
        children = []
        parents = []
        for i, h in enumerate(hosts):
            p = svc.pool.create_peer(f"peer{i}", task, h)
            for evname in ("register", "download"):
                if p.fsm.can(evname):
                    p.fsm.fire(evname)
            if i < args.concurrency:
                children.append(p)
            else:
                for idx in range(8):
                    p.finished_pieces.set(idx)
                p.bump_feat()
                parents.append(p)
        node_index = {h.id: i % n_nodes for i, h in enumerate(hosts)}
        mb = MicroBatchScorer(scorer)
        handle_pool = ScorerHandlePool(scorer)
        ev.attach_scorer(scorer, node_index, microbatch=mb, handle_pool=handle_pool)
        # two dispatchers over the same Scheduling: the workers=1 vs 2 A/B
        # must differ ONLY in worker count (same lock, same rng, same pool)
        disp1 = RoundDispatcher(svc.scheduling, workers=1)
        disp2 = RoundDispatcher(svc.scheduling, workers=2)

        cand = parents[: args.candidates]
        # warm every path (first calls build caches, fork per-thread
        # handles, start the micro-batch flusher)
        for _ in range(3):
            await asyncio.gather(*(ev.evaluate_async(c, cand) for c in children))
            await asyncio.gather(*(disp1.evaluate(c, cand) for c in children))
            await asyncio.gather(*(disp2.evaluate(c, cand) for c in children))

        async def measure(fn) -> tuple[float, np.ndarray]:
            done = 0
            lat: list[float] = []

            async def driver(c):
                nonlocal done
                while done < args.rounds:
                    done += 1
                    t1 = time.monotonic()
                    await fn(c)
                    lat.append(time.monotonic() - t1)

            t0 = time.monotonic()
            await asyncio.gather(*(driver(c) for c in children))
            return args.rounds / (time.monotonic() - t0), np.asarray(lat) * 1000

        # ---- eval leg (prepare+score only), three shapes interleaved ----
        eval_legs = {
            "microbatch": lambda c: ev.evaluate_async(c, cand),
            "workers1": lambda c: disp1.evaluate(c, cand),
            "workers2": lambda c: disp2.evaluate(c, cand),
        }
        eval_rates: dict[str, list[float]] = {k: [] for k in eval_legs}
        # latency samples POOLED across all three reps (keeping only the
        # last rep's array paired a median-of-3 throughput with a single
        # noise sample of latency on a ±30%-drift box)
        eval_lats: dict[str, list[np.ndarray]] = {k: [] for k in eval_legs}
        flushes0, rounds0 = mb.flushes, mb.rounds
        for _rep in range(3):
            for name, fn in eval_legs.items():
                rps, lat = await measure(fn)
                eval_rates[name].append(rps)
                eval_lats[name].append(lat)
        # coalescing stats cover exactly the microbatch legs (the dispatcher
        # legs never touch the micro-batcher)
        eval_flushes, eval_rounds = mb.flushes - flushes0, mb.rounds - rounds0
        mb_rps = float(np.median(eval_rates["microbatch"]))
        w1_rps = float(np.median(eval_rates["workers1"]))
        w2_rps = float(np.median(eval_rates["workers2"]))
        # Headline = the best-measured serving config ON THIS HOST, named in
        # eval_best_config: on the 2-core CI box the event loop's own
        # per-round glue plus one worker already saturate the GIL, so
        # workers=1 (round CPU off-loop, loop glue on the freed core) is
        # typically the winner and workers=2 adds nothing the box can give —
        # the full scaling curve needs wider hosts (ROADMAP #1's caveat;
        # tests/test_dispatch.py proves the 1→2 growth property with a
        # GIL-releasing scorer stub).
        best = max(eval_legs, key=lambda k: float(np.median(eval_rates[k])))
        eval_rps = float(np.median(eval_rates[best]))
        eval_lat = np.concatenate(eval_lats[best])

        # ---- full round (sample + filters + score + top-4) ----
        # Six legs interleaved same-run (ISSUE 18 + 19): the shipping Python
        # serial loop, the dispatcher batching rounds through the PYTHON
        # batch leg (PR 7's best shape), the native round driver
        # (df_round_drive: snapshot under the lock → ONE GIL-released FFI
        # for filter-revalidate + feature columns + score + stable top-k)
        # on 1 and 2 dispatcher workers, and the MIRROR-backed driver
        # (df_mirror_drive: no snapshot at all — sample/filter/gather/score
        # against the C-side mirrored peer table) on the same two shapes.
        # round_driver and the mirror attachment are flipped per measurement
        # on the SAME Scheduling (same pool, same rng, same lock), so each
        # A/B isolates exactly one mechanism.
        sched = svc.scheduling
        mirror_client = svc.enable_native_mirror()
        sched._mirror = None  # dflint: disable=DF036 A/B rig: legs opt into the attached client explicitly below
        full_legs = {
            "serial": ("serial", False, lambda c: sched.find_candidate_parents_async(c)),
            "dispatcher": ("serial", False, lambda c: disp2.find(c)),
            "native_workers1": ("auto", False, lambda c: disp1.find(c)),
            "native_workers2": ("auto", False, lambda c: disp2.find(c)),
        }
        if mirror_client is not None:
            full_legs["mirror_workers1"] = ("auto", True, lambda c: disp1.find(c))
            full_legs["mirror_workers2"] = ("auto", True, lambda c: disp2.find(c))
        for driver, use_mirror, fn in full_legs.values():  # warm every leg
            sched.config.round_driver = driver
            sched._mirror = mirror_client if use_mirror else None  # dflint: disable=DF036 A/B rig: per-leg toggle of the one attached client (deltas keep flowing while detached)
            await asyncio.gather(*(fn(c) for c in children))
        full_rates: dict[str, list[float]] = {k: [] for k in full_legs}
        full_lats: dict[str, list[np.ndarray]] = {k: [] for k in full_legs}
        # per-leg stage decomposition (ISSUE 19 satellite): Scheduling keeps
        # cumulative ns per stage — snapshot/delta-apply (Python descriptor
        # or snapshot build + result demux), drive (the FFI call), commit
        # (the DAG apply, which find-only legs never run) — sliced per leg
        # by delta around each measurement
        full_stages: dict[str, list[int]] = {k: [0, 0, 0] for k in full_legs}
        native_driven0 = sched.native_rounds_served
        mirror_driven0 = sched.mirror_rounds_served
        for _rep in range(3):
            for name, (driver, use_mirror, fn) in full_legs.items():
                sched.config.round_driver = driver
                sched._mirror = mirror_client if use_mirror else None  # dflint: disable=DF036 A/B rig: per-leg toggle of the one attached client
                s0, d0, c0 = (sched.stage_snapshot_ns, sched.stage_drive_ns,
                              sched.stage_commit_ns)
                rps, lat = await measure(fn)
                st = full_stages[name]
                st[0] += sched.stage_snapshot_ns - s0
                st[1] += sched.stage_drive_ns - d0
                st[2] += sched.stage_commit_ns - c0
                full_rates[name].append(rps)
                full_lats[name].append(lat)
        sched.config.round_driver = "auto"
        sched._mirror = mirror_client  # dflint: disable=DF036 A/B rig: restore the attached client after the leg sweep
        # coverage proof for the A/B: rounds the driver actually scored
        # natively across the native legs (0 would void the comparison —
        # every round silently riding the serial fallback)
        native_rounds_driven = sched.native_rounds_served - native_driven0
        mirror_rounds_driven = sched.mirror_rounds_served - mirror_driven0
        med = {k: float(np.median(v)) for k, v in full_rates.items()}
        full_serial_rps = med["serial"]
        full_disp_rps = med["dispatcher"]
        # same best-config honesty as the eval leg: the serial loop is the
        # shipping default (dispatch_workers=0) and must never be made to
        # LOOK slower by pinning the headline to a config this host can't
        # feed — best-of within each family, named explicitly
        py_best = "dispatcher" if full_disp_rps >= full_serial_rps else "serial"
        nat_best = max(("native_workers1", "native_workers2"), key=lambda k: med[k])
        round_driver_rps = med[nat_best]
        native_speedup = round_driver_rps / max(med[py_best], 1e-9)
        if mirror_client is not None:
            mirror_best = max(("mirror_workers1", "mirror_workers2"),
                              key=lambda k: med[k])
            mirror_rps = med[mirror_best]
            mirror_speedup = mirror_rps / max(med[py_best], 1e-9)
            mirror_stats = mirror_client.stats()
        else:
            mirror_best = mirror_rps = mirror_speedup = mirror_stats = None
        full_best = max(full_legs, key=lambda k: med[k])
        full_rps = med[full_best]
        full_lat = np.concatenate(full_lats[full_best])

        def stage_us(leg: str | None) -> dict:
            """Per-round stage split for one leg across its 3 reps. Null
            hygiene: a stage the leg never ran (commit on find-only legs,
            drive on pure-Python legs) reports None, not a fake 0.0."""
            if leg is None:
                return {"snapshot": None, "drive": None, "commit": None}
            snap, drv, com = full_stages[leg]
            n = 3 * args.rounds
            return {
                "snapshot": round(snap / n / 1e3, 2) if snap else None,
                "drive": round(drv / n / 1e3, 2) if drv else None,
                "commit": round(com / n / 1e3, 2) if com else None,
            }

        disp1.shutdown()
        disp2.shutdown()

        # Cost decomposition → the host's serving ceiling. Everything on this
        # path is CPU work on the scheduler's event-loop core: feature
        # assembly (Python/numpy) and the native GEMMs (which sit near the
        # core's SIMD peak — see scorer.cc). 1/(prepare+ffi) is therefore the
        # best ANY single-core deployment can serve end-to-end; the gap
        # between achieved and ceiling is asyncio + micro-batch overhead. On
        # multi-core hosts the micro-batcher offloads the native call (GIL
        # released) so assembly and GEMMs pipeline, raising the ceiling
        # toward 1/max(prepare, ffi).
        probe_n = 512
        t0 = time.monotonic()
        for _ in range(probe_n):
            ev._prepare(children[0], cand)
        prepare_us = (time.monotonic() - t0) / probe_n * 1e6
        feats, cc, pp, _known = ev._prepare(children[0], cand)
        if cc is None:
            # hosts unknown to the serving graph: the per-stage ceiling
            # cannot be probed — degrade the report to null ceiling fields
            # instead of crashing after the measurements completed
            # (ADVICE r05 #2)
            ffi_us = None
            ceiling_rps = None
        else:
            M = 8
            mf = np.tile(feats, (M, 1, 1))
            mc = np.tile(cc, (M, 1))
            mp = np.tile(pp, (M, 1))
            for _ in range(5):
                scorer.score_rounds(mf, child=mc, parent=mp)
            t0 = time.monotonic()
            for _ in range(probe_n // M):
                scorer.score_rounds(mf, child=mc, parent=mp)
            ffi_us = (time.monotonic() - t0) / probe_n * 1e6
            ceiling_rps = 1e6 / (prepare_us + ffi_us)
        if mirror_client is not None:
            sched._mirror = None  # dflint: disable=DF036 A/B rig: deliberate unwiring before closing the client
            mirror_client.close()
        handle_pool.close()
        scorer.close()

    def pct(lat: np.ndarray, q: float) -> float:
        return round(float(np.percentile(lat, q)), 3) if len(lat) else None

    # Honest ceiling accounting (ISSUE 7 satellite): the r05 capture reported
    # host_cpu_count 1 on a 2-core box (os.cpu_count semantics under the
    # container) — cores now come from the scheduling-affinity mask with
    # os.cpu_count alongside, the ceiling stays PER-CORE by definition
    # (1/(prepare+ffi) on one core), and the fraction divides by the cores
    # the dispatcher could actually use, so "1.05 of ceiling" can no longer
    # read as "done" when a second core sits idle.
    cpus = usable_cpu_count()
    cores_usable = min(disp2.workers, cpus)
    return {
        "metric": "evaluator_scoring_rounds_per_sec",
        "value": round(eval_rps, 1),
        "unit": (
            f"rounds/s (MLEvaluator end-to-end, feature build included; "
            f"best config = {best}, see eval_best_config)"
        ),
        "extra": {
            "candidates_per_round": len(cand),
            "concurrency": args.concurrency,
            "rounds": args.rounds,
            "eval_p50_ms": pct(eval_lat, 50),
            "eval_p99_ms": pct(eval_lat, 99),
            "eval_best_config": best,
            "rounds_per_sec_microbatch": round(mb_rps, 1),
            "rounds_per_sec_workers1": round(w1_rps, 1),
            "rounds_per_sec_workers2": round(w2_rps, 1),
            "thread_scaling_speedup": round(w2_rps / max(w1_rps, 1e-9), 3),
            "dispatch_workers": disp2.workers,
            "full_round_rps": round(full_rps, 1),
            "full_round_best_config": full_best,
            "full_round_rps_serial": round(full_serial_rps, 1),
            "full_round_rps_dispatcher": round(full_disp_rps, 1),
            "full_round_p50_ms": pct(full_lat, 50),
            "full_round_p99_ms": pct(full_lat, 99),
            # ISSUE 18 headline: the native round driver vs the best PYTHON
            # round loop this host can serve (py_best named so the speedup
            # is never against a strawman)
            "round_driver_best_config": nat_best,
            "round_driver_rounds_per_s": round(round_driver_rps, 1),
            "round_driver_rps_workers1": round(med["native_workers1"], 1),
            "round_driver_rps_workers2": round(med["native_workers2"], 1),
            "native_speedup_vs_best_py": round(native_speedup, 3),
            "best_py_config": py_best,
            "native_rounds_driven": int(native_rounds_driven),
            # ISSUE 19 headline: the mirror-backed driver (no Python
            # snapshot leg at all) vs the same best Python loop, plus the
            # per-round stage split for the snapshot-native and mirror legs
            # (None = that leg never ran the stage — find-only legs never
            # commit, pure-Python legs never drive)
            "round_driver_mirror_best_config": mirror_best,
            "round_driver_mirror_rounds_per_s": (
                round(mirror_rps, 1) if mirror_rps is not None else None
            ),
            "round_driver_mirror_rps_workers1": (
                round(med["mirror_workers1"], 1) if mirror_client is not None else None
            ),
            "round_driver_mirror_rps_workers2": (
                round(med["mirror_workers2"], 1) if mirror_client is not None else None
            ),
            "mirror_speedup_vs_best_py": (
                round(mirror_speedup, 3) if mirror_speedup is not None else None
            ),
            "mirror_rounds_driven": int(mirror_rounds_driven),
            "round_driver_stage_us": stage_us(nat_best),
            "round_driver_mirror_stage_us": stage_us(mirror_best),
            "mirror_full_syncs": (
                int(mirror_stats["full_syncs"]) if mirror_stats else None
            ),
            "mirror_stale_rounds": (
                int(mirror_stats["stale_rounds"]) if mirror_stats else None
            ),
            "native_flushes": eval_flushes,
            "native_rounds": eval_rounds,
            "prepare_us_per_round": round(prepare_us, 1),
            "ffi_us_per_round_amortized": round(ffi_us, 1) if ffi_us is not None else None,
            "single_core_ceiling_rps": round(ceiling_rps, 1) if ceiling_rps else None,
            "ceiling_fraction_achieved": (
                round(eval_rps / (ceiling_rps * cores_usable), 3) if ceiling_rps else None
            ),
            "ceiling_fraction_single_core": (
                round(eval_rps / ceiling_rps, 3) if ceiling_rps else None
            ),
            "host_cpu_count": cpus,
            "host_cpu_count_os": os.cpu_count(),
        },
    }


_SWARM_RPC_VERBS = frozenset({
    "register_peer", "report_task_metadata", "report_pieces",
    "report_piece_result", "report_peer_result", "announce_task",
    "announce_host", "sync_probes", "reschedule", "leave_peer", "leave_host",
    "stat_task",
})


class _CountingSchedulerClient:
    """RemoteSchedulerClient proxy counting RPCs per scheduler address — the
    swarm's per-scheduler load-share measurement (`register_peer` counts
    separately: one per round, so its share IS the ring's task placement)."""

    def __init__(self, addr: str, counts: dict, round_counts: dict):
        from dragonfly2_tpu.rpc.scheduler import RemoteSchedulerClient

        self._inner = RemoteSchedulerClient(addr)
        self._addr = addr
        self._counts = counts
        self._round_counts = round_counts

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name not in _SWARM_RPC_VERBS:
            return attr

        async def wrapped(*a, **k):
            self._counts[self._addr] = self._counts.get(self._addr, 0) + 1
            if name == "register_peer":
                self._round_counts[self._addr] = self._round_counts.get(self._addr, 0) + 1
            return await attr(*a, **k)

        return wrapped


async def run_swarm(
    scheduler_addrs: list[str],
    *,
    peers: int = 200,
    tasks: int = 32,
    pieces: int = 8,
    duration: float = 10.0,
    probe_every: int = 5,
    piece_size: int = 4 << 20,
) -> dict:
    """Swarm mode: N simulated lightweight peers driving the full
    control-plane round over the REAL wire against a scheduler federation —
    register → (seed: metadata + batched piece reports + result) or
    (child: scheduled parents + batched piece reports + result) — plus
    periodic probe syncs feeding the topology the federation gossips.

    No data plane: the swarm measures what the ring + federation can
    SCHEDULE, which is the control-plane scale story ("hundreds of peers per
    scheduler pair"). Peer ids are stable per (peer, task) so the resource
    pools stay bounded (re-registering a finished peer restarts it, the
    same reuse shape `run_stress` relies on)."""
    from dragonfly2_tpu.rpc.balancer import BalancedSchedulerClient
    from dragonfly2_tpu.scheduler.service import HostInfo, TaskMeta

    rpc_counts: dict[str, int] = {}
    round_counts: dict[str, int] = {}
    client = BalancedSchedulerClient(
        scheduler_addrs,
        client_factory=lambda a: _CountingSchedulerClient(a, rpc_counts, round_counts),
    )
    metas = [
        TaskMeta(f"swarm-task-{j:04d}", f"http://origin/swarm-{j}.bin")
        for j in range(tasks)
    ]
    content_length = pieces * piece_size
    rounds = 0
    errors = 0
    latencies: list[float] = []
    stop_at = time.monotonic() + duration

    async def peer_loop(i: int) -> None:
        nonlocal rounds, errors
        host = HostInfo(
            id=f"swarm-host-{i:04d}", ip=f"10.42.{i // 256}.{i % 256}",
            hostname=f"swarm-{i}", download_port=18000 + (i % 40000),
        )
        try:
            await client.announce_host(host)
        except Exception:
            errors += 1
        cycle = 0
        while time.monotonic() < stop_at:
            meta = metas[(i + cycle) % len(metas)]
            peer_id = f"swarm-p{i:04d}-{(i + cycle) % len(metas):04d}"
            t0 = time.monotonic()
            try:
                reg = await client.register_peer(peer_id, meta, host)  # dflint: disable=DF025 load generator: one round per iteration IS the workload being measured
                if reg.error:
                    # a refused registration did no reporting work — it must
                    # not count as a completed round (that would inflate
                    # rounds/s exactly when the federation is overloaded)
                    errors += 1
                    cycle += 1
                    continue
                if reg.back_to_source:
                    # first holder: publish metadata, then report the whole
                    # task as one batched flush — the seed leg of the round
                    await client.report_task_metadata(  # dflint: disable=DF025 load generator workload
                        meta.task_id, content_length=content_length,
                        piece_size=piece_size,
                    )
                    await client.report_pieces(  # dflint: disable=DF025 already the batched verb; one flush per round is the workload
                        peer_id, [(k, 8.0, "") for k in range(pieces)]
                    )
                    await client.report_peer_result(  # dflint: disable=DF025 load generator workload
                        peer_id, success=True, bandwidth_bps=2e8
                    )
                else:
                    parent = reg.parents[0].peer_id if reg.parents else ""
                    await client.report_pieces(  # dflint: disable=DF025 already the batched verb; one flush per round is the workload
                        peer_id, [(k, 5.0, parent) for k in range(pieces)]
                    )
                    await client.report_peer_result(  # dflint: disable=DF025 load generator workload
                        peer_id, success=True, bandwidth_bps=3e8
                    )
                if probe_every and cycle % probe_every == probe_every - 1:
                    dst = f"swarm-host-{(i + 1) % peers:04d}"
                    await client.sync_probes(  # dflint: disable=DF025 load generator workload: periodic probe round per peer
                        host.id,
                        [{"dst_host_id": dst, "rtt_ms": 1.0 + (i % 7), "success": True}],
                    )
                rounds += 1
                latencies.append(time.monotonic() - t0)
            except Exception:
                errors += 1
            cycle += 1

    t0 = time.monotonic()
    await asyncio.gather(*(peer_loop(i) for i in range(peers)))
    elapsed = time.monotonic() - t0
    await client.close()

    total_rpcs = sum(rpc_counts.values()) or 1
    total_rounds = sum(round_counts.values()) or 1
    lat = np.asarray(latencies) * 1000.0
    return {
        "metric": "swarm_rounds_per_sec",
        "value": round(rounds / max(elapsed, 1e-9), 1),
        "unit": "rounds/s (full control-plane cycle per simulated peer)",
        "extra": {
            "schedulers": list(scheduler_addrs),
            "peers": peers,
            "tasks": tasks,
            "pieces_per_round": pieces,
            "rounds": rounds,
            "errors": errors,
            "elapsed_s": round(elapsed, 2),
            "p50_ms": round(float(np.percentile(lat, 50)), 2) if len(lat) else None,
            "p99_ms": round(float(np.percentile(lat, 99)), 2) if len(lat) else None,
            # share of scheduling rounds (register_peer) per ring member —
            # the consistent-hash placement balance — plus the all-RPC share
            "per_scheduler_round_share": {
                a: round(round_counts.get(a, 0) / total_rounds, 3)
                for a in scheduler_addrs
            },
            "per_scheduler_rpc_share": {
                a: round(rpc_counts.get(a, 0) / total_rpcs, 3)
                for a in scheduler_addrs
            },
        },
    }


async def run_swarm_stress(args: argparse.Namespace) -> dict:
    addrs = [a.strip() for a in args.schedulers.split(",") if a.strip()]
    if not addrs:
        raise SystemExit("--swarm requires --schedulers host:port[,host:port...]")
    return await run_swarm(
        addrs,
        peers=args.peers,
        tasks=args.tasks,
        pieces=args.pieces,
        duration=args.duration,
        probe_every=args.probe_every,
    )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="dragonfly2_tpu daemon load generator")
    ap.add_argument("url", nargs="?", default=None,
                    help="source URL to download repeatedly (download mode)")
    ap.add_argument("--sock", default=DEFAULT_SOCK)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--duration", type=float, default=10.0,
                    help="seconds to run (ignored with --count)")
    ap.add_argument("--count", type=int, default=None, help="fixed request count")
    ap.add_argument("--timeout", type=float, default=60.0)
    ap.add_argument("--unique", action="store_true",
                    help="unique task per request (full scheduler+piece path)")
    ap.add_argument("--priority", type=float, default=3.0,
                    help="tenant weight for the high-priority worker class")
    ap.add_argument("--priority-split", type=int, default=0,
                    help="first N workers request at --priority (rest at 1.0): "
                         "drives the traffic shaper's weighted fairness")
    ap.add_argument("--scoring", action="store_true",
                    help="stress the ml scoring serving path instead of downloads")
    ap.add_argument("--swarm", action="store_true",
                    help="simulated-peer swarm against a scheduler federation "
                         "over the real wire (control plane only, no data plane)")
    ap.add_argument("--schedulers", default="",
                    help="scheduler addresses host:port[,host:port...] (--swarm)")
    ap.add_argument("--peers", type=int, default=200,
                    help="simulated peers in the swarm (--swarm)")
    ap.add_argument("--tasks", type=int, default=32,
                    help="distinct tasks the swarm cycles through (--swarm)")
    ap.add_argument("--pieces", type=int, default=8,
                    help="pieces reported per swarm round (--swarm)")
    ap.add_argument("--probe-every", type=int, default=5,
                    help="sync a probe round every N cycles per peer (--swarm)")
    ap.add_argument("--rounds", type=int, default=20000,
                    help="scoring rounds to drive (--scoring)")
    ap.add_argument("--candidates", type=int, default=40,
                    help="candidate parents per round (--scoring)")
    ap.add_argument("--hosts", type=int, default=256,
                    help="hosts in the stress pool (--scoring)")
    args = ap.parse_args(argv)
    if args.scoring:
        result = asyncio.run(run_scoring_stress(args))
        print(json.dumps(result), flush=True)
        return 0
    if args.swarm:
        result = asyncio.run(run_swarm_stress(args))
        print(json.dumps(result), flush=True)
        return 0 if result["extra"]["errors"] == 0 else 1
    if not args.url:
        ap.error("url is required unless --scoring")
    result = asyncio.run(run_stress(args))
    print(json.dumps(result), flush=True)
    return 0 if result["extra"]["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
