"""Thin CLIs: dfget / dfcache / dfstore front-ends over the daemon RPC."""
