"""dftrace: merge per-process span files, reassemble traces, find the
critical path.

Every service (and `dfget --trace-file`) writes finished spans as JSON lines
(`tracing.trace_file` / DRAGONFLY_TRACE_FILE); OTLP batch files
(`tracing.otlp_file`) are readable too. A cluster run therefore leaves one
span file per process — this tool is the collector-less way to read them as
ONE timeline:

  python -m dragonfly2_tpu.cli.dftrace /tmp/trace-*.jsonl
      per-trace critical path (who actually gated the wall clock) plus a
      p50/p95 stage table per span name across all traces

  python -m dragonfly2_tpu.cli.dftrace --trace <id16..> files...
      one trace in detail

  python -m dragonfly2_tpu.cli.dftrace --otlp http://jaeger:4318 files...
      forward the merged spans as OTLP/JSON batches to a collector (the
      same body the live otlp_endpoint exporter POSTs), so an offline run's
      files can still land in Jaeger afterwards.

Critical-path rule: starting at the trace root, repeatedly descend into the
child whose interval ENDS last — the child that gated the parent's return.
Each hop reports its exclusive time (duration minus the on-path child's
duration), so the exclusive times along the path sum exactly to the root's
duration: the printed path IS an account of the measured wall time.
"""

from __future__ import annotations

import argparse
import glob as globlib
import json
import sys
from collections import defaultdict
from typing import Iterable

from dragonfly2_tpu.utils.stats import quantile as _quantile


def _spans_from_otlp_request(req: dict) -> Iterable[dict]:
    """OTLP/JSON ExportTraceServiceRequest → plain span dicts (the tracer's
    JSONL shape), so both export formats merge into one pool."""
    for rs in req.get("resourceSpans", ()):
        service = ""
        for attr in rs.get("resource", {}).get("attributes", ()):
            if attr.get("key") == "service.name":
                service = attr.get("value", {}).get("stringValue", "")
        for ss in rs.get("scopeSpans", ()):
            for s in ss.get("spans", ()):
                start = int(s.get("startTimeUnixNano", "0")) / 1e9
                end = int(s.get("endTimeUnixNano", "0")) / 1e9
                attrs = {}
                for a in s.get("attributes", ()):
                    v = a.get("value", {})
                    # decode by the key PRESENT, not an or-chain over
                    # values: False/0.0 are valid attr values (dispatched=
                    # false, queue_wait_ms=0.0) and must survive, and OTLP
                    # int64s are JSON strings that must come back as ints
                    if "stringValue" in v:
                        attrs[a.get("key", "")] = v["stringValue"]
                    elif "boolValue" in v:
                        attrs[a.get("key", "")] = v["boolValue"]
                    elif "intValue" in v:
                        try:
                            attrs[a.get("key", "")] = int(v["intValue"])
                        except (TypeError, ValueError):
                            attrs[a.get("key", "")] = v["intValue"]
                    elif "doubleValue" in v:
                        attrs[a.get("key", "")] = v["doubleValue"]
                attrs.setdefault("service", service)
                yield {
                    "trace_id": s.get("traceId", ""),
                    "span_id": s.get("spanId", ""),
                    "parent_id": s.get("parentSpanId", ""),
                    "name": s.get("name", ""),
                    "start": start,
                    "duration_ms": round((end - start) * 1e3, 3),
                    "attrs": attrs,
                    "status": {1: "ok", 2: "error"}.get(
                        s.get("status", {}).get("code"), "ok"
                    ),
                    "error": s.get("status", {}).get("message", ""),
                }


def load_spans(paths: list[str]) -> list[dict]:
    """Read span JSONL and/or OTLP-request JSONL files; skip unparsable
    lines (a crashed process may leave a torn tail) rather than dying."""
    spans: list[dict] = []
    for pattern in paths:
        matches = globlib.glob(pattern) or [pattern]
        for path in matches:
            try:
                fh = open(path, "r", encoding="utf-8")
            except OSError as e:
                print(f"dftrace: {path}: {e}", file=sys.stderr)
                continue
            with fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        obj = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail of a killed process
                    if "resourceSpans" in obj:
                        spans.extend(_spans_from_otlp_request(obj))
                    elif "trace_id" in obj:
                        spans.append(obj)
    return spans


def assemble_traces(spans: list[dict]) -> dict[str, list[dict]]:
    """trace_id → spans, de-duplicated by span_id (a file may be read twice
    via overlapping globs), time-ordered."""
    traces: dict[str, dict[str, dict]] = defaultdict(dict)
    for s in spans:
        if s.get("span_id"):
            traces[s["trace_id"]][s["span_id"]] = s
    return {
        tid: sorted(by_id.values(), key=lambda s: s.get("start", 0.0))
        for tid, by_id in traces.items()
    }


def _roots(spans: list[dict]) -> list[dict]:
    ids = {s["span_id"] for s in spans}
    # a root is a span whose parent was never exported — either a true root
    # (parent_id "") or the local fragment of a trace whose upstream file is
    # missing; both are valid timeline anchors
    return [s for s in spans if not s.get("parent_id") or s["parent_id"] not in ids]


def critical_path(spans: list[dict]) -> list[tuple[dict, float]]:
    """[(span, exclusive_ms)] from the root down: at each hop descend into
    the child that finished LAST (it gated the parent's return). Exclusive
    time = span duration minus the on-path child's duration, so the column
    sums exactly to the root's duration."""
    children: dict[str, list[dict]] = defaultdict(list)
    for s in spans:
        if s.get("parent_id"):
            children[s["parent_id"]].append(s)
    roots = _roots(spans)
    if not roots:
        return []
    root = max(roots, key=lambda s: s.get("duration_ms", 0.0))
    path: list[dict] = [root]
    seen = {root["span_id"]}
    cur = root
    while True:
        kids = [c for c in children.get(cur["span_id"], ()) if c["span_id"] not in seen]
        if not kids:
            break
        cur = max(kids, key=lambda s: s.get("start", 0.0) + s.get("duration_ms", 0.0) / 1e3)
        path.append(cur)
        seen.add(cur["span_id"])
    out = []
    for i, s in enumerate(path):
        child_ms = path[i + 1].get("duration_ms", 0.0) if i + 1 < len(path) else 0.0
        out.append((s, max(0.0, s.get("duration_ms", 0.0) - child_ms)))
    return out


def stage_table(spans: list[dict]) -> list[dict]:
    """Per-span-name duration stats across every trace in the pool."""
    by_name: dict[str, list[float]] = defaultdict(list)
    for s in spans:
        by_name[s.get("name", "?")].append(float(s.get("duration_ms", 0.0)))
    rows = []
    for name, vals in by_name.items():
        vals.sort()
        rows.append(
            {
                "name": name,
                "count": len(vals),
                "p50_ms": round(_quantile(vals, 0.50), 3),
                "p95_ms": round(_quantile(vals, 0.95), 3),
                "max_ms": round(vals[-1], 3),
                "total_ms": round(sum(vals), 3),
            }
        )
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def _span_label(s: dict) -> str:
    attrs = s.get("attrs", {}) or {}
    svc = attrs.get("service", "")
    interesting = {
        k: v
        for k, v in attrs.items()
        if k in ("method", "piece", "round", "task_id", "worker", "version",
                 "recv_ms", "hash_wait_ms", "queue_wait_ms", "batch_size",
                 "path", "pieces")
    }
    extra = " ".join(f"{k}={v}" for k, v in sorted(interesting.items()))
    base = f"{s.get('name', '?')}"
    if svc:
        base += f" [{svc}]"
    if s.get("status") == "error":
        base += " !ERROR"
    return f"{base} {extra}".rstrip()


def print_trace(tid: str, spans: list[dict], *, out=sys.stdout) -> None:
    path = critical_path(spans)
    if not path:
        return
    root_ms = path[0][0].get("duration_ms", 0.0)
    excl_sum = sum(e for _s, e in path)
    print(f"trace {tid}  spans={len(spans)}  wall={root_ms:.1f}ms", file=out)
    print("  critical path (exclusive ms sums to wall):", file=out)
    for s, excl in path:
        print(
            f"    {excl:9.2f}ms  (span {s.get('duration_ms', 0.0):9.2f}ms)  {_span_label(s)}",
            file=out,
        )
    print(f"    {'-' * 9}\n    {excl_sum:9.2f}ms  total exclusive", file=out)


def forward_otlp(spans: list[dict], endpoint: str, *, batch: int = 256) -> int:
    """POST merged spans to <endpoint>/v1/traces as OTLP/JSON batches,
    grouped by their recorded service name. Returns batches sent."""
    import urllib.request

    from dragonfly2_tpu.observability.tracing import Span, Tracer, spans_to_otlp

    tracer = Tracer()
    by_service: dict[str, list] = defaultdict(list)
    for d in spans:
        attrs = dict(d.get("attrs", {}) or {})
        service = str(attrs.get("service", "dragonfly"))
        s = Span(tracer, d.get("name", "?"), d.get("trace_id", ""),
                 d.get("parent_id", ""), attrs)
        s.span_id = d.get("span_id", s.span_id)
        s.start = float(d.get("start", 0.0))
        s.end = s.start + float(d.get("duration_ms", 0.0)) / 1e3
        s.status = d.get("status", "ok")
        s.error = d.get("error", "")
        by_service[service].append(s)
    sent = 0
    for service, group in by_service.items():
        for i in range(0, len(group), batch):
            req = spans_to_otlp(group[i : i + batch], service)
            r = urllib.request.Request(
                endpoint.rstrip("/") + "/v1/traces",
                data=json.dumps(req).encode(),
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(r, timeout=30).close()
            sent += 1
    return sent


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dftrace", description="merge span files; critical paths + stage table"
    )
    ap.add_argument("files", nargs="+", help="span JSONL / OTLP JSONL files (globs ok)")
    ap.add_argument("--trace", default="", help="only this trace id (prefix match)")
    ap.add_argument("--top", type=int, default=5,
                    help="print the N longest traces (default 5)")
    ap.add_argument("--otlp", default="",
                    help="forward merged spans to this collector base URL")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (traces + stage table)")
    args = ap.parse_args(argv)

    spans = load_spans(args.files)
    if not spans:
        print("dftrace: no spans found", file=sys.stderr)
        return 1
    traces = assemble_traces(spans)
    if args.trace:
        traces = {t: s for t, s in traces.items() if t.startswith(args.trace)}
        if not traces:
            print(f"dftrace: no trace matches {args.trace!r}", file=sys.stderr)
            return 1
        # the stage table must describe the trace(s) being inspected, not
        # every span the input files happened to hold
        spans = [s for items in traces.values() for s in items]

    if args.otlp:
        sent = forward_otlp(spans, args.otlp)
        print(f"forwarded {len(spans)} spans in {sent} OTLP batches to {args.otlp}")

    def trace_wall(items: list[dict]) -> float:
        p = critical_path(items)
        return p[0][0].get("duration_ms", 0.0) if p else 0.0

    ranked = sorted(traces.items(), key=lambda kv: -trace_wall(kv[1]))
    if args.json:
        payload = {
            "traces": [
                {
                    "trace_id": tid,
                    "spans": len(items),
                    "wall_ms": trace_wall(items),
                    "critical_path": [
                        {
                            "name": s.get("name"),
                            "service": (s.get("attrs") or {}).get("service", ""),
                            "span_ms": s.get("duration_ms", 0.0),
                            "exclusive_ms": round(excl, 3),
                            "attrs": s.get("attrs", {}),
                        }
                        for s, excl in critical_path(items)
                    ],
                }
                for tid, items in ranked[: args.top]
            ],
            "stages": stage_table(spans),
        }
        json.dump(payload, sys.stdout, indent=1)
        print()
        return 0

    print(f"{len(spans)} spans, {len(traces)} traces from {len(args.files)} inputs\n")
    for tid, items in ranked[: args.top]:
        print_trace(tid, items)
        print()
    print("stage table (all traces):")
    print(f"  {'span name':34s} {'count':>6s} {'p50 ms':>9s} {'p95 ms':>9s} {'max ms':>9s} {'total ms':>10s}")
    for row in stage_table(spans):
        print(
            f"  {row['name']:34s} {row['count']:6d} {row['p50_ms']:9.2f} "
            f"{row['p95_ms']:9.2f} {row['max_ms']:9.2f} {row['total_ms']:10.1f}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
