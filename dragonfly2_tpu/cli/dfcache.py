"""dfcache: the P2P cluster cache CLI.

Parity with reference client/dfcache/dfcache.go:44-162 (Stat/Import/Export/
Delete a file in the cluster cache) + cmd/dfcache. Talks to the local daemon
over its unix-socket RPC, spawning it if needed (same behavior as dfget).

  python -m dragonfly2_tpu.cli.dfcache import ./model.bin --tag llama
  python -m dragonfly2_tpu.cli.dfcache stat   <task-id>
  python -m dragonfly2_tpu.cli.dfcache export <task-id> -O ./copy.bin
  python -m dragonfly2_tpu.cli.dfcache rm     <task-id>
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

from dragonfly2_tpu.cli.dfget import DEFAULT_SOCK, ensure_daemon
from dragonfly2_tpu.rpc.core import RpcClient, RpcError


async def _amain(args: argparse.Namespace) -> int:
    if not await ensure_daemon(
        args.sock, args.scheduler, args.storage,
        no_spawn=args.no_spawn, spawn_timeout=args.spawn_timeout,
    ):
        return 1
    client = RpcClient(args.sock, timeout=args.timeout)
    try:
        if args.cmd == "import":
            result = await client.call(
                "import_file",
                {
                    "path": os.path.abspath(args.path),
                    "tag": args.tag,
                    "application": args.application,
                },
            )
            print(json.dumps(result))
        elif args.cmd == "stat":
            result = await client.call("stat_task", {"task_id": args.task_id})
            if result is None:
                print(f"error: task {args.task_id} not found in local cache", file=sys.stderr)
                return 1
            print(json.dumps(result))
        elif args.cmd == "export":
            await client.call(
                "export_task",
                {"task_id": args.task_id, "output": os.path.abspath(args.output)},
            )
            print(f"exported {args.task_id} -> {args.output}")
        elif args.cmd == "rm":
            await client.call("delete_task", {"task_id": args.task_id})
            print(f"deleted {args.task_id}")
        return 0
    except RpcError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        await client.close()


def main() -> None:
    ap = argparse.ArgumentParser(prog="dfcache", description="P2P cluster cache CLI")
    ap.add_argument("--sock", default=DEFAULT_SOCK)
    ap.add_argument("--scheduler", default=None, help="scheduler addr (spawn only)")
    ap.add_argument("--storage", default=None, help="daemon storage root (spawn only)")
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("--spawn-timeout", type=float, default=15.0)
    ap.add_argument("--no-spawn", action="store_true")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("import", help="add a local file to the cluster cache")
    p.add_argument("path")
    p.add_argument("--tag", default="")
    p.add_argument("--application", default="")
    p = sub.add_parser("stat", help="stat a cached task")
    p.add_argument("task_id")
    p = sub.add_parser("export", help="export a cached task to a file")
    p.add_argument("task_id")
    p.add_argument("-O", "--output", required=True)
    p = sub.add_parser("rm", help="remove a task from the local cache")
    p.add_argument("task_id")
    args = ap.parse_args()
    sys.exit(asyncio.run(_amain(args)))


if __name__ == "__main__":
    main()
