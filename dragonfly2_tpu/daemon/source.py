"""Back-to-source clients, registered per URL scheme.

Parity with reference pkg/source (source_client.go:102-137 ResourceClient:
GetContentLength / IsSupportRange / Download / GetLastModified, plus the
scheme registry and clients/{http,s3,oss,hdfs,oras}). Here: http(s) via
aiohttp and file:// for local staging + tests (this container has zero
egress, so every origin in practice is localhost or a file). The s3/oss/obs
family rides the same interface once an object-storage backend lands.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path
from typing import AsyncIterator, Optional
from urllib.parse import urlsplit

import aiohttp

from dragonfly2_tpu.utils.pieces import Range


class SourceError(Exception):
    pass


@dataclass
class SourceInfo:
    content_length: int  # -1 when unknown
    supports_range: bool
    last_modified: str = ""
    etag: str = ""


class ResourceClient:
    scheme: str = ""

    async def info(self, url: str, headers: dict | None = None) -> SourceInfo:
        raise NotImplementedError

    async def download(
        self, url: str, rng: Range | None = None, headers: dict | None = None
    ) -> AsyncIterator[bytes]:
        raise NotImplementedError
        yield b""  # pragma: no cover

    async def close(self) -> None:
        pass


class HTTPSourceClient(ResourceClient):
    scheme = "http"

    def __init__(
        self, *, chunk_size: int = 1 << 20, timeout: float = 300.0, ssl_context=None
    ):
        self.chunk_size = chunk_size
        self._timeout = aiohttp.ClientTimeout(total=timeout)
        self._ssl = ssl_context  # e.g. cluster-CA trust for private https origins
        self._session: aiohttp.ClientSession | None = None

    def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            connector = aiohttp.TCPConnector(ssl=self._ssl) if self._ssl is not None else None
            self._session = aiohttp.ClientSession(timeout=self._timeout, connector=connector)
        return self._session

    async def info(self, url: str, headers: dict | None = None) -> SourceInfo:
        async with self._sess().head(url, headers=headers or {}, allow_redirects=True) as resp:
            if resp.status >= 400:
                # some origins reject HEAD; probe with a 1-byte range GET
                return await self._info_via_get(url, headers)
            length = int(resp.headers.get("Content-Length", -1))
            return SourceInfo(
                content_length=length,
                supports_range=resp.headers.get("Accept-Ranges", "").lower() == "bytes",
                last_modified=resp.headers.get("Last-Modified", ""),
                etag=resp.headers.get("ETag", ""),
            )

    async def _info_via_get(self, url: str, headers: dict | None) -> SourceInfo:
        h = dict(headers or {})
        h["Range"] = "bytes=0-0"
        async with self._sess().get(url, headers=h, allow_redirects=True) as resp:
            if resp.status == 206:
                cr = resp.headers.get("Content-Range", "")  # bytes 0-0/N
                total = int(cr.rsplit("/", 1)[1]) if "/" in cr else -1
                return SourceInfo(content_length=total, supports_range=True)
            if resp.status < 400:
                return SourceInfo(
                    content_length=int(resp.headers.get("Content-Length", -1)),
                    supports_range=False,
                )
            raise SourceError(f"origin {url}: HTTP {resp.status}")

    async def download(
        self, url: str, rng: Range | None = None, headers: dict | None = None
    ) -> AsyncIterator[bytes]:
        h = dict(headers or {})
        if rng is not None:
            h["Range"] = rng.header()
        async with self._sess().get(url, headers=h, allow_redirects=True) as resp:
            if resp.status >= 400:
                raise SourceError(f"origin {url}: HTTP {resp.status}")
            if rng is not None and resp.status != 206:
                raise SourceError(f"origin {url}: range not honored (HTTP {resp.status})")
            async for chunk in resp.content.iter_chunked(self.chunk_size):
                yield chunk

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


class FileSourceClient(ResourceClient):
    """file:// origin — local staging for checkpoint fan-out and tests."""

    scheme = "file"

    def __init__(self, *, chunk_size: int = 1 << 20):
        self.chunk_size = chunk_size

    @staticmethod
    def _path(url: str) -> Path:
        parts = urlsplit(url)
        return Path(parts.path)

    async def info(self, url: str, headers: dict | None = None) -> SourceInfo:
        p = self._path(url)
        if not p.is_file():
            raise SourceError(f"no such file: {p}")
        return SourceInfo(content_length=p.stat().st_size, supports_range=True)

    async def download(
        self, url: str, rng: Range | None = None, headers: dict | None = None
    ) -> AsyncIterator[bytes]:
        p = self._path(url)
        if not p.is_file():
            raise SourceError(f"no such file: {p}")
        with open(p, "rb") as f:
            if rng is not None:
                f.seek(rng.start)
                remaining = rng.length
            else:
                remaining = p.stat().st_size
            while remaining > 0:
                chunk = f.read(min(self.chunk_size, remaining))
                if not chunk:
                    raise SourceError(f"short read from {p}")
                remaining -= len(chunk)
                yield chunk


class SourceRegistry:
    """Scheme -> client registry (ref pkg/source register/loader)."""

    def __init__(self, *, http_ssl=None) -> None:
        self._clients: dict[str, ResourceClient] = {}
        http = HTTPSourceClient(ssl_context=http_ssl)
        self.register("http", http)
        self.register("https", http)
        self.register("file", FileSourceClient())

    def register(self, scheme: str, client: ResourceClient) -> None:
        self._clients[scheme] = client

    def client_for(self, url: str) -> ResourceClient:
        scheme = urlsplit(url).scheme or "file"
        client = self._clients.get(scheme)
        if client is None:
            raise SourceError(f"unsupported url scheme: {scheme!r} ({url})")
        return client

    async def info(self, url: str, headers: dict | None = None) -> SourceInfo:
        return await self.client_for(url).info(url, headers)

    async def download(
        self, url: str, rng: Range | None = None, headers: dict | None = None
    ) -> AsyncIterator[bytes]:
        async for chunk in self.client_for(url).download(url, rng, headers):
            yield chunk

    async def close(self) -> None:
        seen = set()
        for c in self._clients.values():
            if id(c) not in seen:
                seen.add(id(c))
                await c.close()
