"""Back-to-source clients, registered per URL scheme.

Parity with reference pkg/source (source_client.go:102-137 ResourceClient:
GetContentLength / IsSupportRange / Download / GetLastModified, plus the
scheme registry and clients/{http,s3,oss,hdfs,oras}). Here: http(s) via
aiohttp, file:// for local staging + tests, and s3:// over the SigV4 client
(any S3-dialect endpoint — which is how OSS/OBS are reached too, via their
S3-compatibility modes). All clients support URL-entry listing where the
protocol can enumerate (HTML auto-index, directory scan, ListObjectsV2),
feeding dfget --recursive.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from pathlib import Path
from typing import AsyncIterator
from urllib.parse import urlsplit

import aiohttp

from dragonfly2_tpu.resilience import faultline
from dragonfly2_tpu.utils.pieces import Range


class SourceError(Exception):
    pass


@dataclass
class SourceInfo:
    content_length: int  # -1 when unknown
    supports_range: bool
    last_modified: str = ""
    etag: str = ""


@dataclass
class URLEntry:
    """One child of a listable URL (ref pkg/source source_client.go:129-137
    URLEntry): used by recursive download. `name` is the final path element
    only; `is_dir` entries are re-listed, files are downloaded."""

    url: str
    name: str
    is_dir: bool


class ResourceClient:
    scheme: str = ""

    async def info(self, url: str, headers: dict | None = None) -> SourceInfo:
        raise NotImplementedError

    async def download(
        self, url: str, rng: Range | None = None, headers: dict | None = None
    ) -> AsyncIterator[bytes]:
        raise NotImplementedError
        yield b""  # pragma: no cover

    async def list_entries(self, url: str, headers: dict | None = None) -> list[URLEntry]:
        """Children of a directory-like URL (ref source.List). Clients that
        cannot enumerate raise SourceError."""
        raise SourceError(f"scheme does not support listing: {url}")

    async def close(self) -> None:
        pass


class HTTPSourceClient(ResourceClient):
    scheme = "http"

    def __init__(
        self, *, chunk_size: int = 1 << 20, timeout: float = 300.0, ssl_context=None
    ):
        self.chunk_size = chunk_size
        self._timeout = aiohttp.ClientTimeout(total=timeout)
        self._ssl = ssl_context  # e.g. cluster-CA trust for private https origins
        self._session: aiohttp.ClientSession | None = None

    def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            connector = aiohttp.TCPConnector(ssl=self._ssl) if self._ssl is not None else None
            self._session = aiohttp.ClientSession(timeout=self._timeout, connector=connector)
        return self._session

    async def info(self, url: str, headers: dict | None = None) -> SourceInfo:
        async with self._sess().head(url, headers=headers or {}, allow_redirects=True) as resp:
            if resp.status >= 400:
                # some origins reject HEAD; probe with a 1-byte range GET
                return await self._info_via_get(url, headers)
            length = int(resp.headers.get("Content-Length", -1))
            return SourceInfo(
                content_length=length,
                supports_range=resp.headers.get("Accept-Ranges", "").lower() == "bytes",
                last_modified=resp.headers.get("Last-Modified", ""),
                etag=resp.headers.get("ETag", ""),
            )

    async def _info_via_get(self, url: str, headers: dict | None) -> SourceInfo:
        h = dict(headers or {})
        h["Range"] = "bytes=0-0"
        async with self._sess().get(url, headers=h, allow_redirects=True) as resp:
            if resp.status == 206:
                cr = resp.headers.get("Content-Range", "")  # bytes 0-0/N
                total = int(cr.rsplit("/", 1)[1]) if "/" in cr else -1
                return SourceInfo(content_length=total, supports_range=True)
            if resp.status < 400:
                return SourceInfo(
                    content_length=int(resp.headers.get("Content-Length", -1)),
                    supports_range=False,
                )
            raise SourceError(f"origin {url}: HTTP {resp.status}")

    async def download(
        self, url: str, rng: Range | None = None, headers: dict | None = None
    ) -> AsyncIterator[bytes]:
        h = dict(headers or {})
        if rng is not None:
            h["Range"] = rng.header()
        async with self._sess().get(url, headers=h, allow_redirects=True) as resp:
            if resp.status >= 400:
                raise SourceError(f"origin {url}: HTTP {resp.status}")
            if rng is not None and resp.status != 206:
                raise SourceError(f"origin {url}: range not honored (HTTP {resp.status})")
            async for chunk in resp.content.iter_chunked(self.chunk_size):
                yield chunk

    async def list_entries(self, url: str, headers: dict | None = None) -> list[URLEntry]:
        """Parse an HTML auto-index page (nginx autoindex / python http.server
        style): every <a href> that resolves to a strict child of this URL is
        an entry; a trailing slash marks a directory."""
        import html as _html
        import re as _re
        from urllib.parse import unquote, urljoin

        req_base = url if url.endswith("/") else url + "/"
        async with self._sess().get(
            req_base, headers=headers or {}, allow_redirects=True
        ) as resp:
            if resp.status >= 400:
                raise SourceError(f"listing {url}: HTTP {resp.status}")
            ctype = resp.headers.get("Content-Type", "")
            if "html" not in ctype:
                raise SourceError(f"listing {url}: not an index page ({ctype})")
            # resolve hrefs against where the index actually lives (the
            # request may have been redirected, e.g. /dir -> /dir/ or a
            # versioned path)
            base = str(resp.url)
            if not base.endswith("/"):
                base += "/"
            page = await resp.text()
        entries: list[URLEntry] = []
        seen: set[str] = set()
        for href in _re.findall(r'<a\s[^>]*href="([^"]+)"', page, _re.IGNORECASE):
            href = _html.unescape(href)
            child = urljoin(base, href)
            if not child.startswith(base) or child == base:
                continue  # parent links, absolute escapes, sort links
            rel = child[len(base):]
            if "?" in rel or "#" in rel:
                continue
            is_dir = rel.endswith("/")
            rel = rel.rstrip("/")
            if "/" in rel or not rel:
                continue  # only immediate children; deeper levels via recursion
            name = unquote(rel)
            # a hostile index can smuggle separators/.. through percent
            # encoding (..%2F..) — the decoded NAME joins local paths, so it
            # must be a single clean path element or the mirror writes
            # outside --output
            if not name or name in (".", "..") or "/" in name or "\\" in name:
                continue
            if name in seen:
                continue
            seen.add(name)
            entries.append(URLEntry(url=child, name=name, is_dir=is_dir))
        return entries

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


class FileSourceClient(ResourceClient):
    """file:// origin — local staging for checkpoint fan-out and tests."""

    scheme = "file"

    def __init__(self, *, chunk_size: int = 1 << 20):
        self.chunk_size = chunk_size

    @staticmethod
    def _path(url: str) -> Path:
        parts = urlsplit(url)
        return Path(parts.path)

    async def info(self, url: str, headers: dict | None = None) -> SourceInfo:
        p = self._path(url)
        if not p.is_file():
            raise SourceError(f"no such file: {p}")
        return SourceInfo(content_length=p.stat().st_size, supports_range=True)

    async def download(
        self, url: str, rng: Range | None = None, headers: dict | None = None
    ) -> AsyncIterator[bytes]:
        p = self._path(url)
        if not p.is_file():
            raise SourceError(f"no such file: {p}")
        with open(p, "rb") as f:
            if rng is not None:
                f.seek(rng.start)
                remaining = rng.length
            else:
                remaining = p.stat().st_size
            while remaining > 0:
                chunk = f.read(min(self.chunk_size, remaining))
                if not chunk:
                    raise SourceError(f"short read from {p}")
                remaining -= len(chunk)
                yield chunk

    async def list_entries(self, url: str, headers: dict | None = None) -> list[URLEntry]:
        p = self._path(url)
        if not p.is_dir():
            raise SourceError(f"not a directory: {p}")
        entries = []
        for child in sorted(p.iterdir()):
            is_dir = child.is_dir()
            entries.append(
                URLEntry(
                    url=f"file://{child}" + ("/" if is_dir else ""),
                    name=child.name,
                    is_dir=is_dir,
                )
            )
        return entries


class S3SourceClient(ResourceClient):
    """s3://bucket/key origins (ref pkg/source/clients/s3protocol): signed
    HeadObject/ranged GetObject against any S3-dialect endpoint, plus
    delimiter-based listing so s3:// trees work with recursive download.
    Credentials/endpoint come from the environment (AWS_ENDPOINT_URL,
    AWS_ACCESS_KEY_ID, AWS_SECRET_ACCESS_KEY, AWS_REGION) unless a
    pre-built client is injected."""

    scheme = "s3"

    def __init__(self, client=None):
        self._client = client  # lazily built from env on first use

    def _c(self):
        if self._client is None:
            from dragonfly2_tpu.objectstorage.s3client import S3Client, S3Config

            self._client = S3Client(S3Config.from_env())
        return self._client

    def _split(self, url: str) -> tuple[str, str]:
        parts = urlsplit(url)
        bucket, key = parts.netloc, parts.path.lstrip("/")
        if not bucket:
            raise SourceError(f"bad {self.scheme} url (no bucket): {url}")
        return bucket, key

    async def info(self, url: str, headers: dict | None = None) -> SourceInfo:
        from dragonfly2_tpu.objectstorage.s3client import S3Error

        bucket, key = self._split(url)
        try:
            obj = await self._c().head_object(bucket, key)
        except S3Error as e:
            raise SourceError(f"{self.scheme} head {url}: {e}") from e
        return SourceInfo(
            content_length=obj.size, supports_range=True,
            last_modified=obj.last_modified, etag=obj.etag,
        )

    async def download(
        self, url: str, rng: Range | None = None, headers: dict | None = None
    ) -> AsyncIterator[bytes]:
        from dragonfly2_tpu.objectstorage.s3client import S3Error

        bucket, key = self._split(url)
        try:
            async for chunk in self._c().get_object(
                bucket, key, range_header=rng.header() if rng is not None else ""
            ):
                yield chunk
        except S3Error as e:
            raise SourceError(f"{self.scheme} get {url}: {e}") from e

    async def list_entries(self, url: str, headers: dict | None = None) -> list[URLEntry]:
        from dragonfly2_tpu.objectstorage.s3client import S3Error

        bucket, prefix = self._split(url)
        if prefix and not prefix.endswith("/"):
            prefix += "/"
        try:
            res = await self._c().list_objects(bucket, prefix=prefix, delimiter="/")
        except S3Error as e:
            raise SourceError(f"{self.scheme} list {url}: {e}") from e
        entries: list[URLEntry] = []
        for o in res.objects:
            name = o.key[len(prefix):]
            if not name or name in (".", "..") or "/" in name or "\\" in name:
                continue
            entries.append(
                URLEntry(url=f"{self.scheme}://{bucket}/{o.key}", name=name, is_dir=False)
            )
        for p in res.common_prefixes:
            name = p[len(prefix):].rstrip("/")
            if not name or name in (".", "..") or "/" in name or "\\" in name:
                continue
            entries.append(
                URLEntry(url=f"{self.scheme}://{bucket}/{p}", name=name, is_dir=True)
            )
        return entries

    async def close(self) -> None:
        if self._client is not None:
            await self._client.close()


class OSSSourceClient(S3SourceClient):
    """oss://bucket/key origins (ref pkg/source/clients/ossprotocol, 389 LoC).

    Aliyun OSS speaks an S3-compatible dialect; the hand-rolled SigV4 client
    covers it, so this is the s3 client bound to OSS_* credentials
    (OSS_ENDPOINT, OSS_ACCESS_KEY_ID, OSS_ACCESS_KEY_SECRET, OSS_REGION) —
    the same dialect-reuse the reference gets from aws-sdk-go pointed at an
    OSS endpoint. URLs keep their oss:// scheme in task ids and rewrites."""

    scheme = "oss"

    def _c(self):
        if self._client is None:
            from dragonfly2_tpu.objectstorage.s3client import S3Client, S3Config

            e = os.environ
            endpoint = e.get("OSS_ENDPOINT", "")
            if not endpoint:
                raise SourceError("no OSS endpoint configured (OSS_ENDPOINT)")
            self._client = S3Client(
                S3Config(
                    endpoint=endpoint,
                    access_key=e.get("OSS_ACCESS_KEY_ID", ""),
                    secret_key=e.get("OSS_ACCESS_KEY_SECRET", ""),
                    region=e.get("OSS_REGION", "us-east-1"),
                )
            )
        return self._client


class SourceRegistry:
    """Scheme -> client registry (ref pkg/source register/loader)."""

    def __init__(self, *, http_ssl=None) -> None:
        from dragonfly2_tpu.daemon.hdfs_source import HDFSSourceClient
        from dragonfly2_tpu.daemon.oras_source import ORASSourceClient

        self._clients: dict[str, ResourceClient] = {}
        http = HTTPSourceClient(ssl_context=http_ssl)
        self.register("http", http)
        self.register("https", http)
        self.register("file", FileSourceClient())
        self.register("s3", S3SourceClient())
        self.register("oss", OSSSourceClient())
        self.register("oras", ORASSourceClient())
        self.register("hdfs", HDFSSourceClient())
        self._register_plugins()

    def _register_plugins(self) -> None:
        """External protocol clients by import path (ref pkg/source/loader +
        internal/dfplugin): DRAGONFLY_SOURCE_PLUGINS="scheme=pkg.mod:factory,…"
        — each factory yields a ResourceClient for its scheme. A bad spec
        fails the daemon at boot, not on first download."""
        raw = os.environ.get("DRAGONFLY_SOURCE_PLUGINS", "")
        if not raw:
            return
        from dragonfly2_tpu.utils.plugins import load_object, parse_plugin_map, require_methods

        for scheme, spec in parse_plugin_map(raw).items():
            client = load_object(spec)
            require_methods(client, ("info", "download", "close"), spec=spec, kind="source")
            # urlsplit lowercases schemes, so the registry key must match
            self.register(scheme.lower(), client)

    def register(self, scheme: str, client: ResourceClient) -> None:
        self._clients[scheme] = client

    def client_for(self, url: str) -> ResourceClient:
        scheme = urlsplit(url).scheme or "file"
        client = self._clients.get(scheme)
        if client is None:
            raise SourceError(f"unsupported url scheme: {scheme!r} ({url})")
        return client

    async def info(self, url: str, headers: dict | None = None) -> SourceInfo:
        return await self.client_for(url).info(url, headers)

    async def download(
        self, url: str, rng: Range | None = None, headers: dict | None = None
    ) -> AsyncIterator[bytes]:
        # Faultline rides the registry (one seam covers every scheme client).
        # Exactly TWO rng decisions per stream — `source.read` (latency/error/
        # drop) at open, `source.body` (truncate/corrupt) on the first chunk —
        # so injection probability is per-READ, independent of how the
        # transport happens to chunk the body (per-chunk draws would compound
        # a small rate into near-certain failure on a 64-chunk piece).
        # Disabled cost: one module-global is-None check.
        if faultline.ACTIVE is None:
            async for chunk in self.client_for(url).download(url, rng, headers):
                yield chunk
            return
        await faultline.ACTIVE.fire("source.read")
        first = True
        async for chunk in self.client_for(url).download(url, rng, headers):
            if first:
                first = False
                mutated = faultline.ACTIVE.mutate("source.body", chunk)
                if len(mutated) != len(chunk):  # truncated: short body, then EOF
                    if mutated:
                        yield mutated
                    return
                chunk = mutated
            yield chunk

    async def list_entries(self, url: str, headers: dict | None = None) -> list[URLEntry]:
        client = self.client_for(url)
        lister = getattr(client, "list_entries", None)
        if lister is None:  # duck-typed plugin without listing support
            raise SourceError(f"scheme does not support listing: {url}")
        return await lister(url, headers)

    async def close(self) -> None:
        seen = set()
        for c in self._clients.values():
            if id(c) not in seen:
                seen.add(id(c))
                await c.close()
