"""Data plane: the peer daemon — piece storage, download conductor, upload
server, back-to-source clients (reference client/daemon equivalents)."""
