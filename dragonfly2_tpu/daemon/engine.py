"""Peer engine: task-level API over the conductor (download / stream / seed).

Parity with reference client/daemon/peer/peertask_manager.go:47-58
(StartFileTask / StartSeedTask) and the reuse fast path (peertask_reuse.go):
completed tasks short-circuit to local storage, partial tasks resume from
their finished-piece bitset. One engine per daemon process; it owns the
storage manager, the upload (piece) server, and the scheduler client.
"""

from __future__ import annotations

import asyncio
import logging
import weakref
from pathlib import Path

from dragonfly2_tpu.daemon.conductor import ConductorConfig, PeerTaskConductor, SchedulerClient
from dragonfly2_tpu.daemon.source import SourceRegistry
from dragonfly2_tpu.daemon.storage import OncePinRelease, StorageManager, TaskStorage
from dragonfly2_tpu.daemon.upload import UploadServer
from dragonfly2_tpu.resilience import deadline as dl
from dragonfly2_tpu.scheduler.service import HostInfo, SchedulerService, TaskMeta
from dragonfly2_tpu.utils import idgen

logger = logging.getLogger(__name__)


class RangeOutOfBounds(ValueError):
    """An output_range outside the downloaded task's content length — a
    caller error, distinguished from internal ValueErrors so rpc adapters can
    map ONLY this to bad_request."""


class InProcessSchedulerClient:
    """SchedulerClient over a same-process SchedulerService (test/e2e wiring;
    the wire client in dragonfly2_tpu.rpc implements the same protocol)."""

    def __init__(self, service: SchedulerService):
        self._svc = service

    async def register_peer(self, peer_id, meta, host):
        return await self._svc.register_peer(peer_id, meta, host)

    async def report_task_metadata(self, task_id, *, content_length, piece_size, digest="", direct_piece=b""):
        self._svc.report_task_metadata(
            task_id, content_length=content_length, piece_size=piece_size,
            digest=digest, direct_piece=direct_piece,
        )

    async def report_piece_result(self, peer_id, piece_index, *, success, cost_ms=0.0, parent_id=""):
        self._svc.report_piece_result(
            peer_id, piece_index, success=success, cost_ms=cost_ms, parent_id=parent_id
        )

    async def report_pieces(self, peer_id, reports):
        return self._svc.report_pieces(peer_id, list(reports))

    async def report_batch(self, peer_id, reports, result=None):
        return self._svc.report_batch(peer_id, list(reports), result=result)

    async def announce_task(self, peer_id, meta, host, *, content_length, piece_size, piece_indices, digest=""):
        self._svc.announce_task(
            peer_id, meta, host, content_length=content_length,
            piece_size=piece_size, piece_indices=list(piece_indices), digest=digest,
        )

    async def report_peer_result(self, peer_id, *, success, bandwidth_bps=0.0):
        self._svc.report_peer_result(peer_id, success=success, bandwidth_bps=bandwidth_bps)

    async def reschedule(self, peer_id):
        return await self._svc.reschedule(peer_id)

    async def leave_peer(self, peer_id):
        self._svc.leave_peer(peer_id)

    async def leave_host(self, host_id):
        self._svc.leave_host(host_id)

    async def announce_host(self, host, stats=None):
        self._svc.announce_host(host, stats)

    async def sync_probes(self, host_id, results):
        return self._svc.sync_probes(host_id, results)


class PeerEngine:
    def __init__(
        self,
        *,
        storage_root: str | Path,
        scheduler: SchedulerClient,
        ip: str = "127.0.0.1",
        hostname: str = "",
        host_type: str = "normal",
        idc: str = "",
        location: str = "",
        upload_port: int = 0,
        conductor_config: ConductorConfig | None = None,
        total_download_rate_bps: float | None = None,
        storage_gc_interval: float = 900.0,
        storage_ttl: float = 24 * 3600,
        storage_capacity_bytes: int | None = None,
        disk_gc_threshold: float | None = None,
        data_tls=None,
    ):
        from dragonfly2_tpu.daemon.traffic_shaper import (
            TOTAL_DOWNLOAD_RATE_BPS,
            SamplingTrafficShaper,
        )

        self.ip = ip
        self.hostname = hostname or f"peer-{idgen.local_ip()}"
        # TCP RPC port, set by the daemon server when it listens on TCP —
        # advertised via HostInfo.port so the scheduler can trigger seeds.
        self.rpc_port = 0
        self.host_type = host_type
        self.idc = idc
        self.location = location
        self.storage = StorageManager(storage_root)
        self.scheduler = scheduler
        self.sources = SourceRegistry()
        # secure-by-default data plane: a DataPlaneTls bundle
        # (security/transport.py) puts the upload server AND every piece
        # fetch on mTLS with the cipher the host's one-shot probe picked;
        # None keeps the plain wire (tests, closed networks)
        self.data_tls = data_tls
        self.upload = UploadServer(
            self.storage, host=ip, port=upload_port,
            tls=None if data_tls is None else data_tls.server_ctx,
        )
        self.conductor_config = conductor_config or ConductorConfig()
        # ONE host-wide download budget shared by all concurrent conductors
        # (ref NewSamplingTrafficShaper, traffic_shaper.go:139) — per-task
        # buckets alone would oversubscribe the host N×.
        self.shaper = SamplingTrafficShaper(
            total_rate_bps=(
                TOTAL_DOWNLOAD_RATE_BPS
                if total_download_rate_bps is None
                else total_download_rate_bps
            ),
            per_flow_cap_bps=self.conductor_config.download_rate_bps,
        )
        # Periodic storage reclaim (ref gc_manager.go:54-77 ticker →
        # storage CleanUp): TTL plus capacity/disk watermarks, LRU over
        # complete tasks, in-progress immune (daemon/storage.py reclaim).
        from dragonfly2_tpu.utils.gcreg import GC

        self.gc = GC()
        self.gc.add(
            "storage-reclaim",
            storage_gc_interval,
            lambda: self._run_reclaim(
                ttl=storage_ttl,
                capacity_bytes=storage_capacity_bytes,
                disk_high_ratio=disk_gc_threshold,
            ),
        )
        # close pooled piece-fetch sockets idle past their keep-alive window
        # (parents never contacted again must not pin fds forever)
        self.gc.add("raw-pool-prune", 120.0, self._prune_raw_pool)
        self._raw_client = None
        self._piece_pipeline = None
        # Live conductor tasks: a caller cancelling its download_task future
        # does NOT cancel the conductor (awaiting a task never owns it) — the
        # engine owns them, so stop() (and the test crash harness) can
        # terminate in-flight downloads instead of leaving orphan tasks
        # writing into storage after the engine is gone.
        self._conductors: set[asyncio.Task] = set()
        # Stable per-task peer id for possession announces: announce_task
        # supersedes every OTHER same-host row, and create_peer returns an
        # existing row unchanged — so reusing one id per task makes the
        # periodic keepalive announce an exact no-op on the scheduler
        # (a fresh random id per announce would delete the live seed row,
        # severing children's DAG edges every interval). A conductor's
        # download registers its own id here so later announces adopt the
        # very row children are already attached to.
        self._announce_peer_ids: dict[str, str] = {}
        self._started = False

    async def _run_reclaim(self, **kw) -> None:
        # Off the event loop: the sweep stats every task file and rmtree's
        # evictees — seconds of blocking disk I/O on a large store would
        # freeze every active transfer (pins keep the thread from deleting
        # anything a conductor or an in-flight read is using).
        removed = await asyncio.to_thread(self.storage.reclaim, **kw)
        if any(removed.values()):
            logger.info("storage reclaim: %s", removed)

    @property
    def host_id(self) -> str:
        return idgen.host_id(self.hostname, self.upload.port)

    def host_info(self) -> HostInfo:
        return HostInfo(
            id=self.host_id,
            ip=self.ip,
            hostname=self.hostname,
            port=self.rpc_port,
            download_port=self.upload.port,
            type=self.host_type,
            idc=self.idc,
            location=self.location,
        )

    async def start(self) -> None:
        if not self._started:
            from dragonfly2_tpu.daemon import metrics

            # one-hot wire posture for dftop: which cipher piece MB/s rides
            active = self.data_tls.policy if self.data_tls is not None else "plain"
            for cipher in ("plain", "aes-gcm", "chacha20"):
                metrics.PIECE_CIPHER.set(
                    1.0 if cipher == active else 0.0, cipher=cipher
                )
            # Crash recovery BEFORE the upload server opens: the audit
            # digest-verifies every claimed piece of restored incomplete
            # tasks (a metadata snapshot can claim bits over torn data after
            # a machine crash), so a torn piece is never servable even
            # briefly. Disk-heavy → worker thread.
            recovered = await asyncio.to_thread(self.storage.recover)
            await self.upload.start()
            self.gc.start()
            self._started = True
            await self._announce_recovered(recovered)

    async def _announce_recovered(self, recovered) -> None:
        """Re-announce every restored task's surviving pieces so this peer
        rejoins the swarm as a (possibly partial) seed — the reference daemon
        reloads data+metadata and resumes serving (local_storage.go), but a
        rejoin the scheduler never hears about serves nobody. Best-effort:
        a scheduler that is down at boot is retried by the daemon's periodic
        announce loop (announce_tasks)."""
        from dragonfly2_tpu.daemon import metrics

        for ts, kept, dropped in recovered:
            if dropped:
                metrics.PIECE_DROPPED_RECOVERY_TOTAL.inc(len(dropped))
            if kept == 0:
                continue  # fully-torn task: drops counted, nothing to announce
            metrics.PIECE_RECOVERED_TOTAL.inc(kept)
            state = "done" if ts.meta.done else "partial"
            if await self._announce_possession(ts):
                metrics.TASK_RECOVERED_TOTAL.inc(state=state)
                logger.info(
                    "task %s: recovered %d piece(s) (%s), re-announced",
                    ts.meta.task_id[:12], kept, state,
                )

    async def _announce_possession(self, ts: TaskStorage) -> bool:
        """One announce_task RPC claiming this host's on-disk pieces; the
        scheduler supersedes any ghost peer rows this host left behind."""
        m = ts.meta
        meta = TaskMeta(
            task_id=m.task_id, url=m.url, digest=m.digest,
            tag=m.tag, application=m.application,
        )
        peer_id = self._announce_peer_ids.setdefault(
            m.task_id, idgen.peer_id(self.ip, self.hostname)
        )
        try:
            await self.scheduler.announce_task(
                peer_id, meta, self.host_info(),
                content_length=m.content_length, piece_size=m.piece_size,
                piece_indices=sorted(ts.finished.indices()), digest=m.digest,
            )
            return True
        except Exception:  # noqa: BLE001 — boot/keepalive announce is advisory;
            # the periodic loop retries and downloads still work unannounced
            logger.warning("announce of task %s failed", m.task_id[:12], exc_info=True)
            return False

    async def announce_tasks(self, *, include_partial: bool = True) -> int:
        """Re-announce possession of locally-held tasks (daemon announce
        loop): after a scheduler restart its resource pool is empty, and the
        existing backoff+breaker reconnect alone would leave this host's
        content invisible — the scheduler rebuilds its view from these
        announces alone. Stable per-task peer ids make this idempotent on a
        scheduler that did NOT restart (the announce adopts the existing
        row). Partial tasks are included by default — a recovered partial
        seed must survive a scheduler restart that postdates the boot
        announce — but a PINNED incomplete task is skipped: its running
        conductor owns the scheduler-side peer row."""
        n = 0
        for ts in self.storage.tasks():
            m = ts.meta
            if m.total_pieces is None or m.total_pieces < 0 or ts.finished_count() == 0:
                continue
            if not m.done and not include_partial:
                continue
            if not m.done and ts.pins > 0:
                continue  # a running conductor owns this task's peer row
            if await self._announce_possession(ts):
                n += 1
        return n

    def _shared_raw_client(self):
        """One raw range client for ALL conductors: keep-alive connections to
        a parent survive across tasks, so a recursive dfget (or a multi-file
        checkpoint fetch) reuses sockets instead of reconnecting per file.
        Under TLS the sharing matters twice: pooled connections skip the
        handshake entirely, and the bundle's session cache lets every fresh
        connect across all tasks resume abbreviated."""
        if self._raw_client is None:
            from dragonfly2_tpu.daemon.rawrange import RawRangeClient

            self._raw_client = RawRangeClient(tls=self.data_tls)
        return self._raw_client

    def _shared_pipeline(self):
        """One piece pipeline (buffer pool + hash threads) for ALL
        conductors: pooled piece buffers and the hash-on-receive executor
        are host-level resources — per-task pools would re-pay the warmup
        allocations on every file of a multi-file checkpoint fetch."""
        if self._piece_pipeline is None:
            from dragonfly2_tpu.daemon.pipeline import PiecePipeline

            self._piece_pipeline = PiecePipeline()
        return self._piece_pipeline

    async def _prune_raw_pool(self) -> None:
        if self._raw_client is not None:
            closed = self._raw_client.prune()
            if closed:
                logger.debug("raw range pool: pruned %d idle sockets", closed)

    async def cancel_conductors(self) -> None:
        """Terminate in-flight downloads (shutdown / crash-harness path)."""
        for t in list(self._conductors):
            t.cancel()
        if self._conductors:
            await asyncio.gather(*list(self._conductors), return_exceptions=True)
        self._conductors.clear()

    async def stop(self) -> None:
        if self._started:
            await self.cancel_conductors()
            self.gc.stop()
            await self.upload.stop()
            await self.sources.close()
            if self._raw_client is not None:
                await self._raw_client.close()
                self._raw_client = None
            if self._piece_pipeline is not None:
                self._piece_pipeline.close()
                self._piece_pipeline = None
            self.storage.flush_all()  # persist debounced piece metadata
            self._started = False

    # ---- task API (ref StartFileTask / StartSeedTask) ----

    def make_meta(self, url: str, **kw) -> TaskMeta:
        if url.startswith("d7y://cache/"):
            # imported cache object: the URL carries its digest-keyed task id
            # (see import_file) — recompute nothing, or two hosts disagree
            task_id = url.rsplit("/", 1)[1]
        else:
            task_id = idgen.task_id(
                url,
                filters=kw.get("filters", ()),
                tag=kw.get("tag", ""),
                application=kw.get("application", ""),
                digest=kw.get("digest", ""),
            )
        return TaskMeta(
            task_id=task_id,
            url=url,
            digest=kw.get("digest", ""),
            tag=kw.get("tag", ""),
            application=kw.get("application", ""),
            filters=tuple(kw.get("filters", ())),
        )

    async def _reuse_or_conduct(
        self,
        meta: TaskMeta,
        headers: dict[str, str] | None,
        *,
        seed: bool = False,
        priority: float = 1.0,
    ):
        """Shared reuse/purge/conductor logic for download_task + stream_task.

        Returns (ts, producer): producer is None on the reuse fast path, else
        a running conductor future; ts has metadata set (Content-Length known)
        by the time this returns."""
        import asyncio

        ts = self.storage.find_completed_task(meta.task_id)
        if ts is not None:
            # Pin across the verify AND the caller's subsequent use (export /
            # stream): the reclaim sweep runs in a thread and must never
            # rmtree a task an operation holds. Callers unpin when done.
            ts.pin()
            ok = False
            try:
                # verify() hashes the whole file — off the event loop
                ok = await asyncio.to_thread(ts.verify)
            finally:
                if not ok:
                    ts.unpin()
            if ok:
                logger.info("task %s: reuse fast path", meta.task_id[:12])
                return ts, None
            # completed-but-corrupt local copy: purge so the conductor
            # re-fetches instead of short-circuiting on the full bitset
            logger.warning("task %s: local copy corrupt, purging", meta.task_id[:12])
            self.storage.delete_task(meta.task_id)
        peer_id = idgen.peer_id(self.ip, self.hostname, seed=seed)
        # later possession announces adopt this download's row (same id)
        # instead of superseding it out from under attached children
        self._announce_peer_ids[meta.task_id] = peer_id
        conductor = PeerTaskConductor(
            peer_id=peer_id,
            meta=meta,
            host=self.host_info(),
            scheduler=self.scheduler,
            storage=self.storage,
            sources=self.sources,
            config=self.conductor_config,
            headers=headers,
            shaper=self.shaper,
            raw_client=self._shared_raw_client(),
            pipeline=self._shared_pipeline(),
            data_tls=self.data_tls,
            flow_weight=priority,
        )
        producer = asyncio.ensure_future(conductor.run())
        self._conductors.add(producer)
        producer.add_done_callback(self._conductors.discard)
        # Wait until the conductor registered storage + metadata. Polling:
        # the TaskStorage (and its progress event) does not exist until the
        # conductor registers with the scheduler, so there is nothing to
        # subscribe to yet; registration is a couple of RPC round-trips.
        while True:
            ts = self.storage.get(meta.task_id)
            if ts is not None and ts.meta.total_pieces >= 0:
                ts.pin()  # released by the caller when its operation completes
                return ts, producer
            if producer.done():
                producer.result()  # raise the failure
                raise IOError(f"task {meta.task_id}: no metadata after completion")
            await asyncio.sleep(0.01)

    async def download_task(
        self,
        url: str,
        *,
        output: str | Path | None = None,
        output_range: "tuple[int, int] | None" = None,
        seed: bool = False,
        headers: dict[str, str] | None = None,
        timeout: float | None = None,
        priority: float = 1.0,
        **meta_kw,
    ) -> TaskStorage:
        """Download (or reuse) a task; optionally export to a named file.

        `priority` is the task's tenant weight in the host traffic shaper:
        under contention, concurrent tasks' bandwidth shares converge to the
        ratio of their weights (a priority-3 task gets ~3x a priority-1
        neighbor); with headroom it changes nothing.

        `output_range=(start, end)` (inclusive bytes, HTTP Range semantics)
        exports just that slice — performed HERE, under this operation's pin,
        so a threaded storage reclaim can never evict the task between the
        download completing and the ranged export reading it. Raises
        ValueError when the range falls outside the task's content length.

        `timeout` is the task's whole-download budget: it rides the deadline
        contextvar into the conductor (whose watchdog narrows it) and from
        there into every rpc call and piece fetch (resilience.deadline)."""
        from dragonfly2_tpu.daemon import metrics
        from dragonfly2_tpu.observability.tracing import default_tracer
        from dragonfly2_tpu.utils.pieces import Range

        await self.start()
        meta = self.make_meta(url, **meta_kw)
        metrics.TASK_TOTAL.inc(type="seed" if seed else "file")
        if seed:
            metrics.SEED_TASK_TOTAL.inc()

        # the span opens BEFORE the conductor task is created so every
        # conductor-side span (dispatch rounds, pieces, report flushes, the
        # scheduler RPCs) nests under daemon.peer_task through the task's
        # captured Context — spanning only the await left the conductor
        # parented to whatever the caller had current
        with default_tracer().span(
            "daemon.peer_task", task_id=meta.task_id, url=url, seed=seed
        ):
            with dl.scope(timeout):
                # the conductor task is created inside the scope, so it
                # inherits the budget through its captured Context
                ts, producer = await self._reuse_or_conduct(
                    meta, headers, seed=seed, priority=priority
                )
            pinned = ts  # engine-held pin for this operation (reclaim immunity)
            try:
                if producer is not None:
                    metrics.CONCURRENT_TASKS.inc()
                    try:
                        ts = await producer
                    except Exception:
                        metrics.TASK_RESULT_TOTAL.inc(success="false")
                        raise
                    finally:
                        metrics.CONCURRENT_TASKS.dec()
                    metrics.TASK_RESULT_TOTAL.inc(success="true")
                if output is not None:
                    if output_range is not None:
                        start, end = output_range
                        if start < 0 or end < start or end >= ts.meta.content_length:
                            raise RangeOutOfBounds(
                                f"range {start}-{end} out of bounds for "
                                f"{ts.meta.content_length} bytes"
                            )
                        await ts.export_range(output, Range(start, end - start + 1))
                    else:
                        await ts.export_to(output)
                return ts
            finally:
                pinned.unpin()

    async def stream_task(
        self,
        url: str,
        *,
        headers: dict[str, str] | None = None,
        timeout: float | None = None,
        **meta_kw,
    ):
        """Start (or reuse) a task and return (content_length, async-iterator)
        yielding the body in piece order as pieces land — the daemon's
        StartStreamTask path (ref peertask_manager.go:52, used by the proxy
        transport, transport.go:58-119). Returns as soon as task metadata is
        known, so a proxy can send response headers before the download
        finishes."""
        from dragonfly2_tpu.daemon import metrics

        await self.start()
        meta = self.make_meta(url, **meta_kw)
        metrics.TASK_TOTAL.inc(type="stream")

        with dl.scope(timeout):
            ts, producer = await self._reuse_or_conduct(meta, headers)

        # The operation pin from _reuse_or_conduct is normally released by the
        # body generator's finally — but a caller that never iterates (or
        # closes) the generator (proxy client gone before the transport reads)
        # would leak it, making the task permanently reclaim-immune. A
        # once-only release also wired to the generator's GC covers that path.
        release = OncePinRelease(ts)

        async def body(ts=ts, producer=producer):
            if producer is not None:
                metrics.CONCURRENT_TASKS.inc()
            try:
                async for chunk in ts.stream_ordered(watch=producer):
                    yield chunk
                if producer is not None:
                    await producer  # surface trailing failures (digest check)
                metrics.TASK_RESULT_TOTAL.inc(success="true")
            except BaseException:
                metrics.TASK_RESULT_TOTAL.inc(success="false")
                if producer is not None and not producer.done():
                    producer.cancel()
                raise
            finally:
                release()  # the stream held the operation pin to the last chunk
                if producer is not None:
                    metrics.CONCURRENT_TASKS.dec()

        gen = body()
        weakref.finalize(gen, release)
        return ts.meta.content_length, gen

    async def import_file(
        self,
        path: str | Path,
        *,
        tag: str = "",
        application: str = "",
        piece_size: int | None = None,
    ) -> TaskStorage:
        """Import a local file into the P2P cache (ref dfcache Import,
        client/dfcache/dfcache.go:105 importTask): slice it into pieces in
        local storage, then register with the scheduler as an instantly
        successful peer so other peers can parent off this host (the
        reference's AnnounceTask path, scheduler/service/service_v1.go).
        Keyed by content digest (idgen.persistent_cache_task_id), so identical
        bytes imported under any filename on any host dedupe to one task.
        File I/O and hashing run off the event loop; pieces stream from disk
        (multi-GB model files must not be held in RAM)."""
        await self.start()
        import asyncio

        from dragonfly2_tpu.utils import digest as digestlib
        from dragonfly2_tpu.utils.pieces import compute_piece_size, piece_count, piece_range

        path = Path(path)

        def _hash_and_size() -> tuple[str, int]:
            with open(path, "rb") as f:
                d = digestlib.compute_file("sha256", f)
            return str(d), path.stat().st_size

        dig, size = await asyncio.to_thread(_hash_and_size)
        # piece_size override: checkpoint publishers pick larger pieces than
        # the generic ladder (fewer per-piece round-trips on the fan-out
        # path). The effective size is baked into the task id, so publishers
        # using different geometries yield distinct tasks instead of one task
        # with a conflicting index-keyed digest map.
        if piece_size is None:
            piece_size = compute_piece_size(size)
        task_id = idgen.persistent_cache_task_id(dig, tag, application, piece_size)
        url = f"d7y://cache/{task_id}"
        meta = TaskMeta(
            task_id=task_id, url=url, digest=dig, tag=tag, application=application
        )

        ts = self.storage.find_completed_task(task_id)
        if ts is None:
            ts = self.storage.register_task(task_id, url=url, tag=tag, digest=dig)
            n = piece_count(size, piece_size)
            ts.set_task_info(
                content_length=size, piece_size=piece_size, total_pieces=n, digest=dig
            )
            with open(path, "rb") as f:
                for idx in range(n):
                    r = piece_range(idx, piece_size, size)
                    chunk = await asyncio.to_thread(f.read, r.length)
                    await ts.write_piece(idx, chunk)
            ts.mark_done()

        # announce possession so the scheduler adds this peer as a ready
        # parent — one RPC, no scheduling round (ref AnnounceTask)
        peer_id = idgen.peer_id(self.ip, self.hostname)
        await self.scheduler.announce_task(
            peer_id, meta, self.host_info(),
            content_length=size, piece_size=ts.meta.piece_size,
            piece_indices=list(range(ts.meta.total_pieces)), digest=dig,
        )
        return ts

    async def seed_task(self, task) -> None:
        """seed_trigger hook for SchedulerService: pull the task from origin
        so normal peers can parent off this engine (ref StartSeedTask +
        seeder.ObtainSeeds, client/daemon/rpcserver/seeder.go:49-53)."""
        await self.download_task(
            task.url, seed=True, tag=task.tag, application=task.application,
            digest=task.digest, filters=task.filters,
        )
