"""Node-wide sampling traffic shaper.

Parity with the reference's NewSamplingTrafficShaper
(client/daemon/peer/traffic_shaper.go:139): ONE host-wide download budget
(default 1 GiB/s, client/config/constants.go:46) shared by all concurrent
task conductors, reallocated every sampling interval by each task's observed
need — an idle task's bandwidth flows to the busy ones. Without this, N
concurrent tasks each carrying their own 512 MB/s bucket oversubscribe the
host N×.

Redesign vs the reference: no background goroutine — resampling happens
lazily on the acquire path once the interval elapses (single-threaded asyncio
makes this race-free and testable without a timer task). Observed issuance
alone can't reveal a starved flow's true need (a conductor acquires serially,
so it can only issue what its current allocation grants); a flow that is
BLOCKED in its bucket at sample time is saturated, and its need is taken as a
multiple of its current rate — multiplicative ramp, so a starved flow reaches
any allocation within a few intervals instead of creeping up additively.

Allocation per resample: every flow keeps a guaranteed floor; the spare
budget is split proportionally to observed need; per-flow caps (the 512 MB/s
per-peer limit) redistribute their excess to uncapped flows. Flows younger
than one full interval count as max-need so new downloads ramp immediately.

Tenant priorities (`open_flow(..., weight=)`): each flow's share of the
CONTENDED budget scales by its weight, so two saturated tasks with weights
1 and 3 converge to a 1:3 bandwidth split. For this to be a stable fixed
point, a saturated flow's demand is taken as the PER-FLOW CAP rather than a
multiple of its current rate — ramping off the current rate made allocation
proportional to prior allocation, which compounds every interval and only
stops at the floor/cap rails (the weighted split would never converge to
the weights). Demand-capped shares converge in one resample and still ramp
a starved flow instantly (cap >> anything it had).
"""

from __future__ import annotations

import logging
import time

from dragonfly2_tpu.utils.ratelimit import TokenBucket

logger = logging.getLogger(__name__)

TOTAL_DOWNLOAD_RATE_BPS = float(1 << 30)  # ref constants.go:46
PER_FLOW_CAP_BPS = float(512 << 20)  # ref constants.go:45


class Flow:
    """One task's slice of the host budget; quacks like TokenBucket.acquire."""

    def __init__(
        self,
        shaper: "SamplingTrafficShaper",
        flow_id: str,
        bucket: TokenBucket,
        weight: float = 1.0,
    ):
        self._shaper = shaper
        self.flow_id = flow_id
        self.bucket = bucket
        # tenant priority: scales this flow's share of contended bandwidth
        self.weight = max(1e-6, float(weight))
        self.created_at = time.monotonic()
        self.window_bytes = 0.0  # demand since last resample
        self.pending_bytes = 0.0  # blocked in the bucket right now
        self.blocked_in_window = False  # hit an empty bucket since last sample
        self.consumed_bytes = 0.0  # lifetime, for metrics/tests
        self.closed = False

    @property
    def rate_bps(self) -> float:
        return self.bucket.rate

    @property
    def saturated(self) -> bool:
        """The flow wanted more than its allocation granted this window.
        Both signals matter: pending_bytes catches a flow blocked at the
        moment ANOTHER flow triggers the resample; the sticky window flag
        catches the flow's own past blocks (its own trigger point always has
        pending == 0 — conductors acquire serially)."""
        return self.pending_bytes > 0 or self.blocked_in_window

    async def acquire(self, n: float) -> None:
        self.window_bytes += n
        self._shaper.maybe_resample()
        if self.bucket.try_acquire(n):
            self.consumed_bytes += n
            return
        self.blocked_in_window = True
        self.pending_bytes += n
        try:
            await self.bucket.acquire(n)
        finally:
            self.pending_bytes -= n
        self.consumed_bytes += n

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._shaper._unregister(self)


class SamplingTrafficShaper:
    def __init__(
        self,
        *,
        total_rate_bps: float = TOTAL_DOWNLOAD_RATE_BPS,
        per_flow_cap_bps: float = PER_FLOW_CAP_BPS,
        min_flow_rate_bps: float = 4 << 20,
        interval_s: float = 1.0,
    ):
        if total_rate_bps <= 0:
            raise ValueError("total_rate_bps must be > 0")
        self.total_rate_bps = float(total_rate_bps)
        self.per_flow_cap_bps = min(float(per_flow_cap_bps), self.total_rate_bps)
        self.min_flow_rate_bps = max(1.0, min(float(min_flow_rate_bps), self.per_flow_cap_bps))
        self.interval_s = float(interval_s)
        self._flows: dict[str, Flow] = {}
        self._last_sample = time.monotonic()
        self._last_needs: dict[str, float] = {}  # carried into out-of-band reallocs
        self.resamples = 0

    # ---- flow lifecycle ----

    def open_flow(self, flow_id: str, *, weight: float = 1.0) -> Flow:
        """Register a task download; triggers an immediate reallocation so
        the newcomer gets headroom without waiting a full interval. `weight`
        is the task's tenant priority (module docstring): contended
        bandwidth splits weight-proportionally."""
        bucket = TokenBucket(self.min_flow_rate_bps, burst=self.min_flow_rate_bps / 2)
        flow = Flow(self, flow_id, bucket, weight=weight)
        self._flows[flow_id] = flow
        # Out-of-band reallocation carries the LAST sampled needs: a task
        # arriving must not zero the established flows' weights and collapse
        # them to the floor for a whole interval (the newcomer weighs in at
        # max-need via the young-flow rule regardless).
        self._reallocate(self._last_needs)
        return flow

    def _unregister(self, flow: Flow) -> None:
        self._flows.pop(flow.flow_id, None)
        self._last_needs.pop(flow.flow_id, None)
        if self._flows:
            self._reallocate(self._last_needs)

    # ---- sampling + allocation ----

    def maybe_resample(self, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        elapsed = now - self._last_sample
        if elapsed < self.interval_s:
            return False
        needs = {}
        for fid, f in self._flows.items():
            need = f.window_bytes / elapsed
            if f.saturated:
                # Blocked in its bucket → wants more than granted, and
                # issuance only shows what the old allocation permitted.
                # Demand is taken as the per-flow cap: the starved flow
                # reaches any allocation in ONE resample, and (unlike a
                # rate-multiple ramp) the weighted split over cap-demands is
                # a stable fixed point at the configured weights.
                need = self.per_flow_cap_bps
            needs[fid] = need
        for f in self._flows.values():
            f.window_bytes = 0.0
            f.blocked_in_window = False
        self._last_sample = now
        self._last_needs = needs
        self._reallocate(needs, now=now)
        self.resamples += 1
        return True

    def _reallocate(self, needs: dict[str, float], now: float | None = None) -> None:
        flows = list(self._flows.values())
        if not flows:
            return
        now = time.monotonic() if now is None else now
        n = len(flows)
        floor = min(self.min_flow_rate_bps, self.total_rate_bps / n)
        spare = self.total_rate_bps - floor * n
        # Share weight = observed need (flows younger than a full interval
        # weigh in at the per-flow cap — no meaningful sample yet) scaled by
        # the flow's tenant priority: contended bandwidth converges to the
        # weight ratio because saturated flows all demand the same cap.
        weights = {}
        for f in flows:
            if now - f.created_at < self.interval_s:
                need = self.per_flow_cap_bps
            else:
                need = needs.get(f.flow_id, 0.0)
            weights[f.flow_id] = need * f.weight
        total_w = sum(weights.values())

        alloc = {f.flow_id: floor for f in flows}
        if spare > 0:
            if total_w <= 0:
                for f in flows:
                    alloc[f.flow_id] += spare / n
            else:
                # proportional split with cap redistribution: capped flows'
                # excess flows back to the uncapped ones (a few passes reach
                # the fixed point; n is small — concurrent tasks on one host)
                remaining = spare
                active = {f.flow_id: weights[f.flow_id] for f in flows}
                for _ in range(4):
                    w_sum = sum(active.values())
                    if remaining <= 1e-9 or w_sum <= 0:
                        break
                    overflow = 0.0
                    granted = remaining
                    remaining = 0.0
                    for fid in list(active):
                        share = granted * active[fid] / w_sum
                        new = alloc[fid] + share
                        if new > self.per_flow_cap_bps:
                            overflow += new - self.per_flow_cap_bps
                            alloc[fid] = self.per_flow_cap_bps
                            del active[fid]
                        else:
                            alloc[fid] = new
                    remaining = overflow
        for f in flows:
            rate = max(1.0, min(alloc[f.flow_id], self.per_flow_cap_bps))
            f.bucket.set_rate(rate, burst=max(rate / 2, 64 << 10))

    # ---- introspection ----

    def allocations(self) -> dict[str, float]:
        return {fid: f.bucket.rate for fid, f in self._flows.items()}

    def __len__(self) -> int:
        return len(self._flows)
