"""Daemon-side RTT prober feeding the scheduler's network topology.

Reference equivalent: the probe collection protocol the reference left
unfinished (SyncProbes stub, scheduler_server_v2.go:153-156; the daemon side
was never written). Each round: report last results via sync_probes, receive
the next target list, measure RTT to each target by timing a TCP connect to
its piece server (the reference planned ICMP ping; TCP connect needs no
privileges and measures the path peers actually use for transfers).
"""

from __future__ import annotations

import asyncio
import logging
import time

logger = logging.getLogger(__name__)

DEFAULT_PROBE_INTERVAL = 20 * 60.0  # ref networktopology probe interval
CONNECT_TIMEOUT = 3.0
SAMPLES_PER_TARGET = 3


async def measure_rtt_ms(ip: str, port: int, *, samples: int = SAMPLES_PER_TARGET) -> float | None:
    """Median TCP-connect time in ms, or None if unreachable."""
    times = []
    for _ in range(samples):
        t0 = time.perf_counter()
        try:
            _, writer = await asyncio.wait_for(
                asyncio.open_connection(ip, port), CONNECT_TIMEOUT
            )
        except (OSError, asyncio.TimeoutError):
            continue
        times.append((time.perf_counter() - t0) * 1000.0)
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, OSError):
            pass
    if not times:
        return None
    times.sort()
    return times[len(times) // 2]


class Prober:
    def __init__(
        self,
        scheduler,  # SchedulerClient with sync_probes
        host_id: str,
        *,
        interval: float = DEFAULT_PROBE_INTERVAL,
    ):
        self.scheduler = scheduler
        self.host_id = host_id
        self.interval = interval
        self.rounds = 0
        self._task: asyncio.Task | None = None
        self._pending: list[dict] = []  # results to report next round

    async def probe_once(self) -> int:
        """One sync round; returns number of successful measurements."""
        targets = await self.scheduler.sync_probes(self.host_id, self._pending)
        self._pending = []
        ok = 0
        for t in targets or []:
            rtt = await measure_rtt_ms(t["ip"], t["port"])
            if rtt is None:
                self._pending.append(
                    {"dst_host_id": t["host_id"], "rtt_ms": 0.0, "success": False}
                )
            else:
                self._pending.append(
                    {"dst_host_id": t["host_id"], "rtt_ms": rtt, "success": True}
                )
                ok += 1
        # report this round immediately so the topology is fresh even if the
        # process dies before the next tick
        if self._pending:
            await self.scheduler.sync_probes(self.host_id, self._pending)
            self._pending = []
        self.rounds += 1
        return ok

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def _run(self) -> None:
        while True:
            try:
                await self.probe_once()
            except Exception as e:
                logger.warning("probe round failed: %s", e)
            await asyncio.sleep(self.interval)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
