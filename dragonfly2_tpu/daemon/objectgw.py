"""Object-storage HTTP gateway on the peer daemon, backing dfstore.

Parity with reference client/daemon/objectstorage/objectstorage.go (gin
gateway: GetObject streams through the peer engine with the signed backend
URL as origin; PutObject writes to the backend and fans the content out via
the P2P seed path) — re-shaped on aiohttp with the pluggable
`objectstorage.backend` instead of S3-only.

Routes (dfstore's wire API):
  GET    /healthz
  GET    /buckets                                  list buckets
  PUT    /buckets/{bucket}                         create bucket
  DELETE /buckets/{bucket}                         delete bucket
  GET    /buckets/{b}/objects                      list objects (?prefix=)
  GET    /buckets/{b}/objects/{key:.+}             get (P2P by default, ?mode=direct to bypass)
  HEAD   /buckets/{b}/objects/{key:.+}             metadata
  PUT    /buckets/{b}/objects/{key:.+}             put (?seed=1 to pre-populate P2P cache)
  DELETE /buckets/{b}/objects/{key:.+}             delete
"""

from __future__ import annotations

import logging
from typing import Optional

from aiohttp import web

from dragonfly2_tpu.objectstorage import ObjectStorageBackend, ObjectStorageError

logger = logging.getLogger(__name__)

_STATUS = {"not_found": 404, "already_exists": 409, "invalid": 400, "internal": 500}


class ObjectGateway:
    def __init__(
        self,
        engine,
        backend: ObjectStorageBackend,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.engine = engine
        self.backend = backend
        self.host = host
        self.port = port
        self._runner: Optional[web.AppRunner] = None

    def app(self) -> web.Application:
        app = web.Application(client_max_size=1 << 30)
        r = app.router
        r.add_get("/healthz", self._healthz)
        r.add_get("/buckets", self._list_buckets)
        r.add_put("/buckets/{bucket}", self._create_bucket)
        r.add_delete("/buckets/{bucket}", self._delete_bucket)
        r.add_get("/buckets/{bucket}/objects", self._list_objects)
        r.add_get("/buckets/{bucket}/objects/{key:.+}", self._get_object, allow_head=False)
        r.add_head("/buckets/{bucket}/objects/{key:.+}", self._head_object)
        r.add_put("/buckets/{bucket}/objects/{key:.+}", self._put_object)
        r.add_delete("/buckets/{bucket}/objects/{key:.+}", self._delete_object)
        return app

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app(), access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        logger.info("object gateway on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        await self.backend.close()  # s3/oss/obs hold an aiohttp session

    # ---- handlers ----

    @staticmethod
    def _err(e: ObjectStorageError) -> web.Response:
        return web.json_response(
            {"error": str(e), "code": e.code}, status=_STATUS.get(e.code, 500)
        )

    async def _healthz(self, _req: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def _list_buckets(self, _req: web.Request) -> web.Response:
        buckets = await self.backend.list_buckets()
        return web.json_response(
            {"buckets": [{"name": b.name, "created_at": b.created_at} for b in buckets]}
        )

    async def _create_bucket(self, req: web.Request) -> web.Response:
        try:
            await self.backend.create_bucket(req.match_info["bucket"])
        except ObjectStorageError as e:
            return self._err(e)
        return web.json_response({"ok": True}, status=201)

    async def _delete_bucket(self, req: web.Request) -> web.Response:
        try:
            await self.backend.delete_bucket(req.match_info["bucket"])
        except ObjectStorageError as e:
            return self._err(e)
        return web.json_response({"ok": True})

    async def _list_objects(self, req: web.Request) -> web.Response:
        try:
            limit_s = req.query.get("limit", "")
            limit = max(1, int(limit_s)) if limit_s else None
        except ValueError:
            return web.json_response({"error": "limit must be an integer"}, status=400)
        try:
            objs = await self.backend.list_objects(
                req.match_info["bucket"], prefix=req.query.get("prefix", ""), limit=limit
            )
        except ObjectStorageError as e:
            return self._err(e)
        return web.json_response(
            {
                "objects": [
                    {
                        "key": o.key,
                        "content_length": o.content_length,
                        "digest": o.digest,
                        "etag": o.etag,
                    }
                    for o in objs
                ]
            }
        )

    async def _head_object(self, req: web.Request) -> web.Response:
        try:
            meta = await self.backend.stat_object(
                req.match_info["bucket"], req.match_info["key"]
            )
        except ObjectStorageError as e:
            return web.Response(status=_STATUS.get(e.code, 500))
        return web.Response(
            headers={
                "Content-Length": str(meta.content_length),
                "Content-Type": meta.content_type,
                "ETag": meta.etag,
                "X-Dragonfly-Digest": meta.digest,
            }
        )

    async def _stream_direct(
        self, req: web.Request, bucket: str, key: str, meta
    ) -> web.StreamResponse:
        """Stream straight from the backend — the direct mode and the
        p2p-failure fallback must not hold a multi-GB object in RAM. The
        first chunk is pulled BEFORE headers go out so backend errors still
        map to JSON error responses."""
        agen = self.backend.get_object_stream(bucket, key)
        try:
            try:
                first = await anext(agen, b"")
            except ObjectStorageError as e:
                return self._err(e)
            # chunked, no Content-Length: the length came from an earlier
            # stat and a concurrent overwrite would desynchronize the framing
            resp = web.StreamResponse(
                headers={"Content-Type": meta.content_type, "ETag": meta.etag}
            )
            resp.enable_chunked_encoding()
            await resp.prepare(req)
            if first:
                await resp.write(first)
            async for chunk in agen:
                await resp.write(chunk)
            await resp.write_eof()
            return resp
        finally:
            # early return, backend error, or client disconnect must not
            # leave the backend's HTTP response open until GC
            await agen.aclose()

    async def _get_object(self, req: web.Request) -> web.StreamResponse:
        bucket, key = req.match_info["bucket"], req.match_info["key"]
        try:
            meta = await self.backend.stat_object(bucket, key)
        except ObjectStorageError as e:
            return self._err(e)
        if req.query.get("mode") == "direct":
            return await self._stream_direct(req, bucket, key, meta)
        # P2P path: the backend's presigned URL is the back-to-source origin,
        # so every daemon in the cluster dedupes this object as one task
        # (ref objectstorage.go GetObject → StartStreamTask with signed URL)
        try:
            origin = self.backend.presign_get(bucket, key)
            length, body = await self.engine.stream_task(origin, digest=meta.digest)
        except Exception as e:
            logger.warning("p2p object get %s/%s failed (%s); direct read", bucket, key, e)
            return await self._stream_direct(req, bucket, key, meta)
        resp = web.StreamResponse(
            headers={
                "Content-Length": str(length),
                "Content-Type": meta.content_type,
                "ETag": meta.etag,
                "X-Dragonfly-Via": "p2p",
            }
        )
        await resp.prepare(req)
        async for chunk in body:
            await resp.write(chunk)
        await resp.write_eof()
        return resp

    async def _put_object(self, req: web.Request) -> web.Response:
        bucket, key = req.match_info["bucket"], req.match_info["key"]
        try:
            # stream the body: multi-GB artifacts never sit fully in RAM
            meta = await self.backend.put_object(
                bucket,
                key,
                req.content.iter_chunked(1 << 20),
                content_type=req.content_type or "application/octet-stream",
            )
        except ObjectStorageError as e:
            return self._err(e)
        seeded = False
        if req.query.get("seed") in ("1", "true"):
            # pre-populate the P2P cache so first readers hit peers, not the
            # backend (ref PutObject's seed fan-out)
            try:
                origin = self.backend.presign_get(bucket, key)
                await self.engine.download_task(origin, seed=True, digest=meta.digest)
                seeded = True
            except Exception:
                logger.exception("seeding object %s/%s failed", bucket, key)
        return web.json_response(
            {
                "key": key,
                "content_length": meta.content_length,
                "digest": meta.digest,
                "etag": meta.etag,
                "seeded": seeded,
            },
            status=201,
        )

    async def _delete_object(self, req: web.Request) -> web.Response:
        try:
            await self.backend.delete_object(req.match_info["bucket"], req.match_info["key"])
        except ObjectStorageError as e:
            return self._err(e)
        return web.json_response({"ok": True})
