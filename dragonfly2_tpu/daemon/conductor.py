"""Peer-task conductor: drives one task download end to end.

Parity with reference client/daemon/peer/peertask_conductor.go:68-1157 — the
survey's flagged hard part ("1,565 LoC of subtle concurrency: three bitmaps +
broker + dispatcher + per-parent sync streams + traffic shaper + back-source
cutover"). Redesigned as an explicit asyncio pipeline instead of goroutine
spaghetti:

  register → (back-to-source | P2P) → piece workers → storage → report → done

P2P mode: a score-based PieceDispatcher (ref piece_dispatcher.go:33-124,
ε-random exploration) assigns each missing piece to a parent that has it;
N workers pull assignments, HTTP-range the bytes from the parent's upload
server, verify, write, and report. Parent piece availability is pushed via
long-poll on the parents' /metadata endpoint (`?since=<version>&wait=` parks
until the parent's piece state advances — replacing the reference's bidi
SyncPieceTasks streams). Failures block the parent and trigger a scheduler
reschedule; after the retry budget the conductor cuts over to back-to-source
for the remaining pieces (ref partial back-source path).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Protocol

import aiohttp

from dragonfly2_tpu.daemon.rawrange import AddressFamilyError
from dragonfly2_tpu.daemon.source import SourceError, SourceRegistry
from dragonfly2_tpu.daemon.storage import StorageManager, TaskStorage
from dragonfly2_tpu.observability.tracing import default_tracer
from dragonfly2_tpu.resilience import deadline as dl
from dragonfly2_tpu.resilience import faultline
from dragonfly2_tpu.resilience.backoff import BackoffPolicy
from dragonfly2_tpu.rpc.core import RpcError
from dragonfly2_tpu.scheduler.service import HostInfo, ParentInfo, RegisterResult, TaskMeta
from dragonfly2_tpu.utils import digest as digestlib
from dragonfly2_tpu.utils.aio import gather_all_cancel_on_error
from dragonfly2_tpu.utils.bitset import Bitset
from dragonfly2_tpu.utils.pieces import Range, compute_piece_size, piece_count, piece_range
from dragonfly2_tpu.utils.ratelimit import TokenBucket

logger = logging.getLogger(__name__)


def _url_host(ip: str) -> str:
    """IPv6 literals must be bracketed in URLs (yarl rejects bare colons —
    an unbracketed v6 parent URL would fail as InvalidURL and charge the
    parent, defeating the raw-client's aiohttp fallback entirely)."""
    return f"[{ip}]" if ":" in ip else ip


class SchedulerClient(Protocol):
    """What the conductor needs from the control plane. Implemented in-process
    (wrapping SchedulerService) and over the wire (rpc client)."""

    async def register_peer(self, peer_id: str, meta: TaskMeta, host: HostInfo) -> RegisterResult: ...
    async def report_task_metadata(self, task_id: str, *, content_length: int,
                                   piece_size: int, digest: str = "",
                                   direct_piece: bytes = b"") -> None: ...
    async def report_piece_result(self, peer_id: str, piece_index: int, *, success: bool,
                                  cost_ms: float = 0.0, parent_id: str = "") -> None: ...
    async def report_pieces(self, peer_id: str, reports) -> int: ...
    async def report_peer_result(self, peer_id: str, *, success: bool,
                                 bandwidth_bps: float = 0.0) -> None: ...
    async def reschedule(self, peer_id: str) -> RegisterResult: ...
    async def leave_peer(self, peer_id: str) -> None: ...


class PieceReportBuffer:
    """Per-conductor buffer of SUCCESSFUL piece reports, flushed through the
    report_pieces batch RPC — the control-plane fast path that replaces one
    awaited report_piece_result round trip per piece in the piece-worker
    path. Failed pieces never enter the buffer: they drive rescheduling and
    are reported individually and promptly by the caller.

    Flush triggers: buffer reaches max_batch, buffered reports go
    flush_interval stale (bounds report staleness for long rounds), the
    conductor flushes at dispatch-round end, and close() flushes at task
    completion (before report_peer_result, so the scheduler's telemetry sees
    the full finished set).

    ONE long-lived flusher task per conductor serves the size and staleness
    triggers (PR 5 carry-over / ROADMAP): the earlier shape re-spawned a
    staleness-timer task per flush cycle and a detached task per size
    trigger — per-piece task churn on the hot path (the pattern dflint DF026
    now flags for threads/pools). add() is still synchronous: it appends,
    sets an event, and the flusher does the rest; `flusher_starts` counts
    task creations so tests can pin the no-churn contract.

    Exactly-once under rpc.write faults: flush() atomically takes the
    buffered triples and awaits ONE report_pieces call; the rpc client
    retries connection-level failures (an injected rpc.write fault raises
    before the frame leaves, so a retry cannot double-deliver — and a
    timeout AFTER a server-side apply re-applies as a no-op because the
    scheduler's apply is idempotent per piece index). If the call fails past
    the client's retry budget the triples are merged back for the next flush
    — piece accounting is never dropped, matching the at-least-once goal the
    chaos suite pins."""

    def __init__(self, scheduler, peer_id: str, *, max_batch: int = 64,
                 flush_interval: float = 0.25, log=None):
        self._sched = scheduler
        self.peer_id = peer_id
        self.max_batch = max_batch
        self.flush_interval = flush_interval
        self.log = log or logger
        self._buf: list[tuple[int, float, str]] = []
        self._lock = asyncio.Lock()  # serializes flushes (ordering + no double-take)
        self._flusher: asyncio.Task | None = None
        # events are created in __init__ (lazily loop-bound on 3.10), set by
        # add(): _wake = "buffer went non-empty", _full = "size trigger hit"
        self._wake = asyncio.Event()
        self._full = asyncio.Event()
        self.rpcs = 0  # report_pieces calls that completed (bench/test counter)
        self.buffered = 0  # pieces that rode a batch instead of a unary RPC
        self.flusher_starts = 0  # long-lived task creations (leak canary: stays 1)

    def add(self, piece_index: int, cost_ms: float = 0.0, parent_id: str = "") -> None:
        """Enqueue one successful piece report. Sync — the piece worker goes
        straight back to its queue; no RPC await, no task spawned, on the
        piece path."""
        self._buf.append((piece_index, cost_ms, parent_id))  # dflint: disable=DF023 loop-thread append, no await around it; the lock serializes FLUSHES, not enqueues
        self.buffered += 1
        if len(self._buf) >= self.max_batch:
            self._full.set()
        if self._flusher is None or self._flusher.done():
            # lazy start (add is the first point with a running loop); a
            # flusher that DIED (cancelled mid-close, crashed) is restarted
            # so a reused buffer never silently stops flushing
            self.flusher_starts += 1
            self._flusher = asyncio.ensure_future(self._flusher_loop())
        else:
            self._wake.set()

    async def _flusher_loop(self) -> None:
        """The single long-lived flusher: parks while the buffer is empty,
        then flushes when the buffer fills (size trigger) or flush_interval
        after it went non-empty (staleness trigger) — the same externally
        observable schedule the per-flush timer tasks produced, without
        creating a task per cycle."""
        while True:
            if not self._buf:
                await self._wake.wait()
                self._wake.clear()
                if not self._buf:  # spurious wake (a direct flush drained us)
                    continue
            if len(self._buf) < self.max_batch:
                try:
                    await asyncio.wait_for(self._full.wait(), self.flush_interval)
                except asyncio.TimeoutError:
                    pass  # staleness trigger: flush whatever is buffered
            self._full.clear()  # dflint: disable=DF023 loop-thread event signaling; the lock serializes FLUSHES — flush()'s own clear just runs inside its locked drain
            await self.flush()
            if self._buf:
                # flush failed past the rpc client's retries and re-merged:
                # PACE the retry. A re-merged buffer >= max_batch would skip
                # the staleness wait above and hammer a dead scheduler in a
                # tight loop (fast-failing RPCs make it a busy spin).
                await asyncio.sleep(self.flush_interval)

    async def flush(self) -> None:
        """Drain the buffer in one report_pieces RPC (or a few, if adds land
        while a flush is in flight). Never raises on RPC failure: a flush
        that fails past the rpc client's retries re-merges its batch and
        leaves recovery to the next trigger. Cancellation (aclose cancelling
        the staleness timer mid-flush) also re-merges before propagating —
        the taken batch must never ride out of scope with the exception, or
        the close flush would snapshot an incomplete finished set."""
        async with self._lock:
            while self._buf:
                batch, self._buf = self._buf, []
                try:
                    # flush span: how often the buffer ships and how full it
                    # is are exactly the control-plane amortization questions
                    # a trace should answer (≤1 flush per dispatch round)
                    with default_tracer().span(
                        "conductor.report_flush", batch=len(batch)
                    ):
                        await self._sched.report_pieces(self.peer_id, batch)  # dflint: disable=DF025 this IS the batch flush; the loop only drains reports that arrived during the awaited call
                    self.rpcs += 1
                except Exception as e:  # noqa: BLE001 — advisory accounting:
                    # keep the pieces for the next flush trigger; the download
                    # itself must never fail on a report (same contract as the
                    # unbatched path's debug-logged best-effort reports)
                    self._buf = batch + self._buf
                    self.log.debug("piece-report flush of %d failed: %r", len(batch), e)
                    return
                except BaseException:
                    # CancelledError is a BaseException since 3.8: without this
                    # re-merge a timer task cancelled at the awaited RPC would
                    # lose its taken batch silently (a server-side apply that
                    # already landed re-applies as a no-op — idempotent).
                    self._buf = batch + self._buf
                    raise
            # Drained: a size-trigger signal set by adds this flush consumed
            # is now stale — left set, the flusher's next cycle would skip
            # the staleness wait and ship a tiny batch (a direct round-end
            # flush racing the size trigger reintroduced near-unary RPCs).
            # The failure paths above return/raise with the buffer non-empty
            # and deliberately leave the signal armed for a prompt retry.
            self._full.clear()

    async def aclose(self) -> None:
        """Task-completion flush; stops the long-lived flusher.

        Unlike mid-round flushes (which can leave failures to the next
        trigger), this is the LAST trigger: a flush that fails past the rpc
        client's retries gets a few more backed-off attempts here, because
        dropping the residue would lose piece accounting at exactly the
        moment report_peer_result snapshots the finished set into telemetry
        (the chaos suite pins no-loss under rpc.write faults)."""
        if self._flusher is not None:
            self._flusher.cancel()
            # await the cancellation: a flusher parked inside flush()'s RPC
            # holds the flush lock and must finish its BaseException re-merge
            # before the close flush below can take the (complete) buffer
            await asyncio.gather(self._flusher, return_exceptions=True)
            self._flusher = None
        backoff = BackoffPolicy(base=0.05, max_delay=1.0)
        for attempt in range(4):
            if attempt:
                await backoff.sleep(attempt - 1)
            await self.flush()
            if not self._buf:
                return
        self.log.warning(
            "dropping %d unreported piece results at task close", len(self._buf)
        )

    async def close_with_result(self, *, success: bool,
                                bandwidth_bps: float = 0.0) -> bool:
        """Task-completion close that rides the residual piece batch AND the
        final peer result in ONE report_batch RPC (one frame, one scheduler
        lock pass) instead of aclose()'s flush followed by a separate unary
        report_peer_result. Returns True when the result landed; False when
        the transport has no report_batch (older scheduler: unimplemented
        over the wire, or a client predating the method) — the caller then
        falls back to aclose() + unary report_peer_result, which this method
        has already half-done by flushing what it could.

        Retry safety matches the unary pair it replaces: both legs are
        idempotent server-side (piece dedupe + terminal-FSM result skip), so
        the rpc client's retries and the backed-off attempts here cannot
        double-account."""
        fn = getattr(self._sched, "report_batch", None)
        if fn is None:
            await self.aclose()
            return False
        if self._flusher is not None:
            self._flusher.cancel()
            await asyncio.gather(self._flusher, return_exceptions=True)
            self._flusher = None
        result = {"success": success, "bandwidth_bps": bandwidth_bps}
        backoff = BackoffPolicy(base=0.05, max_delay=1.0)
        for attempt in range(4):
            if attempt:
                await backoff.sleep(attempt - 1)
            async with self._lock:
                batch, self._buf = self._buf, []
                try:
                    with default_tracer().span(
                        "conductor.report_close", batch=len(batch)
                    ):
                        await fn(self.peer_id, batch, result)
                    self.rpcs += 1
                    return True
                except RpcError as e:
                    self._buf = batch + self._buf
                    if e.code == "unimplemented":
                        break  # rolling upgrade: scheduler predates the method
                    self.log.debug(
                        "batched close of %d failed: %r", len(batch), e
                    )
                except Exception as e:  # noqa: BLE001 — same advisory
                    # contract as flush(): the download never fails on a report
                    self._buf = batch + self._buf
                    self.log.debug(
                        "batched close of %d failed: %r", len(batch), e
                    )
                except BaseException:
                    self._buf = batch + self._buf
                    raise
        # could not land the combo: drain pieces the plain way and tell the
        # caller to send the unary result itself
        await self.aclose()
        return False


@dataclass
class ParentState:
    info: ParentInfo
    pieces: set[int] = field(default_factory=set)
    successes: int = 0
    failures: int = 0
    cost_ewma_ms: float = 0.0
    blocked: bool = False
    # fetches currently riding this parent (striped mode's per-parent
    # window); maintained by PieceDispatcher.begin/end around each fetch
    in_flight: int = 0

    def score(self) -> float:
        """Higher is better: success rate shaded by recent piece cost."""
        total = self.successes + self.failures
        rate = (self.successes + 1) / (total + 2)  # Laplace prior
        cost_penalty = self.cost_ewma_ms / 10_000.0
        return rate - cost_penalty

    def record(self, success: bool, cost_ms: float) -> None:
        if success:
            self.successes += 1
            alpha = 0.3
            self.cost_ewma_ms = (
                cost_ms if self.cost_ewma_ms == 0 else alpha * cost_ms + (1 - alpha) * self.cost_ewma_ms
            )
        else:
            self.failures += 1
            if self.failures >= 3:
                self.blocked = True


class PieceDispatcher:
    """Pick the parent for each piece: best score with ε-random exploration
    (ref piece_dispatcher.go:103-124 exploration/exploitation split).

    Striped mode (`pick(..., striped=True)`) turns the pick into a
    load-balancing decision: among the parents that hold the piece, prefer
    the one with the fewest fetches in flight (score breaks ties), and keep
    each parent's concurrent fetches under `stripe_window`. Assignment
    happens at FETCH time, so the stripes are emergent, not precomputed — a
    slow parent's window stays full longer and it naturally receives fewer
    pieces, which is exactly the tail-aware split the GNN-training paper
    applies to its straggler stage (PAPERS.md: parallelize the slowest
    stage, not just the aggregate). When every window is full the pick
    falls back to least-loaded (never returns None just because the task is
    briefly window-bound — the piece queue provides the real backpressure).
    """

    def __init__(
        self,
        epsilon: float = 0.1,
        rng: random.Random | None = None,
        *,
        stripe_window: int = 4,
    ):
        self.parents: dict[str, ParentState] = {}
        self.epsilon = epsilon
        self.stripe_window = stripe_window
        self._rng = rng or random.Random()

    def update_parents(self, parents: list[ParentInfo]) -> None:
        keep = {p.peer_id for p in parents}
        for pid in list(self.parents):
            if pid not in keep:
                del self.parents[pid]
        for p in parents:
            if p.peer_id not in self.parents:
                self.parents[p.peer_id] = ParentState(p)

    def set_pieces(self, parent_id: str, pieces: set[int]) -> None:
        if parent_id in self.parents:
            self.parents[parent_id].pieces = pieces

    def pick(
        self,
        piece_index: int,
        *,
        striped: bool = False,
        exclude: "frozenset[str] | set[str] | tuple" = (),
    ) -> ParentState | None:
        candidates = [
            s for s in self.parents.values()
            if not s.blocked and piece_index in s.pieces and s.info.peer_id not in exclude
        ]
        if not candidates:
            return None
        if self._rng.random() < self.epsilon:
            return self._rng.choice(candidates)
        if not striped or len(candidates) == 1:
            return max(candidates, key=ParentState.score)
        windowed = [s for s in candidates if s.in_flight < self.stripe_window]
        pool = windowed or candidates
        return min(pool, key=lambda s: (s.in_flight, -s.score()))

    def begin(self, state: ParentState) -> None:
        state.in_flight += 1

    def end(self, state: ParentState) -> None:
        state.in_flight = max(0, state.in_flight - 1)

    def usable(self) -> list[ParentState]:
        return [s for s in self.parents.values() if not s.blocked]


@dataclass
class ConductorConfig:
    piece_workers: int = 4
    # ranged back-to-source pulls this many pieces concurrently (the
    # reference's ConcurrentOption multi-connection source download,
    # piece_manager.go:67); 1 = sequential
    source_concurrency: int = 4
    download_rate_bps: float = 512 << 20  # per-peer default (ref constants.go:45)
    piece_timeout: float = 30.0
    # Fallback re-check cadence when no push event arrives; piece announcements
    # themselves are pushed via parent long-poll, not polled on this interval.
    metadata_poll_interval: float = 0.2
    longpoll_wait: float = 25.0
    # How long to keep riding live parents' push channels with nothing to do
    # before asking the scheduler for new parents.
    no_progress_reschedule: float = 5.0
    reschedule_limit: int = 5
    watchdog_timeout: float = 600.0
    # Retry pacing for piece-level recovery (shared BackoffPolicy shape).
    retry_backoff_base: float = 0.1
    retry_backoff_max: float = 2.0
    # A piece whose worker raised past _download_one_piece is re-enqueued at
    # most this many times before it is reported failed to the scheduler and
    # left to the dispatch loop (and ultimately cutover) to recover.
    piece_requeue_limit: int = 2
    # Ranged back-to-source: per-piece fetch retries before the whole task
    # fails (origin blips must not kill a 95%-done download).
    source_piece_retries: int = 3
    # Successful piece reports batch through the report_pieces RPC (one
    # flush per dispatch round / flush interval instead of one awaited
    # round trip per piece); failed pieces always report individually and
    # immediately (they drive rescheduling). Disable to get the r05 unary
    # path (the chaos suite's equivalence baseline).
    batch_piece_reports: bool = True
    report_batch_size: int = 64
    report_flush_interval: float = 0.25
    # Hand filled piece buffers to writer tasks WITHOUT awaiting them, so one
    # worker pipelines recv of piece N+1 into the store write of piece N.
    # On the 2-core CI image the piece-worker pool already overlaps
    # recv/hash/write across workers on both cores and the extra in-flight
    # write tasks measured ~10% SLOWER (343 vs 311 MB/s in the 4-worker
    # pipeline A/B); on hosts with cores to spare the deferral buys
    # single-worker pipelining. That inversion is why the default is now
    # None = ADAPTIVE: the first dispatch round runs inline while measuring
    # its recv/write stage totals, and WriteBehindGovernor flips deferral on
    # only where the measurement says it pays (spare cores + writes a real
    # fraction of the round). True/False force the static modes (the A/B
    # legs and the chaos equivalence baseline). Backpressure either way: the
    # buffer pool's bounded leases park recv when writers fall behind.
    defer_piece_writes: "bool | None" = None
    # Multi-parent striped fetch: when a hot task has several ready parents,
    # balance piece assignment across them (per-parent in-flight windows)
    # instead of funneling ~everything to the single best-scored parent, so
    # single-task fetch bandwidth aggregates across parents' per-peer
    # serving ceilings. Scheduler accounting is unchanged — every piece
    # still reports with its parent id.
    striped_fetch: bool = True
    stripe_window: int = 4
    # Slowest-stripe steal: when the piece queue is empty but pieces are
    # still in flight (the tail), an idle worker re-fetches a piece that has
    # been riding a slow parent for > max(steal_min_ms, steal_cost_factor *
    # that parent's cost EWMA) from a different parent, and the first copy
    # to land wins (the loser's fetch is cancelled; landing + accounting are
    # guarded so bytes/pieces never double-count).
    tail_steal: bool = True
    steal_min_ms: float = 400.0
    steal_cost_factor: float = 3.0


class WriteBehindGovernor:
    """Runtime write-behind decision (ConductorConfig.defer_piece_writes=None).

    PR 3 measured the static trade-off inverting with core count, so the
    default can't be a constant. The first dispatch round runs INLINE while
    `note()` accumulates the round's recv and write stage totals (two clock
    reads per piece, only while measuring); `decide()` then flips deferral
    on iff (a) there are cores beyond the two the recv+hash overlap already
    uses, and (b) writes are a real fraction of the measured round — on a
    2-core host, or when writes vanish into page cache, deferral only adds
    task churn. The decision and both measurements export as metrics
    (`write_behind_mode{mode}` one-hot, `write_behind_stage_ms{stage}`), so
    the PR 12 timeseries plane records what was decided and from what.
    """

    # writes below this fraction of recv+write don't buy enough overlap to
    # pay for per-piece writer tasks
    MIN_WRITE_FRAC = 0.10
    MIN_SAMPLES = 2

    def __init__(self, forced: "bool | None", *, cpu_count: int | None = None):
        import os

        self.forced = forced
        self.cpus = cpu_count if cpu_count is not None else (os.cpu_count() or 1)
        self.recv_s = 0.0
        self.write_s = 0.0
        self.samples = 0
        self.decided: bool | None = forced
        if forced is not None:
            self._export("forced_deferred" if forced else "forced_inline")

    @property
    def measuring(self) -> bool:
        return self.decided is None

    @property
    def defer(self) -> bool:
        return bool(self.decided)

    def note(self, recv_s: float, write_s: float) -> None:
        if self.decided is None:
            self.recv_s += recv_s
            self.write_s += write_s
            self.samples += 1

    def decide(self) -> bool:
        """Called at first-round end; keeps measuring if the round was too
        small to mean anything (a 1-piece task decides nothing)."""
        if self.decided is not None:
            return self.decided
        if self.samples < self.MIN_SAMPLES:
            return False  # stay inline, keep measuring next round
        total = self.recv_s + self.write_s
        write_frac = self.write_s / total if total > 0 else 0.0
        self.decided = self.cpus > 2 and write_frac >= self.MIN_WRITE_FRAC
        self._export("deferred" if self.decided else "inline")
        return self.decided

    def _export(self, mode: str) -> None:
        from dragonfly2_tpu.daemon import metrics

        for m in ("inline", "deferred", "forced_inline", "forced_deferred"):
            metrics.WRITE_BEHIND_MODE.set(1.0 if m == mode else 0.0, mode=m)
        metrics.WRITE_BEHIND_STAGE_MS.set(round(self.recv_s * 1e3, 3), stage="recv")
        metrics.WRITE_BEHIND_STAGE_MS.set(round(self.write_s * 1e3, 3), stage="write")

    def snapshot(self) -> dict:
        return {
            "mode": (
                "measuring" if self.decided is None
                else {True: "deferred", False: "inline"}[self.decided]
            ),
            "forced": self.forced,
            "recv_ms": round(self.recv_s * 1e3, 3),
            "write_ms": round(self.write_s * 1e3, 3),
            "samples": self.samples,
        }


@dataclass
class _InflightFetch:
    """One piece fetch in flight (striped mode): enough state for the tail
    steal to judge slowness and cancel the loser."""

    idx: int
    task: "asyncio.Task | None"  # set right after creation (fetch needs the entry)
    started: float
    parent_id: str = ""
    stolen: bool = False
    steal_attempts: int = 0  # bounded: a failing steal must not retry forever


class PeerTaskConductor:
    def __init__(
        self,
        *,
        peer_id: str,
        meta: TaskMeta,
        host: HostInfo,
        scheduler: SchedulerClient,
        storage: StorageManager,
        sources: SourceRegistry,
        config: ConductorConfig | None = None,
        http_session: aiohttp.ClientSession | None = None,
        headers: dict[str, str] | None = None,
        shaper=None,
        raw_client=None,
        pipeline=None,
        data_tls=None,
        flow_weight: float = 1.0,
    ):
        from dragonfly2_tpu.utils.dflog import with_context

        self.peer_id = peer_id
        self.meta = meta
        self.host = host
        self.scheduler = scheduler
        # every line this conductor logs carries its task+peer ids
        # (ref dflog WithPeer/WithTask structured context)
        self.log = with_context(logger, task_id=meta.task_id, peer_id=peer_id)
        self.storage = storage
        self.sources = sources
        self.headers = headers or None  # origin request headers (auth etc.)
        self.cfg = config or ConductorConfig()
        self.dispatcher = PieceDispatcher(stripe_window=self.cfg.stripe_window)
        # DataPlaneTls bundle: parents' metadata + piece endpoints speak
        # https/mTLS (the shared raw client carries its own copy; this one
        # drives the aiohttp session + URL scheme)
        self._data_tls = data_tls
        self._scheme = "https" if data_tls is not None else "http"
        # With a node-wide shaper (daemon/traffic_shaper.py) the conductor
        # draws from a dynamically-allocated slice of the HOST budget; the
        # standalone per-task bucket is the no-engine fallback (tests, direct
        # conductor use). flow_weight is the task's tenant priority: the
        # shaper splits contended bandwidth weight-proportionally.
        if shaper is not None:
            self.bucket = shaper.open_flow(peer_id, weight=flow_weight)
        else:
            self.bucket = TokenBucket(self.cfg.download_rate_bps, burst=64 << 20)
        self._session = http_session
        self._owns_session = http_session is None
        # engine-shared RawRangeClient when provided (keep-alive conns to
        # parents survive across this host's tasks); else lazily owned
        self._raw_client = raw_client
        self._owns_raw = raw_client is None
        # engine-shared PiecePipeline (pooled buffers + hash threads reused
        # across every transfer on the host); else lazily owned
        self._pipeline_obj = pipeline
        self._owns_pipeline = pipeline is None
        # deferred store writes: a piece worker hands its filled buffer to a
        # writer task and immediately recycles a fresh buffer into recv; the
        # dispatch loop drains these at round end (see _spawn_piece_write)
        self._pending_writes: set[asyncio.Task] = set()
        # adaptive write-behind: measures the first dispatch round, then
        # decides (ConductorConfig.defer_piece_writes documents the why)
        self._write_behind = WriteBehindGovernor(self.cfg.defer_piece_writes)
        # striped-fetch state: fetches in flight (tail-steal registry) and
        # which parents actually landed pieces (stripe-parents histogram +
        # the stripe smoke's both-parents-served proof)
        self._inflight: dict[int, _InflightFetch] = {}
        self.pieces_by_parent: dict[str, int] = {}
        self.steals_attempted = 0
        self.steals_won = 0
        # pieces this conductor has ACCOUNTED (bytes/metrics/report): the
        # exactly-once guard for duplicate landings. storage._land_piece
        # dedups the WRITE of racing copies but returns success to both
        # writers — without this set, a steal and its original racing into
        # the landing path would both reach _account_piece_success and
        # double-count DOWNLOAD_TRAFFIC_BYTES (the invariant the chaos
        # suite and stripe smoke pin).
        self._accounted: set[int] = set()
        self.ts: TaskStorage | None = None
        self.bytes_from_parents = 0
        self.bytes_from_source = 0
        # refetch accounting for crash-safe resume: pieces already on disk
        # when this conductor started (recovered from a previous run) vs
        # pieces it NEWLY LANDED — the restart suite pins
        # preexisting + fetched == total (recovered pieces never ride again
        # on ranged/p2p paths; a close-delimited full-body fallback re-carries
        # their bytes — visible in bytes_from_source — but still never
        # re-lands or re-reports them)
        self.pieces_preexisting = 0
        self.pieces_fetched = 0
        self._piece_digests: dict[str, str] = {}  # learned from parent metadata
        # Whether the final full-content re-hash can be skipped: true only if
        # EVERY byte of the task was landed by THIS conductor with each piece
        # validated against an expected digest at write time, and every such
        # digest came from a parent that had itself completed (and therefore
        # verified) the task — a mid-download parent's digests are self-
        # computed from bytes IT has not verified yet (see _run_inner).
        self._pieces_unverified = 0
        self._digests_from_done_parents = True
        self._had_preexisting_pieces = False
        self._peer_reported = False
        self._t0 = 0.0
        self._sync_tasks: dict[str, asyncio.Task] = {}  # parent_id -> long-poll loop
        self._update_event = asyncio.Event()  # any parent state/metadata change
        self._backoff = BackoffPolicy(
            base=self.cfg.retry_backoff_base,
            multiplier=2.0,
            max_delay=self.cfg.retry_backoff_max,
            jitter=0.5,
        )
        self._piece_errors: dict[int, int] = {}  # index -> worker-level failures
        # cluster retry budgets (ISSUE 17): process-wide token buckets, one
        # per target class. First attempts are free; RETRIES spend — beyond
        # the budget the conductor fails fast to its next fallback (another
        # parent, back-to-source) instead of amplifying a cluster-wide storm.
        from dragonfly2_tpu.resilience.budget import budget_for

        self._sched_budget = budget_for("scheduler")
        self._parent_budget = budget_for("parent")
        # Successful piece reports ride a per-conductor batch buffer when the
        # client speaks report_pieces (all shipped clients do; test fakes may
        # not — they get the unary path).
        self._reports: PieceReportBuffer | None = None
        if self.cfg.batch_piece_reports and hasattr(scheduler, "report_pieces"):
            self._reports = PieceReportBuffer(
                scheduler, peer_id,
                max_batch=self.cfg.report_batch_size,
                flush_interval=self.cfg.report_flush_interval,
                log=self.log,
            )

    # ---- entry ----

    async def run(self) -> TaskStorage:
        """Download the task fully; returns its storage. Raises on failure.

        The watchdog is a deadline scope, not just a wait_for: nested rpc
        calls and piece fetches see min(remaining, per-op) timeouts through
        the propagated budget, and an engine-level scope (user --timeout)
        narrows it further."""
        self._t0 = time.monotonic()
        try:
            with dl.scope(self.cfg.watchdog_timeout) as budget:
                result = await asyncio.wait_for(self._run_inner(), budget.remaining())
            return result
        except BaseException:
            await self._safe_report_peer(success=False)
            raise
        finally:
            if self.ts is not None:
                self.ts.unpin()  # storage reclaim may evict us again
            close = getattr(self.bucket, "close", None)
            if close is not None:
                close()  # release this task's slice of the host budget
            if self._owns_session and self._session is not None:
                await self._session.close()
            if self._owns_raw and self._raw_client is not None:
                await self._raw_client.close()
            if self._owns_pipeline and self._pipeline_obj is not None:
                self._pipeline_obj.close()

    async def _run_inner(self) -> TaskStorage:
        reg = await self._register_admitted()
        if getattr(reg, "error", ""):
            raise IOError(f"task {self.meta.task_id}: registration refused: {reg.error}")
        self.ts = self.storage.register_task(
            self.meta.task_id,
            url=self.meta.url,
            digest=self.meta.digest,
            tag=self.meta.tag,
            application=self.meta.application,
        )
        self.ts.pin()  # immune to storage reclaim while this download runs
        self.pieces_preexisting = self.ts.finished_count()
        self._had_preexisting_pieces = self.pieces_preexisting > 0

        if reg.scope == "empty":
            self.ts.set_task_info(content_length=0, piece_size=1, total_pieces=0)
            self.ts.mark_done()
            await self._safe_report_peer(success=True)
            return self.ts
        if reg.scope == "tiny" and reg.direct_piece:
            await self._finish_tiny(reg.direct_piece)
            return self.ts
        if reg.back_to_source:
            await self._download_back_to_source()
        else:
            self._apply_task_info(reg)
            await self._download_p2p(reg.parents)

        # The full-content re-hash is redundant when every piece this
        # conductor landed was already validated against an expected digest
        # from the piece-metadata channel — the same per-piece trust chain the
        # reference's piece MD5 check uses (piece_manager.go processPieceFromSource
        # digest verification). Skipping it saves one full read+hash pass per
        # task — seconds per checkpoint shard on the fan-out path. It still
        # runs when any piece lacked a digest (back-to-source computes its
        # own) or when pieces predate this conductor (unknown provenance).
        every_piece_validated = (
            not self._had_preexisting_pieces
            and self._pieces_unverified == 0
            and self._digests_from_done_parents
            and self.ts.meta.total_pieces > 0
        )
        if not every_piece_validated:
            # verify() hashes the whole file — off the event loop, or a 100
            # MiB task would freeze every concurrent transfer for the pass
            if not await asyncio.to_thread(self.ts.verify):
                await self._safe_report_peer(success=False)
                raise digestlib.InvalidDigestError(
                    f"task {self.meta.task_id}: content digest mismatch"
                )
        self.ts.mark_done()
        await self._safe_report_peer(success=True)
        return self.ts

    async def _register_admitted(self) -> RegisterResult:
        """register_peer honoring the scheduler's typed `overloaded` answer
        (ISSUE 17 admission-control rung): the refusal carries a
        retry_after_s hint — pre-charge the scheduler retry budget, wait it
        out (jittered, bounded by the task budget), and re-register instead
        of failing the task. Any other refusal surfaces unchanged."""
        reg = await self.scheduler.register_peer(self.peer_id, self.meta, self.host)
        for attempt in range(1, 4):
            if getattr(reg, "error", "") != "overloaded":
                return reg
            retry_after = float(getattr(reg, "retry_after_s", 0.0)) or 1.0
            self._sched_budget.charge(retry_after)
            remaining = dl.remaining()
            if remaining is not None and remaining <= retry_after:
                return reg  # the wait would outlive the task budget
            # jitter UP only: arriving before retry_after would re-hit the
            # admission gate; spreading later de-synchronizes the shed crowd
            delay = retry_after * (1.0 + 0.5 * random.random())
            self.log.info(
                "scheduler overloaded; re-registering in %.1fs (attempt %d)",
                delay, attempt,
            )
            await asyncio.sleep(delay)
            reg = await self.scheduler.register_peer(self.peer_id, self.meta, self.host)  # dflint: disable=DF025 bounded 3-attempt admission handshake paced by the server's retry_after hint — one peer re-registering, not per-item fan-out
        return reg

    def _apply_task_info(self, reg: RegisterResult) -> None:
        if reg.content_length is not None and self.ts.meta.content_length < 0:
            self.ts.set_task_info(
                content_length=reg.content_length,
                piece_size=reg.piece_size,
                total_pieces=reg.total_pieces,
                digest=reg.digest or self.meta.digest,
            )

    async def _finish_tiny(self, data: bytes) -> None:
        self.ts.set_task_info(
            content_length=len(data), piece_size=max(1, len(data)), total_pieces=1
        )
        if not self.ts.has_piece(0):
            await self.ts.write_piece(0, data)
            self.pieces_fetched += 1
        self.ts.mark_done()
        await self._safe_report_peer(success=True)

    # ---- back-to-source (ref pieceManager.DownloadSource) ----

    async def _download_back_to_source(self) -> None:
        # source bytes carry no expected piece digests (we compute them as we
        # write) — the end-of-task full verify must run when a digest is known
        self._pieces_unverified += 1
        url = self.meta.url
        info = await self.sources.info(url, self.headers)
        if self.ts.meta.content_length < 0:
            if info.content_length < 0:
                await self._download_source_unknown_length(info)
                return
            psize = compute_piece_size(info.content_length)
            self.ts.set_task_info(
                content_length=info.content_length,
                piece_size=psize,
                total_pieces=piece_count(info.content_length, psize),
                digest=self.meta.digest,
            )
            await self.scheduler.report_task_metadata(
                self.meta.task_id,
                content_length=info.content_length,
                piece_size=psize,
                digest=self.meta.digest,
            )
        m = self.ts.meta
        if m.content_length == 0:
            self.ts.mark_done()
            return
        if info.supports_range:
            await self._download_source_ranged()
        else:
            await self._download_source_sequential()
        if m.content_length <= 128:
            data = await self.ts.read_range(Range(0, m.content_length))
            await self.scheduler.report_task_metadata(
                self.meta.task_id,
                content_length=m.content_length,
                piece_size=m.piece_size,
                direct_piece=data,
            )

    async def _download_source_ranged(self) -> None:
        """Pull missing pieces via CONCURRENT Range requests (the reference's
        multi-connection source download, piece_manager.go:67 ConcurrentOption):
        pieces write at disjoint offsets, so N in-flight ranges parallelize
        the origin link the way p2p piece workers parallelize parents. Each
        piece retries independently (shared backoff policy); a piece that
        exhausts its retries fails the task, cancelling its siblings."""
        m = self.ts.meta
        sem = asyncio.Semaphore(max(1, self.cfg.source_concurrency))

        async def fetch_once(idx: int) -> None:
            from dragonfly2_tpu.daemon import metrics

            if self.ts.has_piece(idx):
                return  # idempotent under retry: the piece already landed
            r = piece_range(idx, m.piece_size, m.content_length)
            t0 = time.monotonic()
            # pooled buffer + hash-on-receive: chunks land straight in a
            # reused buffer (no bytearray growth reallocs, no final bytes()
            # copy) and the piece digest is computed as they arrive instead
            # of in write_piece's second pass
            pipeline = self._pipeline()
            pooled = await pipeline.pool.acquire(r.length)
            # origin pieces join the trace too: the cutover path must be
            # attributable in the same timeline as parent fetches
            with default_tracer().span(
                "conductor.piece", piece=idx, bytes=r.length, path="origin"
            ):
                try:
                    pump = pipeline.hash_pump(pooled.view)
                    try:
                        off = 0
                        async for chunk in self.sources.download(self.meta.url, r, self.headers):
                            if off + len(chunk) > r.length:
                                raise IOError(
                                    f"source piece {idx}: got more than {r.length} bytes"
                                )
                            pooled.view[off : off + len(chunk)] = chunk
                            off += len(chunk)
                            pump.feed(off)
                            await self.bucket.acquire(len(chunk))
                        if off != r.length:
                            raise IOError(f"source piece {idx}: got {off}, want {r.length}")
                        d = await pump.finish()
                    except BaseException:
                        pump.abort()
                        raise
                    await self.ts.write_piece_view(idx, pooled.view, digest=d)
                finally:
                    pooled.release()
            self.bytes_from_source += r.length
            # same accounting as the sequential path (_write_source_piece):
            # cutover dashboards need parent vs back_to_source piece counts
            # to sum to the task's total
            metrics.PIECE_DOWNLOAD_TOTAL.inc(source="back_to_source")
            metrics.DOWNLOAD_BYTES.inc(r.length)
            await self._report_piece_success(idx, (time.monotonic() - t0) * 1000)

        async def fetch(idx: int) -> None:
            # Pieces retry independently with exponential backoff: an origin
            # blip (reset, truncated body, 5xx) must cost one piece a retry,
            # not the whole TaskGroup a cancellation cascade.
            async with sem:
                last: Exception | None = None
                for attempt in range(self.cfg.source_piece_retries + 1):
                    try:
                        await fetch_once(idx)
                        return
                    except (SourceError, IOError, aiohttp.ClientError, asyncio.TimeoutError) as e:
                        last = e
                        remaining = dl.remaining()
                        if remaining is not None and remaining <= 0:
                            break  # budget gone: fail now, the watchdog is racing us
                        if attempt < self.cfg.source_piece_retries:
                            self.log.debug(
                                "source piece %d attempt %d failed: %r", idx, attempt, e
                            )
                            await self._backoff.sleep(attempt)
                raise last if last is not None else IOError(f"source piece {idx} failed")

        await gather_all_cancel_on_error(
            fetch(idx) for idx in self.ts.finished.missing_until(m.total_pieces)
        )

    async def _download_source_sequential(self) -> None:
        """Origin without Range support: stream the whole body once, carving
        pieces as they fill (ref DownloadSource without ConcurrentOption)."""
        m = self.ts.meta
        buf = bytearray()
        idx = 0
        t0 = time.monotonic()
        async for chunk in self.sources.download(self.meta.url, headers=self.headers):
            buf.extend(chunk)
            await self.bucket.acquire(len(chunk))
            while len(buf) >= m.piece_size and idx < m.total_pieces - 1:
                piece, buf = bytes(buf[: m.piece_size]), bytearray(buf[m.piece_size :])
                await self._write_source_piece(idx, piece, t0)
                idx += 1
                t0 = time.monotonic()
        if idx != m.total_pieces - 1 or len(buf) != m.content_length - idx * m.piece_size:
            raise IOError(
                f"source stream ended early: piece {idx}, {len(buf)} buffered"
            )
        await self._write_source_piece(idx, bytes(buf), t0)

    async def _write_source_piece(self, idx: int, data: bytes, t0: float) -> None:
        from dragonfly2_tpu.daemon import metrics

        self.bytes_from_source += len(data)
        if self.ts.has_piece(idx):
            # recovered piece on a resumed task: the close-delimited stream
            # re-carried its bytes (no Range support — unavoidable), but it
            # is already landed and reported; re-landing would re-hash and
            # re-count it, and a re-report would double piece accounting
            return
        await self.ts.write_piece(idx, data)
        metrics.PIECE_DOWNLOAD_TOTAL.inc(source="back_to_source")
        metrics.DOWNLOAD_BYTES.inc(len(data))
        await self._report_piece_success(idx, (time.monotonic() - t0) * 1000)

    async def _download_source_unknown_length(self, info) -> None:
        """Origin without Content-Length: stream whole body, then size pieces."""
        buf = bytearray()
        async for chunk in self.sources.download(self.meta.url, headers=self.headers):
            buf.extend(chunk)
            await self.bucket.acquire(len(chunk))
        data = bytes(buf)
        psize = compute_piece_size(len(data))
        self.ts.set_task_info(
            content_length=len(data),
            piece_size=psize,
            total_pieces=piece_count(len(data), psize),
            digest=self.meta.digest,
        )
        for idx in range(self.ts.meta.total_pieces):
            if self.ts.has_piece(idx):
                continue  # recovered piece: already landed, never re-land
            r = piece_range(idx, psize, len(data))
            await self.ts.write_piece(idx, data[r.start : r.start + r.length])
            self.pieces_fetched += 1
        self.bytes_from_source += len(data)
        await self.scheduler.report_task_metadata(
            self.meta.task_id,
            content_length=len(data),
            piece_size=psize,
            direct_piece=data if len(data) <= 128 else b"",
        )

    # ---- P2P (ref pullPiecesWithP2P + downloadPieceWorker) ----

    async def _download_p2p(self, parents: list[ParentInfo]) -> None:
        self.dispatcher.update_parents(parents)
        session = self._http()
        reschedules = 0
        round_no = 0
        last_update = time.monotonic()

        try:
            while True:
                self._sync_parents(session)
                if self.ts.meta.content_length < 0:
                    # Parents are still back-to-source themselves and haven't
                    # learned the object size; wait for their metadata rather
                    # than burning the reschedule budget.
                    if not self.dispatcher.usable():
                        reschedules += 1
                        if reschedules > self.cfg.reschedule_limit \
                                or not self._reschedule_allowed(reschedules):
                            await self._download_back_to_source()
                            return
                        reg = await self._reschedule()  # dflint: disable=DF025 one budget-bounded reschedule per empty dispatch round, not per-item chatter
                        if reg.back_to_source:
                            await self._download_back_to_source()
                            return
                        self.dispatcher.update_parents(reg.parents)
                    await self._wait_update()
                    continue
                if self.ts.meta.content_length == 0 or self.ts.is_complete():
                    return
                total = self.ts.meta.total_pieces
                missing = list(self.ts.finished.missing_until(total))
                available = [i for i in missing if self.dispatcher.pick(i) is not None]
                if not available:
                    if any(not t.done() for t in self._sync_tasks.values()):
                        # Live parents just have nothing new yet — keep riding
                        # the push channel; spend the reschedule budget only
                        # after a real no-progress window.
                        if await self._wait_update():
                            last_update = time.monotonic()
                            continue
                        if time.monotonic() - last_update < self.cfg.no_progress_reschedule:
                            continue
                    reschedules += 1
                    if reschedules > self.cfg.reschedule_limit \
                            or not self._reschedule_allowed(reschedules):
                        self.log.info(
                            "peer %s: cutover to back-to-source for %d pieces",
                            self.peer_id, len(missing),
                        )
                        await self._download_back_to_source()
                        return
                    reg = await self._reschedule()  # dflint: disable=DF025 one budget-bounded reschedule per no-progress window, not per-item chatter
                    if reg.back_to_source:
                        await self._download_back_to_source()
                        return
                    self.dispatcher.update_parents(reg.parents)
                    last_update = time.monotonic()  # fresh no-progress window
                    await self._wait_update()
                    continue

                queue: asyncio.Queue[int] = asyncio.Queue(
                    maxsize=max(1, len(available))
                )
                for i in available:
                    queue.put_nowait(i)
                round_no += 1
                # the round span parents every piece span its workers open
                # (tasks created inside inherit the contextvar context) plus
                # the round-end report flush — the traced unit ROADMAP #1's
                # "per-round glue" lever is accounted in
                with default_tracer().span(
                    "conductor.dispatch_round",
                    round=round_no, pieces=len(available),
                    workers=min(self.cfg.piece_workers, len(available)),
                ):
                    workers = [
                        asyncio.ensure_future(self._piece_worker(session, queue))
                        for _ in range(min(self.cfg.piece_workers, len(available)))
                    ]
                    await queue.join()
                    for w in workers:
                        w.cancel()
                    await asyncio.gather(*workers, return_exceptions=True)
                    # writes the workers deferred must land before the loop
                    # re-reads the bitset, or still-in-flight pieces would look
                    # missing and be refetched
                    await self._drain_writes()
                    # adaptive write-behind: the first measured round decides
                    # the mode for the rest of the task (no-op once decided)
                    if self._write_behind.measuring:
                        self._write_behind.decide()
                    # dispatch-round-end flush: the scheduler learns this
                    # round's pieces in ONE report_pieces RPC (≤1 flush per
                    # round unless the size/interval triggers fired mid-round)
                    if self._reports is not None:
                        await self._reports.flush()
                last_update = time.monotonic()
        finally:
            await self._drain_writes()
            for t in self._sync_tasks.values():
                t.cancel()
            await asyncio.gather(*self._sync_tasks.values(), return_exceptions=True)
            self._sync_tasks.clear()

    def _reschedule_allowed(self, reschedules: int) -> bool:
        """The first reschedule is normal protocol (free); RETRIES spend
        from the process-wide scheduler retry budget. Denied → the caller
        fails fast to back-to-source instead of joining a reschedule storm
        against an overloaded scheduler."""
        if reschedules <= 1 or self._sched_budget.spend():
            return True
        self.log.info(
            "reschedule retry budget exhausted (%s); failing fast to source",
            self._sched_budget.name,
        )
        return False

    async def _reschedule(self) -> RegisterResult:
        """reschedule with scheduler-restart recovery: a scheduler that lost
        this peer (process restart wiped its resource pool, or GC evicted
        us) answers not_found — re-register instead of failing the task, and
        push back what the fresh scheduler is missing (task metadata + the
        pieces this peer already holds) so it rebuilds its view from
        announces alone. The daemons' existing backoff+breaker path already
        covers the reconnect; this covers the state."""
        try:
            return await self.scheduler.reschedule(self.peer_id)
        except KeyError:
            pass  # in-process client surfaces the raw lookup failure
        except RpcError as e:
            if e.code != "not_found":
                raise
        self.log.info("scheduler lost peer %s: re-registering", self.peer_id)
        reg = await self._register_admitted()
        if getattr(reg, "error", ""):
            raise IOError(
                f"task {self.meta.task_id}: re-registration refused: {reg.error}"
            )
        if self.ts is not None and self.ts.meta.content_length >= 0:
            try:
                # announce_task, not report_pieces: possession is declared
                # metrics-free (a success report would re-count
                # DOWNLOAD_TRAFFIC_BYTES for bytes the old incarnation of
                # this scheduler may already have counted, and feed 0.0 cost
                # samples into the peer's parent-selection feature). The
                # announce adopts the row just re-registered (same peer_id),
                # sets task metadata, and marks the held pieces.
                await self.scheduler.announce_task(
                    self.peer_id, self.meta, self.host,
                    content_length=self.ts.meta.content_length,
                    piece_size=self.ts.meta.piece_size,
                    piece_indices=sorted(self.ts.finished.indices()),
                    digest=self.ts.meta.digest,
                )
            except Exception as e:  # noqa: BLE001 — advisory rebuild; the
                # download itself only needs the registration to stand
                self.log.debug("post-re-register state push failed: %r", e)
        return reg

    async def _wait_update(self) -> bool:
        """Park until any parent sync loop reports progress (piece landed,
        metadata learned, parent died). Returns True if an update arrived,
        False on the fallback-timeout re-check. This replaces the fixed
        polling interval on the hot path: piece-arrival latency is now one
        push round-trip, not up to a poll period."""
        try:
            await asyncio.wait_for(
                self._update_event.wait(), timeout=self.cfg.metadata_poll_interval
            )
            arrived = True
        except asyncio.TimeoutError:
            arrived = False
        self._update_event.clear()
        return arrived

    def _sync_parents(self, session: aiohttp.ClientSession) -> None:
        """Ensure one long-poll sync loop per usable parent (ref
        pieceTaskSyncManager.syncPeers); drop loops for removed parents."""
        current = {s.info.peer_id for s in self.dispatcher.usable()}
        for pid in list(self._sync_tasks):
            t = self._sync_tasks[pid]
            if pid not in current or t.done():
                if pid not in current:
                    t.cancel()
                elif not t.cancelled() and t.exception() is not None:
                    self.log.warning("parent %s sync loop died: %r", pid, t.exception())
                del self._sync_tasks[pid]
        for state in self.dispatcher.usable():
            if state.info.peer_id not in self._sync_tasks:
                self._sync_tasks[state.info.peer_id] = asyncio.ensure_future(
                    self._parent_sync_loop(session, state)
                )

    async def _parent_sync_loop(self, session: aiohttp.ClientSession, state: ParentState) -> None:
        """Long-poll one parent's metadata endpoint: the first request returns
        immediately with current state; subsequent requests park server-side
        until the parent's task state changes past the seen version (ref
        pieceTaskSynchronizer.receive push loop)."""
        version = -1
        errors = 0  # consecutive failures feed the shared backoff ladder
        url = (
            f"{self._scheme}://{_url_host(state.info.ip)}:{state.info.download_port}"
            f"/metadata/{self.meta.task_id}"
        )
        while not state.blocked:
            try:
                if faultline.ACTIVE is not None:
                    await faultline.ACTIVE.fire("parent.metadata")
                # `have` makes piece_digests a delta (digests we already hold
                # are never re-sent — O(pieces) total instead of O(pieces²))
                have = 0
                for k in self._piece_digests:
                    have |= 1 << int(k)
                # park no longer than the remaining task budget allows
                wait = self.cfg.longpoll_wait
                remaining = dl.remaining()
                if remaining is not None:
                    wait = max(0.1, min(wait, remaining))
                async with session.get(
                    url,
                    params={
                        "since": str(version),
                        "wait": str(wait),
                        "have": format(have, "x"),
                    },
                    timeout=aiohttp.ClientTimeout(total=wait + 10),
                ) as resp:
                    if resp.status != 200:
                        state.record(False, 0)
                        self._update_event.set()
                        errors += 1
                        # parent may not know the task yet
                        await self._backoff.sleep(errors - 1)
                        continue
                    data = await resp.json()
                errors = 0
                version = data.get("version", version)
                finished_hex = data.get("finished_hex")
                if finished_hex is not None:
                    state.pieces = set(Bitset(int(finished_hex, 16)).indices())
                else:  # older peers announce an index list
                    state.pieces = set(data.get("finished_pieces", ()))
                parent_done = bool(data.get("done"))
                for k, v in data.get("piece_digests", {}).items():
                    # validate BEFORE storing: keys feed the have-bitset
                    # (1 << int(k)) on every later sync — one non-numeric or
                    # out-of-range key from a bad parent must not poison
                    # metadata sync with every OTHER parent forever
                    if not (isinstance(k, str) and k.isdigit()):
                        continue
                    if k not in self._piece_digests:
                        self._piece_digests[k] = v
                        if not parent_done:
                            # streaming parent: its digests are self-computed
                            # over bytes it hasn't end-to-end verified yet, so
                            # the final full verify must still run here
                            self._digests_from_done_parents = False
                if self.ts.meta.content_length < 0 and data.get("content_length", -1) >= 0:
                    self.ts.set_task_info(
                        content_length=data["content_length"],
                        piece_size=data["piece_size"],
                        total_pieces=data["total_pieces"],
                        digest=data.get("digest", ""),
                    )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — a bad parent (garbage JSON,
                # missing fields, network error) must count against it and back
                # off, never kill the sync loop silently
                state.record(False, 0)
                self._update_event.set()
                self.log.debug("parent %s metadata sync error: %r", state.info.peer_id, e)
                errors += 1
                await self._backoff.sleep(errors - 1)
                continue
            self._update_event.set()

    # ---- striped fetch: per-parent windows + slowest-stripe tail steal ----

    def _steal_active(self) -> bool:
        return (
            self.cfg.tail_steal
            and self.cfg.striped_fetch
            and len(self.dispatcher.usable()) > 1
        )

    def _steal_candidate(self) -> "tuple[_InflightFetch | None, float]":
        """(entry, seconds-until-mature): the most overdue in-flight fetch
        that has an alternative parent, or (None, 0) when nothing in flight
        is stealable at all. A fetch matures for stealing after
        max(steal_min_ms, steal_cost_factor * its parent's cost EWMA)."""
        now = time.monotonic()
        best: _InflightFetch | None = None
        best_delay = float("inf")
        for entry in self._inflight.values():
            if entry.stolen or not entry.parent_id or entry.steal_attempts >= 2:
                continue
            alt = self.dispatcher.pick(
                entry.idx, striped=True, exclude=frozenset((entry.parent_id,))
            )
            if alt is None:
                continue  # nobody else holds this piece: nothing to steal to
            st = self.dispatcher.parents.get(entry.parent_id)
            ewma = st.cost_ewma_ms if st is not None else 0.0
            mature_s = max(
                self.cfg.steal_min_ms, self.cfg.steal_cost_factor * ewma
            ) / 1e3
            delay = (entry.started + mature_s) - now
            if delay < best_delay:
                best, best_delay = entry, delay
        if best is None:
            return None, 0.0
        return best, max(0.0, best_delay)

    async def _steal_piece(self, session, entry: _InflightFetch) -> None:
        """Duplicate-fetch a tail piece from a different parent; first copy
        to LAND wins (the landing path's has_piece guard makes the loser's
        write+accounting a no-op, so DOWNLOAD_TRAFFIC_BYTES never double
        counts). A winning steal cancels the loser's fetch so the round
        doesn't wait out the slow parent anyway."""
        from dragonfly2_tpu.daemon import metrics

        entry.stolen = True
        entry.steal_attempts += 1
        self.steals_attempted += 1
        won = False
        try:
            # won = OUR fetch landed the piece and claimed its exactly-once
            # attribution. has_piece alone is not a win test: the ORIGINAL
            # can land and still be mid-accounting (task not done), and
            # counting that as a steal win would both overstate steal
            # efficacy and cancel the original's in-flight success report.
            won = await self._download_one_piece(
                session, entry.idx, exclude=frozenset((entry.parent_id,)),
                inline_write=True,
            )
        except Exception as e:  # noqa: BLE001 — a failed steal must not kill
            # the worker loop (the original fetch still owns the piece)
            self.log.debug("tail steal of piece %d failed: %r", entry.idx, e)
        current = self._inflight.get(entry.idx)
        if won:
            if current is entry and not entry.task.done():
                # the steal landed while the original is still grinding: cut
                # the loser loose (its cleanup releases its buffer; the
                # worker sees the cancellation as "stolen" and moves on)
                entry.task.cancel()
            self.steals_won += 1
            metrics.PIECE_STEALS_TOTAL.inc(won="true")
        else:
            entry.stolen = False  # original may still need recovery/steals
            metrics.PIECE_STEALS_TOTAL.inc(won="false")

    async def _next_assignment(self, session, queue: asyncio.Queue) -> int:
        """queue.get with tail-steal: an idle worker (empty queue, pieces
        still in flight) re-fetches the slowest mature stripe instead of
        parking. Waits are bounded by the next candidate's maturity and
        always yield to fresh queue work the moment it appears."""
        while True:
            try:
                return queue.get_nowait()
            except asyncio.QueueEmpty:
                pass
            if not self._steal_active() or not self._inflight:
                return await queue.get()
            entry, delay = self._steal_candidate()
            if entry is None:
                return await queue.get()
            if delay <= 0:
                await self._steal_piece(session, entry)
                continue
            try:
                return await asyncio.wait_for(queue.get(), timeout=delay)
            except asyncio.TimeoutError:
                continue  # candidate matured (or the flight set changed)

    async def _run_piece_fetch(self, session, idx: int) -> None:
        """One piece fetch, registered for tail stealing when striping is
        live. The fetch runs as its own task so a winning steal can cancel
        it; a cancellation that was NOT a steal (round teardown) propagates
        to the worker exactly as before."""
        if not self._steal_active():
            await self._download_one_piece(session, idx)
            return
        entry = _InflightFetch(idx=idx, task=None, started=time.monotonic())
        fetch = asyncio.ensure_future(
            self._download_one_piece(session, idx, inflight=entry)
        )
        entry.task = fetch
        self._inflight[idx] = entry
        try:
            await fetch
        except asyncio.CancelledError:
            if not fetch.cancelled():
                # the WORKER is being cancelled (teardown): take the fetch
                # down with us and propagate
                fetch.cancel()
                raise
            # else: a steal won and cancelled the fetch — the piece is
            # landed (or will be refetched next round); not a failure
        finally:
            if self._inflight.get(idx) is entry:
                del self._inflight[idx]

    async def _piece_worker(self, session: aiohttp.ClientSession, queue: asyncio.Queue) -> None:
        while True:
            idx = await self._next_assignment(session, queue)
            try:
                if not self.ts.has_piece(idx):
                    await self._run_piece_fetch(session, idx)
            except Exception as e:
                # _download_one_piece handles the expected fetch/verify
                # failures itself; anything landing HERE (storage write error,
                # report rpc failure, injected storage fault) used to be
                # debug-logged and silently dropped until the 600 s watchdog
                # fired. Re-enqueue bounded; past the bound, report the piece
                # failed so the dispatcher/cutover logic sees it immediately.
                n = self._piece_errors.get(idx, 0) + 1
                self._piece_errors[idx] = n
                if n <= self.cfg.piece_requeue_limit and not self.ts.has_piece(idx) \
                        and self._parent_budget.spend():
                    # the immediate re-enqueue is a RETRY and spends the
                    # parent retry budget; denied → the piece reports failed
                    # below and recovers via dispatch/reschedule/cutover
                    # (another parent or the source) without the extra hammer
                    self.log.debug(
                        "piece %d worker failed (attempt %d), re-enqueueing: %r", idx, n, e
                    )
                    queue.put_nowait(idx)
                else:
                    self.log.warning(
                        "piece %d failed past the re-enqueue budget", idx, exc_info=True
                    )
                    try:
                        await self.scheduler.report_piece_result(  # dflint: disable=DF025 failed pieces report individually BY DESIGN (they drive rescheduling promptly); successes batch via PieceReportBuffer
                            self.peer_id, idx, success=False
                        )
                    except Exception as report_err:  # noqa: BLE001 — the report is
                        # best-effort; the dispatch loop re-sees the piece anyway
                        self.log.debug("piece %d failure report failed: %r", idx, report_err)
            finally:
                queue.task_done()

    async def _download_one_piece(
        self,
        session: aiohttp.ClientSession,
        idx: int,
        *,
        exclude: frozenset = frozenset(),
        inflight: "_InflightFetch | None" = None,
        inline_write: bool = False,
    ) -> bool:
        """Returns True when THIS fetch landed the piece and claimed its
        attribution (False: no parent, failure, or another copy won)."""
        striped = self.cfg.striped_fetch and len(self.dispatcher.usable()) > 1
        state = self.dispatcher.pick(idx, striped=striped, exclude=exclude)
        if state is None:
            return False
        if inflight is not None:
            inflight.parent_id = state.info.peer_id
        m = self.ts.meta
        r = piece_range(idx, m.piece_size, m.content_length)
        path_qs = (
            f"/download/{self.meta.task_id[:3]}/{self.meta.task_id}?peerId={self.peer_id}"
        )
        t0 = time.monotonic()
        # per-op timeout capped by the propagated task budget; the floor
        # matters because aiohttp treats total=0 as "no timeout", which is
        # exactly wrong for an exhausted budget
        piece_timeout = max(0.001, dl.timeout(self.cfg.piece_timeout))
        use_raw = r.length >= self._RAW_FETCH_BYTES
        # per-piece span with the PR 3 pipeline's stage decomposition lifted
        # into attributes (recv/hash-wait, write in the nested write span):
        # this is what lets dftrace say WHERE a slow piece spent its time.
        # Stage clocks are read only when the trace is sampled — an
        # unsampled piece pays the span object and nothing else.
        self.dispatcher.begin(state)  # per-parent window accounting (striping)
        try:
            with default_tracer().span(
                "conductor.piece",
                piece=idx, parent_peer=state.info.peer_id, bytes=r.length,
                path="raw" if use_raw else "http",
            ) as piece_span:
                return await self._fetch_and_land_piece(
                    session, state, idx, r, path_qs, piece_timeout, t0,
                    use_raw, piece_span, inline_write=inline_write,
                )
        finally:
            self.dispatcher.end(state)

    async def _fetch_and_land_piece(
        self, session, state, idx, r, path_qs, piece_timeout, t0,
        use_raw, piece_span, *, inline_write: bool = False,
    ) -> bool:
        pooled = None
        digest = ""
        data = b""
        sampled = piece_span.sampled
        # stage clocks run when the trace wants them OR while the write-
        # behind governor is measuring its first round (two monotonic reads
        # per piece, nothing else)
        clocked = sampled or self._write_behind.measuring
        recv_s = 0.0
        try:
            if faultline.ACTIVE is not None:
                await faultline.ACTIVE.fire("parent.fetch")
            await self.bucket.acquire(r.length)
            if use_raw:
                # big pieces ride the zero-copy pipeline: the body lands
                # straight in a POOLED buffer (sock_recv_into, no per-piece
                # allocation) and is sha256'd AS IT ARRIVES on the pipeline's
                # hash thread — recv and hash run on two cores instead of two
                # serial passes on one (daemon/rawrange.py + pipeline.py).
                # Truncate/corrupt faults fire inside the recv loop — the
                # pipeline's read point — so chaos proofs cover this path.
                pipeline = self._pipeline()
                pooled = await pipeline.pool.acquire(r.length)
                pump = pipeline.hash_pump(pooled.view)
                try:
                    t_recv = time.monotonic() if clocked else 0.0
                    await self._raw_http().get_range_into(
                        state.info.ip, state.info.download_port, path_qs,
                        r.header(), pooled.view, timeout=piece_timeout,
                        on_chunk=pump.feed, fault_point="parent.piece_body",
                    )
                    t_hash = time.monotonic() if clocked else 0.0
                    if clocked:
                        recv_s = t_hash - t_recv
                    if sampled:
                        piece_span.set_attr("recv_ms", round(recv_s * 1e3, 3))
                    digest = await pump.finish()
                    if sampled:
                        # the hash overlaps recv; this is the residual WAIT
                        # for the hash thread after the last byte landed
                        piece_span.set_attr(
                            "hash_wait_ms", round((time.monotonic() - t_hash) * 1e3, 3)
                        )
                except AddressFamilyError:
                    # this host cannot speak the parent's address family over
                    # a raw socket (e.g. IPv6 parent, odd local stack): not
                    # the parent's fault — retry below via aiohttp, whose
                    # resolver handles mixed stacks (ADVICE r05 #1)
                    pump.abort()
                    pooled.release()
                    pooled = None
                    use_raw = False
                    piece_span.set_attr("path", "http")
                    self.log.debug(
                        "parent %s: raw socket family unavailable for %s, "
                        "falling back to aiohttp", state.info.peer_id, state.info.ip,
                    )
                except BaseException:
                    pump.abort()
                    pooled.release()
                    pooled = None
                    raise
            if not use_raw:
                headers = {"Range": r.header()}
                ctx = default_tracer().current_context()
                if ctx is not None:
                    # the aiohttp fallback carries the same traceparent the
                    # raw client stamps, so IPv6/small pieces join the trace
                    headers["traceparent"] = ctx.traceparent()
                t_recv = time.monotonic() if sampled else 0.0
                async with session.get(
                    f"{self._scheme}://{_url_host(state.info.ip)}:{state.info.download_port}{path_qs}",
                    headers=headers,
                    timeout=aiohttp.ClientTimeout(total=piece_timeout),
                ) as resp:
                    if resp.status != 206:
                        raise IOError(f"parent returned HTTP {resp.status}")
                    data = await resp.read()
                if sampled:
                    piece_span.set_attr(
                        "recv_ms", round((time.monotonic() - t_recv) * 1e3, 3)
                    )
                if faultline.ACTIVE is not None:
                    # damage the payload AFTER the fetch so the digest check
                    # (and only it) stands between a corrupt parent and disk
                    data = faultline.ACTIVE.mutate("parent.piece_body", data)
        except (aiohttp.ClientError, asyncio.TimeoutError, IOError) as e:
            piece_span.set_attr("failed", True)
            await self._record_piece_failure(
                state, idx, (time.monotonic() - t0) * 1000, f"failed: {e}"
            )
            return False
        cost = (time.monotonic() - t0) * 1000
        if self.ts.has_piece(idx):
            # another fetch of this piece landed while ours was on the wire
            # (tail steal, or a worker-requeue race): the winner already
            # wrote + accounted it — landing again would double-count
            # DOWNLOAD_TRAFFIC_BYTES and re-hash a finished piece
            if pooled is not None:
                pooled.release()
            return False
        expected = self._piece_digests.get(str(idx), "")
        if not expected:
            self._pieces_unverified += 1
        if use_raw:
            if expected and digest != expected:
                # checked HERE, before any write is (possibly deferred to a
                # writer task): the parent must be charged and the piece
                # retried immediately, not after a write round-trip
                pooled.release()
                await self._record_piece_failure(
                    state, idx, cost,
                    f"corrupt: digest {digest[:12]} != {expected[:12]}", corrupt=True,
                )
                return False
            # the store write runs on a worker thread either way
            # (write_piece_view offloads big writes); deferring additionally
            # lets THIS worker recycle a fresh buffer into recv before the
            # write lands — the governor decides at runtime, see
            # ConductorConfig.defer_piece_writes for the measured trade-off.
            # Steal fetches force INLINE (`inline_write`): the stealer's
            # win test is whether its own chain claimed attribution, and a
            # spawned write would make every deferred-mode steal read as a
            # loss — never cancelling the slow loser and re-stealing the
            # same piece until its cap.
            if self._write_behind.defer and not inline_write:
                self._spawn_piece_write(state, idx, pooled, digest, cost, r.length)
                return False  # outcome unknowable here; only steals need it
            return await self._write_fetched_piece(
                state, idx, pooled, digest, cost, r.length, recv_s=recv_s
            )
        try:
            await self.ts.write_piece(idx, data, expected_digest=expected)
        except (ValueError, digestlib.InvalidDigestError) as e:
            await self._record_piece_failure(state, idx, cost, f"corrupt: {e}", corrupt=True)
            return False
        return await self._account_piece_success(state, idx, cost, len(data))

    async def _record_piece_failure(
        self, state, idx, cost, why: str, *, corrupt: bool = False
    ) -> None:
        """Shared failure accounting for every per-piece rejection path:
        charge the parent, tell the scheduler, log (warning for corruption —
        it implicates the parent's data, debug for routine fetch errors)."""
        state.record(False, cost)
        await self.scheduler.report_piece_result(
            self.peer_id, idx, success=False, cost_ms=cost, parent_id=state.info.peer_id
        )
        log = self.log.warning if corrupt else self.log.debug
        log("piece %d from %s %s", idx, state.info.peer_id, why)

    def _spawn_piece_write(self, state, idx, pooled, digest, cost, length) -> None:
        t = asyncio.ensure_future(
            self._write_fetched_piece(state, idx, pooled, digest, cost, length)
        )
        self._pending_writes.add(t)
        t.add_done_callback(self._pending_writes.discard)

    async def _write_fetched_piece(
        self, state, idx, pooled, digest, cost, length, recv_s: float = 0.0
    ) -> bool:
        """Land a digest-verified pooled buffer in storage (writer side of
        the recv/hash/write overlap; awaited inline or spawned per the
        write-behind decision). A write failure leaves the piece's bitset
        bit unset, so the dispatch loop refetches it — the same bounded
        recovery the worker-level re-enqueue gives small-piece writes.
        Returns True when this write claimed the piece's attribution."""
        try:
            try:
                measuring = self._write_behind.measuring
                t_w = time.monotonic() if measuring else 0.0
                # write stage span (inline: nested under conductor.piece;
                # deferred: a sibling task span in the same round) — the
                # third leg of the recv/hash/write stage decomposition
                with default_tracer().span(
                    "conductor.piece_write", piece=idx, bytes=length
                ):
                    await self.ts.write_piece_view(idx, pooled.view, digest=digest)
                if measuring:
                    # the governor's decision inputs: this piece's recv vs
                    # write stage durations (inline mode, first round)
                    self._write_behind.note(recv_s, time.monotonic() - t_w)
            finally:
                pooled.release()
        except Exception as e:
            n = self._piece_errors.get(idx, 0) + 1
            self._piece_errors[idx] = n
            if n <= self.cfg.piece_requeue_limit and not self.ts.has_piece(idx):
                self.log.debug(
                    "piece %d deferred write failed (attempt %d), will refetch: %r",
                    idx, n, e,
                )
                return False
            self.log.warning("piece %d failed past the write-retry budget", idx,
                             exc_info=True)
            try:
                await self.scheduler.report_piece_result(self.peer_id, idx, success=False)
            except Exception as report_err:  # noqa: BLE001 — best-effort advisory;
                # the dispatch loop re-sees the piece anyway
                self.log.debug("piece %d failure report failed: %r", idx, report_err)
            return False
        return await self._account_piece_success(state, idx, cost, length)

    async def _account_piece_success(self, state, idx, cost, length) -> bool:
        """Returns True when THIS call claimed the piece's (exactly-once)
        attribution — the signal `_steal_piece` uses to decide whether its
        fetch actually won the race or merely observed the other copy's
        landing."""
        # the serving parent earns its success/cost sample either way — it
        # DID deliver valid bytes, even if another copy landed first
        state.record(True, cost)
        if idx in self._accounted:
            # duplicate landing (steal + original racing: storage deduped
            # the write, both callers got success): bytes, metrics, and the
            # scheduler report must count EXACTLY once — the first copy to
            # reach accounting wins attribution.
            return False
        self._accounted.add(idx)
        self.bytes_from_parents += length
        pid = state.info.peer_id
        self.pieces_by_parent[pid] = self.pieces_by_parent.get(pid, 0) + 1
        from dragonfly2_tpu.daemon import metrics

        metrics.PIECE_DOWNLOAD_TOTAL.inc(source="parent")
        metrics.DOWNLOAD_BYTES.inc(length)
        await self._report_piece_success(idx, cost, pid)
        return True

    async def _report_piece_success(self, idx: int, cost_ms: float, parent_id: str = "") -> None:
        """Success-report fast path: enqueue into the batch buffer (sync, no
        RPC on the piece path) or fall back to the unary best-effort report.
        Either way a landed piece is never failed by its report (the
        worker-level catch would re-enqueue a piece that needs no refetch)."""
        self.pieces_fetched += 1
        if self._reports is not None:
            self._reports.add(idx, cost_ms, parent_id)
            return
        try:
            await self.scheduler.report_piece_result(
                self.peer_id, idx, success=True, cost_ms=cost_ms, parent_id=parent_id
            )
        except Exception as e:  # noqa: BLE001 — advisory report; the piece IS on disk
            self.log.debug("piece %d success report failed: %r", idx, e)

    async def _drain_writes(self) -> None:
        """Barrier for deferred store writes (round end / teardown). Write
        tasks handle their own failures, so gather only shields teardown
        from surprise cancellation races."""
        while self._pending_writes:
            await asyncio.gather(*list(self._pending_writes), return_exceptions=True)

    # ---- helpers ----

    def _http(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            # 1 MiB read buffer: the 64 KiB default hits the stream reader's
            # high-water mark hundreds of times per 16 MiB checkpoint piece,
            # each a transport pause/resume round-trip on the event loop
            connector = None
            if self._data_tls is not None:
                # parents' metadata long-polls + small-piece fallbacks ride
                # the same mTLS client identity the raw path handshakes with
                connector = aiohttp.TCPConnector(ssl=self._data_tls.client_ctx)
            self._session = aiohttp.ClientSession(
                read_bufsize=1 << 20, connector=connector
            )
        return self._session

    # pieces at/above this size fetch via the raw recv_into client; below it
    # aiohttp's robustness is worth its copy (the copy is noise there)
    _RAW_FETCH_BYTES = 256 << 10

    def _raw_http(self) -> "RawRangeClient":
        if self._raw_client is None:
            from dragonfly2_tpu.daemon.rawrange import RawRangeClient

            # standalone conductors (tests, direct use) must still speak the
            # data plane's wire posture — a plain client against mTLS
            # parents would charge every parent with handshake garbage
            self._raw_client = RawRangeClient(tls=self._data_tls)
        return self._raw_client

    def _pipeline(self):
        if self._pipeline_obj is None:
            from dragonfly2_tpu.daemon.pipeline import PiecePipeline

            self._pipeline_obj = PiecePipeline()
        return self._pipeline_obj

    async def _safe_report_peer(self, *, success: bool) -> None:
        if self._peer_reported:  # failure paths raise after reporting: once only
            return
        self._peer_reported = True
        if success and self.pieces_by_parent:
            # stripe width for this task: how many distinct parents actually
            # served pieces (1 = classic single-parent assignment)
            from dragonfly2_tpu.daemon import metrics

            metrics.PIECE_STRIPE_PARENTS.observe(float(len(self.pieces_by_parent)))
        elapsed = max(1e-6, time.monotonic() - self._t0)
        bw = (self.bytes_from_parents + self.bytes_from_source) / elapsed
        if self._reports is not None:
            # task-completion flush BEFORE the peer result: report_peer_result
            # snapshots the peer's finished set into telemetry, so buffered
            # pieces must land first. close_with_result rides both in ONE
            # report_batch RPC when the scheduler speaks it; False means the
            # pieces were flushed the plain way and the unary result below
            # still owes.
            try:
                if await self._reports.close_with_result(
                    success=success, bandwidth_bps=bw
                ):
                    return
            except Exception:
                self.log.exception("batched close failed for %s", self.peer_id)
        try:
            await self.scheduler.report_peer_result(
                self.peer_id, success=success, bandwidth_bps=bw
            )
        except Exception:
            self.log.exception("report_peer_result failed for %s", self.peer_id)
