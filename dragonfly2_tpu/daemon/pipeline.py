"""Zero-copy piece-transfer pipeline: pooled buffers + hash-on-receive.

BENCH_r05 put the checkpoint fan-out path at ~2.3 ns per payload byte of
SERIAL single-core CPU: socket recv (~1.1 ns/B) into a freshly allocated
bytearray, a second full pass for sha256 validation (~0.9 ns/B) on a cold
buffer, then the store write (~0.3 ns/B) — plus one heap allocation per
piece. This module removes the allocation and overlaps the stages across
cores, the same discipline that keeps input pipelines feeding accelerators
in TPU training stacks (prefetch + host/device overlap):

  BufferPool     size-bucketed reusable bytearrays: a piece fetch borrows a
                 buffer and the store write returns it, so steady-state
                 transfers allocate nothing. The per-bucket outstanding
                 bound doubles as BACKPRESSURE — when writer threads fall
                 behind, acquire() parks the recv side instead of letting
                 filled buffers pile up unbounded.
  HashPump       incremental sha256 fed from the buffer AS recv_into fills
                 it. Updates run on the pipeline's hash thread (hashlib
                 releases the GIL for buffers > 2 KiB), so recv on the event
                 loop and hashing genuinely run on two cores; by the time
                 the last chunk lands, all but the tail of the piece is
                 already hashed — the second full pass is gone.
  PiecePipeline  the shared facade an engine threads through its conductors
                 (like the shared RawRangeClient): one pool + one hash
                 executor per daemon process.

The third overlap stage — handing a filled buffer to a writer thread and
immediately recycling a fresh buffer into recv — lives in the conductor
(_spawn_piece_write), because it needs the piece-worker loop; storage's
write_piece_view is the no-copy, no-rehash landing half.

dflint expectations for code touching pooled buffers: the pool's sync
methods run on the event-loop thread only (no locks needed — keep it that
way); buffers handed to worker threads (hash updates, store writes) are
READ-ONLY there, and a buffer is released back to the pool only after every
reader of it has finished or been abandoned (an abandoned HashPump may still
read a recycled buffer — harmless, its digest is discarded).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import queue
import threading
from typing import Optional

logger = logging.getLogger(__name__)

# Smallest pooled buffer: requests below this share the 64 KiB bucket (the
# raw fetch path only engages at 256 KiB anyway). Largest: MAX_PIECE_SIZE —
# anything bigger is served unpooled rather than pinning >64 MiB per slot.
MIN_BUCKET = 64 << 10
MAX_BUCKET = 64 << 20

# hash-on-receive geometry: pieces at/below the inline threshold are hashed
# in one pass at finish() (a thread round-trip costs more than the hash);
# larger pieces hand one accumulated chunk at a time to the drain worker —
# 1 MiB amortizes the queue/GIL hand-off without delaying overlap much
INLINE_HASH_BYTES = 256 << 10
HASH_CHUNK_BYTES = 1 << 20


def bucket_size(length: int) -> int:
    """Bucket for a request: next power of two >= max(length, MIN_BUCKET)."""
    size = MIN_BUCKET
    while size < length:
        size <<= 1
    return size


class PooledBuffer:
    """A leased buffer: `view` is a memoryview of EXACTLY the requested
    length (never the full bucket — consumers cannot read a previous piece's
    stale tail past it). release() is idempotent; error paths and finally
    blocks may both call it."""

    __slots__ = ("view", "_pool", "_buf", "_bucket", "_released")

    def __init__(self, pool: "BufferPool", buf: bytearray, bucket: int, length: int):
        self._pool = pool
        self._buf = buf
        self._bucket = bucket
        self.view = memoryview(buf)[:length]
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        # The exported memoryview is NOT .release()d: an aborted pump's
        # queued hash jobs slice this exact view object on the shard thread,
        # and slicing a released view raises — which would kill the shard.
        # The view (and its bytearray) are reclaimed by GC with the lease.
        self._pool._checkin(self._bucket, self._buf)


class BufferPool:
    """Size-bucketed reusable bytearray pool with per-bucket backpressure.

    All methods run on the event-loop thread (single-threaded asyncio — no
    locking); the semaphores are created lazily inside acquire() so they
    bind to the running loop (dflint DF021 discipline).

    Knobs:
      max_idle_per_bucket  buffers RETAINED per bucket when idle (memory cap:
                           idle retention is at most
                           sum(bucket_size * max_idle) over live buckets)
      max_outstanding_per_bucket  leases in flight per bucket before
                           acquire() parks — the pipeline's backpressure:
                           recv stops borrowing when hash/write stages still
                           hold this many buffers
    """

    def __init__(
        self,
        *,
        max_idle_per_bucket: int = 8,
        max_outstanding_per_bucket: int = 32,
    ):
        self._idle: dict[int, list[bytearray]] = {}
        self._sems: dict[int, asyncio.Semaphore] = {}
        self._max_idle = max_idle_per_bucket
        self._max_outstanding = max_outstanding_per_bucket
        self.hits = 0
        self.misses = 0

    async def acquire(self, length: int) -> PooledBuffer:
        if length > MAX_BUCKET:
            # oversized one-off: plain allocation, no pooling, no slot held
            self.misses += 1
            return PooledBuffer(self, bytearray(length), -1, length)
        bucket = bucket_size(length)
        sem = self._sems.get(bucket)
        if sem is None:
            sem = self._sems[bucket] = asyncio.Semaphore(self._max_outstanding)
        await sem.acquire()  # backpressure: parks when the bucket is maxed out
        idle = self._idle.get(bucket)
        if idle:
            self.hits += 1
            return PooledBuffer(self, idle.pop(), bucket, length)
        self.misses += 1
        return PooledBuffer(self, bytearray(bucket), bucket, length)

    def _checkin(self, bucket: int, buf: bytearray) -> None:
        if bucket < 0:
            return  # oversized one-off was never pooled
        idle = self._idle.setdefault(bucket, [])
        if len(idle) < self._max_idle:
            idle.append(buf)
        sem = self._sems.get(bucket)
        if sem is not None:
            sem.release()

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "idle_bytes": sum(b * len(v) for b, v in self._idle.items()),
        }


def _resolve_quietly(fut: asyncio.Future) -> None:
    if not fut.done():
        fut.set_result(None)


class _HashShard:
    """One hasher thread + its FIFO job queue. Pumps are assigned to a shard
    round-robin; the single consumer per shard preserves each pump's update
    order while INTERLEAVING chunks of every assigned pump — no pump waits
    for another to finish before its hashing starts. (A first cut dedicated
    a worker to each pump for its lifetime; with more in-flight pieces than
    workers, late pumps got zero overlap until early ones completed and the
    checkpoint fan-out halved.) Daemon thread: an unclosed pipeline never
    blocks interpreter exit."""

    __slots__ = ("q", "thread", "closed")

    def __init__(self, name: str):
        self.q: queue.SimpleQueue = queue.SimpleQueue()
        self.closed = False
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self) -> None:
        while True:
            job = self.q.get()
            if job is None:
                # Closing: mark closed FIRST, then drain whatever made it
                # into the queue — a pump racing close() either lands its
                # signal before this drain finishes (resolved here) or
                # observes `closed` after its put and fails fast in
                # finish(); without this, a signal enqueued after the
                # sentinel would leave finish() awaiting forever and the
                # piece worker stalling until the 600 s watchdog.
                self.closed = True
                self._drain_after_close()
                return
            if job[0] == 0:  # update: h.update releases the GIL at these sizes
                _, h, view, start, end = job
                try:
                    h.update(view[start:end])
                except Exception as e:  # noqa: BLE001 — an aborted pump's
                    # stale job (e.g. a view over a since-released buffer)
                    # must never kill the shard: every pump assigned here
                    # would then await finish() forever
                    logger.debug("hash shard dropped stale update: %r", e)
            else:  # completion signal for a pump's finish()
                self._signal(job)

    def _drain_after_close(self) -> None:
        while True:
            try:
                job = self.q.get_nowait()
            except queue.Empty:
                return
            if job is not None and job[0] == 1:
                self._signal(job)

    @staticmethod
    def _signal(job) -> None:
        _, loop, fut = job
        try:
            loop.call_soon_threadsafe(_resolve_quietly, fut)
        except RuntimeError:  # loop already closed: nobody awaits
            logger.debug("hash shard signal after loop close")


class HashPump:
    """Incremental sha256 over a buffer being filled in place.

    feed(filled) is called on the event-loop thread as bytes land (`filled`
    = total valid bytes so far); once a full HASH_CHUNK accumulates, its
    range goes onto the pump's shard queue — h.update runs on the shard
    thread with the GIL released, and the hand-off costs ONE queue put, no
    event-loop scheduling. (A first cut chained per-chunk run_in_executor
    calls instead; each chunk then needed two loop-callback slots that
    queued behind the saturated recv loop, and "overlapped" hashing measured
    SLOWER than a serial second pass — 345 vs 575 MB/s.) finish() flushes
    the tail and awaits a completion signal that rides the same FIFO queue;
    abort() is a no-op placeholder — an abandoned pump holds no worker, and
    its queued updates drain harmlessly (the digest is never read).

    Small buffers (<= inline_bytes) skip the thread entirely and hash in one
    pass at finish() — for them the round-trip would cost more than the
    hash.
    """

    __slots__ = ("_view", "_h", "_shard", "_chunk", "_inline", "_fed")

    def __init__(
        self,
        view: memoryview,
        shard: Optional[_HashShard],
        *,
        chunk_bytes: int = HASH_CHUNK_BYTES,
        inline_bytes: int = INLINE_HASH_BYTES,
    ):
        self._view = view
        self._h = hashlib.sha256()
        self._shard = shard
        self._chunk = chunk_bytes
        self._inline = shard is None or len(view) <= inline_bytes
        self._fed = 0  # bytes already handed to the hasher

    def feed(self, filled: int) -> None:
        if self._inline or filled - self._fed < self._chunk:
            return
        if self._shard.closed:
            return  # shutting down: finish() will fail fast, don't pile jobs
        self._shard.q.put((0, self._h, self._view, self._fed, filled))
        self._fed = filled

    async def finish(self) -> str:
        """Flush the unfed tail, wait for the shard to apply it, return hex."""
        if self._inline:
            self._h.update(self._view)
            return self._h.hexdigest()
        if self._fed < len(self._view):
            self._shard.q.put((0, self._h, self._view, self._fed, len(self._view)))
            self._fed = len(self._view)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._shard.q.put((1, loop, fut))  # FIFO: lands after every update
        if self._shard.closed:
            # pipeline closed under us (daemon shutdown racing a fetch): the
            # shard's post-sentinel drain may or may not have seen the
            # signal — fail the fetch NOW either way; a silent await could
            # hang until the task watchdog, and a partial digest must never
            # be returned
            raise RuntimeError("piece pipeline closed while hashing")
        await fut
        return self._h.hexdigest()

    def abort(self) -> None:
        """Abandon the pump (fetch failed). Queued updates may still read a
        buffer that gets recycled — memory-safe, and the digest of an
        aborted pump is never consumed. No worker or queue is pinned."""


class PiecePipeline:
    """Per-daemon shared pipeline state: one buffer pool + one hash executor.

    Passed to conductors the way the shared RawRangeClient is, so pooled
    buffers and hash threads are reused across every concurrent transfer on
    the host instead of per task."""

    def __init__(
        self,
        *,
        pool: BufferPool | None = None,
        hash_threads: int = 2,
        hash_chunk_bytes: int = HASH_CHUNK_BYTES,
        inline_hash_bytes: int = INLINE_HASH_BYTES,
    ):
        self.pool = pool or BufferPool()
        self._hash_threads = hash_threads
        self._hash_chunk = hash_chunk_bytes
        self._inline = inline_hash_bytes
        self._shards: list[_HashShard] = []
        self._next_shard = 0

    def hash_pump(self, view: memoryview) -> HashPump:
        shard = None
        if len(view) > self._inline:
            if not self._shards:
                self._shards = [
                    _HashShard(f"df-hash-{i}") for i in range(self._hash_threads)
                ]
            shard = self._shards[self._next_shard % len(self._shards)]
            self._next_shard += 1
        return HashPump(
            view,
            shard,
            chunk_bytes=self._hash_chunk,
            inline_bytes=self._inline,
        )

    def close(self) -> None:
        for shard in self._shards:
            shard.q.put(None)
        self._shards = []

    def stats(self) -> dict:
        return self.pool.stats()
