"""Piece upload server: serves stored pieces to child peers over HTTP.

Parity with reference client/daemon/upload/upload_manager.go:92-127,214
(HTTP GET /download/{taskID[:3]}/{taskID}?peerId= with Range headers) plus a
piece-metadata endpoint replacing the reference's gRPC GetPieceTasks/
SyncPieceTasks streams (rpcserver.go:151,268): children poll
GET /metadata/{taskID} for the parent's finished-piece bitset + digests.
Rate-limited by the shared token bucket (1 GiB/s default upload cap,
ref client/config/constants.go:47).

TLS (`tls=` server context from security/transport.py): the piece plane
serves mTLS through a RAW asyncio server built on AsyncTlsTransport instead
of aiohttp — asyncio's SSLProtocol write path measured ~350 MB/s regardless
of peer (per-record Python in the encrypt pipeline), a 3x tax the fan-out
cannot pay. The raw server speaks the same HTTP/1.1 contract (206 ranges
with Content-Length framing, the /metadata long-poll, keep-alive) but
streams bodies through `send_file_range`: preadv into ONE reused
record-aligned buffer, encrypt through the BIO, big blocking sendalls — the
whole chain on a worker thread with the GIL released, which is what
replaces `sendfile` until kTLS exists (probed at context build; unavailable
on this kernel/Python — see security.transport.probe_ktls). The plain path
keeps aiohttp + sendfile untouched.
"""

from __future__ import annotations

import asyncio
import logging
import math
import os
import socket as socketlib
import ssl as _ssl
import time
import weakref
from collections import OrderedDict
from urllib.parse import parse_qsl, unquote

from aiohttp import web

from dragonfly2_tpu.daemon.storage import OncePinRelease, StorageManager, TaskStorage
from dragonfly2_tpu.utils.pieces import parse_http_range
from dragonfly2_tpu.utils.ratelimit import TokenBucket

logger = logging.getLogger(__name__)

_MAX_REQUEST_HEAD = 16 << 10
# idle bound armed on the threaded TLS body send (per-sendall): a client
# that stops reading cannot park a worker thread in send(2) forever —
# shutdown-on-close wakes it, this self-unblocks it even without a close
_TLS_SEND_TIMEOUT_S = 30.0

_REASONS = {200: "OK", 206: "Partial Content", 400: "Bad Request",
            404: "Not Found", 416: "Range Not Satisfiable", 500: "Internal Server Error"}


class _HttpError(Exception):
    """Routed request failure on the raw TLS server — becomes a plain-text
    error response, mirroring the aiohttp handlers' web.HTTP* raises."""

    def __init__(self, status: int, text: str):
        super().__init__(text)
        self.status = status
        self.text = text


def _close_span_once(holder: list) -> None:
    """Exit an entered serve span exactly once (prepare's finally, or the
    GC finalizer for responses aiohttp never prepares). The contextvar
    token may belong to a dead task context — a ValueError from reset must
    not mask the export, so the exit is attempted and the export is what
    matters (Span.__exit__ resets first, then exports)."""
    if not holder:
        return
    span = holder.pop()
    try:
        span.__exit__(None, None, None)
    except ValueError:
        # token from another context (finalizer thread): the reset fails
        # but the span must still export — finish it by hand
        span._token = None
        span.__exit__(None, None, None)


class _PinnedFileResponse(web.FileResponse):
    """FileResponse holding a storage pin from construction until its own
    prepare() (which opens the file and sends the ranged body) completes:
    the threaded storage reclaim must not rmtree the task in the window
    between the handler returning and aiohttp opening the file. A GC
    finalizer covers responses aiohttp never prepares (connection lost).
    When the request carried a traceparent, the serve span rides along the
    same way — closed after prepare so it covers the sendfile, not just the
    handler's validation, with the finalizer closing it on the
    never-prepared path so no span (or stale contextvar) leaks."""

    def __init__(self, *args, ts: TaskStorage, span=None, **kwargs):
        super().__init__(*args, **kwargs)
        release = OncePinRelease(ts)
        ts.pin()
        self._df_release = release
        self._df_span_holder = [span] if span is not None else []
        weakref.finalize(self, release)
        if span is not None:
            weakref.finalize(self, _close_span_once, self._df_span_holder)

    async def prepare(self, request):
        try:
            return await super().prepare(request)
        finally:
            self._df_release()
            _close_span_once(self._df_span_holder)


class UploadServer:
    def __init__(
        self,
        storage: StorageManager,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        rate_limit_bps: float = 1 << 30,
        tls=None,
    ):
        self.storage = storage
        self.host = host
        self.port = port
        # server ssl.SSLContext (security.transport.data_server_ssl_context):
        # mTLS piece serving with the reused-buffer streaming body path
        self.tls = tls
        self.bucket = TokenBucket(rate_limit_bps, burst=64 << 20)
        self.bytes_served = 0
        self.pieces_served = 0
        # hot-piece accounting: ranges served more than once recently (the
        # fan-out shape — one seed, N children pulling the same pieces).
        # Repeat serves ride sendfile straight out of page cache; the fd
        # cache below keeps a readahead hint warm per hot task.
        self.pieces_served_hot = 0
        self._recent_serves: OrderedDict[tuple[str, int, int], int] = OrderedDict()
        self._fd_cache: OrderedDict[str, int] = OrderedDict()  # task_id -> O_RDONLY fd
        self._runner: web.AppRunner | None = None
        # raw TLS server state (module docstring): accept loop + live conns
        self._tls_lsock: "socketlib.socket | None" = None
        self._tls_accept: asyncio.Task | None = None
        self._tls_conns: set[asyncio.Task] = set()

    _RECENT_SERVES_MAX = 4096
    _FD_CACHE_MAX = 32

    def _app(self) -> web.Application:
        # no /metrics here: the upload port is the public p2p data path;
        # metrics live on the daemon's dedicated debug port (observability.server)
        app = web.Application()
        app.router.add_get("/download/{prefix}/{task_id}", self._handle_download)
        app.router.add_get("/metadata/{task_id}", self._handle_metadata)
        app.router.add_get("/healthz", self._handle_health)
        return app

    async def start(self) -> None:
        if self.tls is not None:
            await self._start_tls_raw()
            return
        # handler_cancellation: parked long-poll metadata handlers must die
        # with the client connection / server shutdown, not hold cleanup for
        # the full longpoll window.
        self._runner = web.AppRunner(
            self._app(), access_log=None, handler_cancellation=True, shutdown_timeout=1.0
        )
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        # resolve the ephemeral port
        self.port = site._server.sockets[0].getsockname()[1]
        logger.info("upload server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        if self._tls_accept is not None:
            self._tls_accept.cancel()
            await asyncio.gather(self._tls_accept, return_exceptions=True)
            self._tls_accept = None
        if self._tls_lsock is not None:
            self._tls_lsock.close()
            self._tls_lsock = None
        for t in list(self._tls_conns):
            t.cancel()
        if self._tls_conns:
            await asyncio.gather(*list(self._tls_conns), return_exceptions=True)
        self._tls_conns.clear()
        for fd in self._fd_cache.values():
            try:
                os.close(fd)
            except OSError as e:
                logger.debug("fd-cache close failed: %r", e)
        self._fd_cache.clear()

    def _advise_range(self, ts: TaskStorage, start: int, length: int) -> None:
        """Nudge the kernel to keep the served range resident
        (POSIX_FADV_WILLNEED through a cached per-task fd): the first child's
        serve pre-warms page cache for the rest of the fan-out, so repeat
        serves stay on the sendfile/page-cache path with zero userspace
        copies. Best-effort — tmpfs stores and platforms without fadvise just
        skip it."""
        if not hasattr(os, "posix_fadvise"):
            return
        task_id = ts.meta.task_id
        fd = self._fd_cache.get(task_id)
        try:
            if fd is not None and os.fstat(fd).st_ino != os.stat(ts.data_path).st_ino:
                # the task was deleted and re-registered since this fd was
                # cached: advising the orphaned inode would warm nothing
                self._fd_cache.pop(task_id, None)
                os.close(fd)
                fd = None
            if fd is None:
                fd = os.open(ts.data_path, os.O_RDONLY)
                self._fd_cache[task_id] = fd
                if len(self._fd_cache) > self._FD_CACHE_MAX:
                    _, old = self._fd_cache.popitem(last=False)
                    os.close(old)
            else:
                self._fd_cache.move_to_end(task_id)
            os.posix_fadvise(fd, start, length, os.POSIX_FADV_WILLNEED)
        except OSError as e:
            # an unlinked (reclaimed) task or exotic fs: the serve itself is
            # unaffected, only the readahead hint is lost
            logger.debug("fadvise for %s failed: %r", task_id[:12], e)
            stale = self._fd_cache.pop(task_id, None)
            if stale is not None:
                try:
                    os.close(stale)
                except OSError:
                    logger.debug("stale fd close failed for %s", task_id[:12])

    def _prune_fd_cache(self) -> None:
        """Drop cached fds whose tasks were reclaimed (run every 64 serves):
        an open fd pins a deleted task's unlinked inode, so the disk blocks
        storage reclaim thought it freed would stay allocated until LRU
        eviction — on a seed serving few distinct tasks, indefinitely."""
        for tid in list(self._fd_cache):
            if self.storage.get(tid) is None:
                fd = self._fd_cache.pop(tid)
                try:
                    os.close(fd)
                except OSError as e:
                    logger.debug("fd-cache prune close failed: %r", e)

    def _note_serve(self, task_id: str, start: int, length: int) -> bool:
        """Track (task, range) repeat serves; True when this range is hot
        (served before recently). Bounded LRU — eviction only loses hotness
        accounting, never correctness."""
        key = (task_id, start, length)
        seen = self._recent_serves.get(key)
        if seen is None:
            self._recent_serves[key] = 1
            if len(self._recent_serves) > self._RECENT_SERVES_MAX:
                self._recent_serves.popitem(last=False)
            return False
        self._recent_serves.move_to_end(key)
        self._recent_serves[key] = seen + 1
        return True

    async def _handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    MAX_LONGPOLL_S = 25.0

    async def _handle_metadata(self, request: web.Request) -> web.Response:
        """Piece-metadata endpoint with long-poll push semantics (replacing
        the reference's bidi SyncPieceTasks stream,
        peertask_piecetask_synchronizer.go:81-237): `?since=<version>&wait=<s>`
        parks the request until the task state changes past `since`, so a
        child learns of a new piece the moment it lands instead of on a
        polling interval.

        `?have=<hex>` (a bitset of piece indices whose digests the caller
        already knows) makes piece_digests a DELTA: without it, every wake
        re-sends all digests — O(pieces²) metadata bytes per child over a
        download, ~40 MB of redundancy for a 1024-piece checkpoint shard."""
        task_id = request.match_info["task_id"]
        ts = self.storage.get(task_id)
        if ts is None:
            raise web.HTTPNotFound(text=f"task {task_id} unknown")
        since = request.query.get("since")
        if since is not None:
            try:
                wait_s = float(request.query.get("wait", "25"))
                if not math.isfinite(wait_s):
                    raise web.HTTPBadRequest(text="wait must be finite")
                await ts.wait_version(int(since), min(max(0.0, wait_s), self.MAX_LONGPOLL_S))
            except ValueError:
                raise web.HTTPBadRequest(text="since/wait must be numeric")
        try:
            return web.json_response(
                self._metadata_payload(ts, task_id, request.query.get("have"))
            )
        except ValueError:
            raise web.HTTPBadRequest(text="have must be a hex bitset")

    @staticmethod
    def _metadata_payload(ts: TaskStorage, task_id: str, have_hex: str | None) -> dict:
        """The metadata response body (shared by the aiohttp and raw-TLS
        servers). Raises ValueError on a malformed `have` bitset."""
        m = ts.meta
        digests = m.piece_digests
        if have_hex:
            have = int(have_hex, 16)
            digests = {k: v for k, v in digests.items() if not (have >> int(k)) & 1}
        return {
            "task_id": task_id,
            "content_length": m.content_length,
            "piece_size": m.piece_size,
            "total_pieces": m.total_pieces,
            "digest": m.digest,
            # hex bitset: a 1024-piece task announces in 256 chars
            # instead of ~6 KB; the index list stays alongside so
            # pre-upgrade peers in a mixed cluster still see pieces
            "finished_hex": format(ts.finished.to_int(), "x"),
            "finished_pieces": sorted(ts.finished.indices()),
            "piece_digests": digests,
            "done": m.done,
            "version": ts.version,
        }

    async def _handle_download(self, request: web.Request) -> web.StreamResponse:
        task_id = request.match_info["task_id"]
        if request.match_info["prefix"] != task_id[:3]:
            raise web.HTTPBadRequest(text="prefix/task mismatch")
        ts = self.storage.get(task_id)
        if ts is None:
            raise web.HTTPNotFound(text=f"task {task_id} unknown")
        total = ts.meta.content_length
        if total <= 0 or ts.meta.piece_size <= 0:
            raise web.HTTPNotFound(text=f"task {task_id} metadata not ready")
        range_header = request.headers.get("Range")
        if range_header is None:
            raise web.HTTPBadRequest(text="Range header required (piece-granular server)")
        try:
            rng = parse_http_range(range_header, total)
        except ValueError as e:
            raise web.HTTPRequestRangeNotSatisfiable(text=str(e))

        # The requested range must be fully covered by finished pieces. A
        # done task has every piece — skip the per-piece loop (O(pieces) per
        # serve; ~1k has_piece calls per whole-shard range on a large
        # checkpoint), which is pure overhead on the repeat-serve hot path.
        if not ts.meta.done:
            psize = ts.meta.piece_size
            first_piece = rng.start // psize
            last_piece = (rng.start + rng.length - 1) // psize
            for idx in range(first_piece, last_piece + 1):
                if not ts.has_piece(idx):
                    raise web.HTTPNotFound(text=f"piece {idx} not yet available")

        # the child's piece fetch shipped its trace context in the standard
        # traceparent header (rawrange + the conductor's aiohttp fallback):
        # the serve joins that trace as a server-side span covering the
        # validation AND the sendfile (closed in _PinnedFileResponse.prepare)
        from dragonfly2_tpu.observability.tracing import (
            TRACEPARENT_HEADER,
            SpanContext,
            default_tracer,
        )

        # rate-limit BEFORE the span opens: a client disconnect cancelling
        # the acquire must not leak an entered span
        await self.bucket.acquire(rng.length)
        span = None
        remote = SpanContext.from_traceparent(request.headers.get(TRACEPARENT_HEADER))
        if remote is not None:
            span = default_tracer().span(  # dflint: disable=DF027 entered here, exited by _PinnedFileResponse.prepare so the span covers the body send
                "upload.serve_piece", parent=remote,
                task_id=task_id, range_start=rng.start, range_length=rng.length,
            )
            span.__enter__()
        try:
            return self._serve_range(request, ts, task_id, rng, span)
        except BaseException as exc:
            # anything failing before the response takes over span ownership
            # (rate-limit cancel, fs errors) must close it — a leaked span
            # loses the segment AND leaves later requests on this keep-alive
            # connection parented to a ghost
            if span is not None:
                span.__exit__(type(exc), exc, None)
            raise

    def _account_serve(self, ts, task_id, rng, span) -> None:
        """Shared serve accounting for the sendfile and TLS body paths."""
        self.bytes_served += rng.length
        self.pieces_served += 1
        if self.pieces_served % 64 == 0:
            self._prune_fd_cache()
        if self._note_serve(task_id, rng.start, rng.length):
            self.pieces_served_hot += 1
            if span is not None:
                span.set_attr("hot", True)
        else:
            # first serve of this range: pre-warm page cache for the rest of
            # the fan-out (repeat serves then read/send straight from cache)
            self._advise_range(ts, rng.start, rng.length)
        from dragonfly2_tpu.daemon import metrics

        metrics.UPLOAD_BYTES.inc(rng.length)
        ts.last_access = time.time()  # serving keeps the task LRU-hot

    def _serve_range(self, request, ts, task_id, rng, span) -> web.StreamResponse:
        self._account_serve(ts, task_id, rng, span)
        # Zero-copy serving: FileResponse honors the Range header itself and
        # sends via loop.sendfile where the platform supports it, so piece
        # bytes go disk→socket without ever entering Python userspace (the
        # previous read_range path buffered the whole piece then copied it
        # through the response). The pinned subclass keeps the task immune to
        # the threaded reclaim until the file is open and sent; once open,
        # eviction only unlinks the inode and the send is safe.
        return _PinnedFileResponse(
            ts.data_path,
            ts=ts,
            span=span,
            chunk_size=1 << 20,
            headers={"Content-Type": "application/octet-stream"},
        )


    # ---- raw TLS server (module docstring: the mTLS piece plane) ----

    async def _start_tls_raw(self) -> None:
        family = socketlib.AF_INET6 if ":" in self.host else socketlib.AF_INET
        lsock = socketlib.socket(family, socketlib.SOCK_STREAM)
        lsock.setsockopt(socketlib.SOL_SOCKET, socketlib.SO_REUSEADDR, 1)
        lsock.bind((self.host, self.port))
        lsock.listen(128)
        lsock.setblocking(False)
        self._tls_lsock = lsock
        self.port = lsock.getsockname()[1]
        self._tls_accept = asyncio.ensure_future(self._tls_accept_loop())
        logger.info("upload server on %s:%d (mTLS, raw)", self.host, self.port)

    async def _tls_accept_loop(self) -> None:
        from dragonfly2_tpu.resilience.backoff import BackoffPolicy

        loop = asyncio.get_running_loop()
        # transient-accept pacing: fd pressure clears in ms, so start small
        backoff = BackoffPolicy(base=0.05, multiplier=2.0, max_delay=1.0, jitter=0.3)
        accept_failures = 0
        while True:
            try:
                conn, _addr = await loop.sock_accept(self._tls_lsock)
                accept_failures = 0
            except asyncio.CancelledError:
                return
            except OSError as e:
                if self._tls_lsock.fileno() < 0:
                    return  # listener closed under us (stop())
                # transient accept failure (ECONNABORTED, EMFILE/ENFILE
                # under fd pressure): the listener is still live and bound —
                # returning here would silently stop accepting piece
                # connections forever while clients hang on the backlog
                logger.warning("TLS piece-server accept failed, retrying: %r", e)
                await backoff.sleep(accept_failures)
                accept_failures += 1
                continue
            conn.setblocking(False)
            conn.setsockopt(socketlib.IPPROTO_TCP, socketlib.TCP_NODELAY, 1)
            # deeper kernel pipeline: encrypt-ahead depth for the send path
            conn.setsockopt(socketlib.SOL_SOCKET, socketlib.SO_SNDBUF, 4 << 20)
            t = asyncio.ensure_future(self._tls_conn_loop(conn))
            self._tls_conns.add(t)
            t.add_done_callback(self._tls_conns.discard)

    async def _tls_conn_loop(self, conn: "socketlib.socket") -> None:
        from dragonfly2_tpu.security.transport import AsyncTlsTransport

        try:
            tr = await AsyncTlsTransport.accept(conn, self.tls)
        except (_ssl.SSLError, ConnectionError, OSError, asyncio.TimeoutError) as e:
            # plaintext speaker, bad client cert, or a half-open probe: the
            # mTLS posture refuses it at the handshake, quietly
            logger.debug("TLS piece-server handshake refused: %r", e)
            conn.close()
            return
        try:
            while True:
                try:
                    req = await self._tls_read_request(tr)
                except _HttpError as e:
                    # malformed head (oversized, non-GET, bad request line):
                    # tell the client why, then drop the connection — the
                    # request framing may be desynced past recovery
                    await self._tls_send_simple(
                        tr, e.status, e.text.encode(), connection="close"
                    )
                    # drain what the client already sent (a POST body, the
                    # rest of an oversized head) before closing: close()
                    # with unread bytes queued answers with RST, which can
                    # destroy the in-flight 400 before the client reads it.
                    # Bounded in bytes, per-read idle, AND total wall-clock
                    # — a client that streams (or trickles) forever gets cut
                    # off, response delivered or not
                    try:
                        loop = asyncio.get_running_loop()
                        deadline = loop.time() + 2.0
                        drained = 0
                        while drained < (1 << 20) and loop.time() < deadline:
                            chunk = await asyncio.wait_for(tr.recv(8192), 0.5)
                            if not chunk:
                                break  # client read the 400 and closed
                            drained += len(chunk)
                    except (asyncio.TimeoutError, ConnectionError, OSError):
                        pass
                    return
                if req is None:
                    return  # clean keep-alive close
                path, query, headers = req
                try:
                    await self._tls_dispatch(tr, path, query, headers)
                except _HttpError as e:
                    await self._tls_send_simple(tr, e.status, e.text.encode())
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
            logger.debug("TLS piece-server connection dropped: %r", e)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — one bad request/connection must
            # never take down the serve plane; the child retries elsewhere
            logger.exception("TLS piece-server connection failed")
        finally:
            tr.close()

    async def _tls_read_request(self, tr) -> "tuple[str, dict, dict] | None":
        """One request head: (path, query-dict, headers-dict), or None on a
        clean close between requests. GET-only (the piece wire contract)."""
        head = bytearray()
        while True:
            end = head.find(b"\r\n\r\n")
            if end >= 0:
                break
            if len(head) > _MAX_REQUEST_HEAD:
                raise _HttpError(400, "request head too large")
            chunk = await tr.recv(8192)
            if not chunk:
                if head:
                    raise ConnectionError("client closed mid-request")
                return None
            head += chunk
        lines = head[:end].decode("latin-1").split("\r\n")
        parts = lines[0].split(" ")
        if len(parts) != 3 or parts[0] != "GET":
            raise _HttpError(400, f"unsupported request line {lines[0]!r}")
        target = parts[1]
        path, _, qs = target.partition("?")
        query = dict(parse_qsl(qs, keep_blank_values=True))
        headers: dict[str, str] = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(":")
            headers[k.strip().lower()] = v.strip()
        return unquote(path), query, headers

    async def _tls_send_simple(
        self, tr, status: int, body: bytes, content_type: str = "text/plain",
        connection: str = "keep-alive",
    ) -> None:
        head = (
            f"HTTP/1.1 {status} {_REASONS.get(status, 'OK')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {connection}\r\n"
            "\r\n"
        ).encode("ascii")
        await tr.sendall(head + body)

    async def _tls_dispatch(self, tr, path: str, query: dict, headers: dict) -> None:
        import json

        if path == "/healthz":
            await self._tls_send_simple(
                tr, 200, b'{"ok": true}', content_type="application/json"
            )
            return
        if path.startswith("/metadata/"):
            task_id = path[len("/metadata/"):]
            ts = self.storage.get(task_id)
            if ts is None:
                raise _HttpError(404, f"task {task_id} unknown")
            since = query.get("since")
            if since is not None:
                try:
                    wait_s = float(query.get("wait", "25"))
                    if not math.isfinite(wait_s):
                        raise _HttpError(400, "wait must be finite")
                    await ts.wait_version(
                        int(since), min(max(0.0, wait_s), self.MAX_LONGPOLL_S)
                    )
                except ValueError:
                    raise _HttpError(400, "since/wait must be numeric")
            try:
                payload = self._metadata_payload(ts, task_id, query.get("have"))
            except ValueError:
                raise _HttpError(400, "have must be a hex bitset")
            await self._tls_send_simple(
                tr, 200, json.dumps(payload).encode(), content_type="application/json"
            )
            return
        if path.startswith("/download/"):
            rest = path[len("/download/"):]
            prefix, _, task_id = rest.partition("/")
            await self._tls_serve_download(tr, prefix, task_id, headers)
            return
        raise _HttpError(404, f"no route for {path}")

    async def _tls_serve_download(self, tr, prefix: str, task_id: str, headers: dict) -> None:
        """The mTLS twin of _handle_download + _serve_range: identical
        validation and accounting, with the body streamed by the transport's
        worker-thread encrypt+send path under the task pin."""
        if prefix != task_id[:3]:
            raise _HttpError(400, "prefix/task mismatch")
        ts = self.storage.get(task_id)
        if ts is None:
            raise _HttpError(404, f"task {task_id} unknown")
        total = ts.meta.content_length
        if total <= 0 or ts.meta.piece_size <= 0:
            raise _HttpError(404, f"task {task_id} metadata not ready")
        range_header = headers.get("range")
        if range_header is None:
            raise _HttpError(400, "Range header required (piece-granular server)")
        try:
            rng = parse_http_range(range_header, total)
        except ValueError as e:
            raise _HttpError(416, str(e))
        if not ts.meta.done:
            psize = ts.meta.piece_size
            for idx in range(rng.start // psize, (rng.start + rng.length - 1) // psize + 1):
                if not ts.has_piece(idx):
                    raise _HttpError(404, f"piece {idx} not yet available")

        from dragonfly2_tpu.observability.tracing import (
            TRACEPARENT_HEADER,
            SpanContext,
            default_tracer,
        )

        # rate-limit BEFORE the span opens (the aiohttp path's discipline):
        # a disconnect cancelling the acquire must not leak an entered span
        await self.bucket.acquire(rng.length)
        span = None
        remote = SpanContext.from_traceparent(headers.get(TRACEPARENT_HEADER))
        if remote is not None:
            span = default_tracer().span(  # dflint: disable=DF027 entered here, exited in this handler's finally so the span covers the threaded body send
                "upload.serve_piece", parent=remote,
                task_id=task_id, range_start=rng.start, range_length=rng.length,
            )
            span.__enter__()
        ts.pin()  # the send IS the handler here: pinned end to end
        try:
            self._account_serve(ts, task_id, rng, span)
            head = (
                "HTTP/1.1 206 Partial Content\r\n"
                "Content-Type: application/octet-stream\r\n"
                f"Content-Length: {rng.length}\r\n"
                f"Content-Range: bytes {rng.start}-{rng.start + rng.length - 1}/{total}\r\n"
                "Connection: keep-alive\r\n"
                "\r\n"
            ).encode("ascii")
            await tr.send_file_range(
                ts.data_path, rng.start, rng.length, head=head,
                timeout=_TLS_SEND_TIMEOUT_S,
            )
        finally:
            ts.unpin()
            if span is not None:
                span.__exit__(None, None, None)
